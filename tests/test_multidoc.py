"""Differential suite for the round-14 tenant packing.

The tentpole contract: doc-id is a first-class segment column in the
staged packed layout, so ONE converge dispatch over a packed tenant
batch yields per-doc outputs BYTE-identical to each doc converged
alone — pinned here for {2, 3, 17} docs with mixed LWW/YATA ops,
deletes, right origins, shared raw client ids, duplicate redelivery,
and empty docs, on both the single-chip route and the forced-2-device
sharded route (whose partition places whole docs per chip). On top:
the MultiDocServer tick loop (fairness, bin-packing, vectorized vs
stock unpack equality), the tenant admission ladder (a flooding
tenant is shed ALONE — the chaos leg), and the multi-doc divergence
sentinel (a fork in one doc is attributed to that doc only).
"""

import numpy as np
import pytest

from crdt_tpu.codec import v1
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.guard.tenant import TenantBudget, fair_order, pack_batches
from crdt_tpu.models import replay as rp
from crdt_tpu.models.multidoc import (
    MultiDocServer,
    _concat_cols,
    cache_digest,
)
from crdt_tpu.obs import Tracer, get_tracer, set_tracer
from crdt_tpu.obs.sentinel import MultiDocSentinel
from crdt_tpu.ops import packed
from crdt_tpu.ops import shard
from crdt_tpu.ops.device import NULLI


@pytest.fixture(autouse=True)
def _no_ambient_sharding(monkeypatch):
    monkeypatch.delenv(shard.SHARD_ENV, raising=False)
    monkeypatch.delenv(shard.MIN_ROWS_ENV, raising=False)


def doc_blobs(seed, *, n_clients=3, K=24, rights=False, deletes=True,
              shared_clients=True, maps=2, lists=2):
    """One doc's update blobs: per-client chained list appends over
    ``lists`` roots + LWW map sets over ``maps`` roots, optional
    mid-insert right origins and tombstones. ``shared_clients`` keeps
    the same raw client ids across docs — the hard case the
    doc-composite interning must keep apart."""
    rng = np.random.default_rng(seed)
    base = 10 if shared_clients else 1000 * (seed + 1)
    blobs = []
    for c in range(n_clients):
        client = base + c
        recs = []
        chain = []
        for k in range(K):
            r = k % 3
            if r == 0:
                recs.append(ItemRecord(
                    client=client, clock=k,
                    parent_root=f"m{k % maps}",
                    key=f"k{int(rng.integers(0, 6))}",
                    content=int(seed * 1000 + c * 100 + k),
                ))
            elif rights and chain and k % 7 == 5:
                j = int(rng.integers(0, len(chain)))
                recs.append(ItemRecord(
                    client=client, clock=k,
                    parent_root=f"l{k % lists}",
                    origin=chain[j - 1] if j > 0 else None,
                    right=chain[j], content=k,
                ))
                chain.insert(j, (client, k))
            else:
                recs.append(ItemRecord(
                    client=client, clock=k,
                    parent_root=f"l{k % lists}",
                    origin=chain[-1] if chain else None,
                    content=int(seed * 1000 + c * 100 + k),
                ))
                chain.append((client, k))
        ds = DeleteSet()
        if deletes:
            ds.add(client, 1)
            ds.add(client, K - 1)
        blobs.append(v1.encode_update(recs, ds))
    return blobs


def oracle_cache(blobs):
    return rp.replay_trace(blobs).cache if blobs else {}


def split_result(res, row_off, i):
    """Reference per-doc slice of a combined result (row-range based,
    independent of the server's vectorized partition)."""
    lo, hi = int(row_off[i]), int(row_off[i + 1])
    win = np.asarray(res.win_rows)
    srow = np.asarray(res.stream_row)
    sseg = np.asarray(res.stream_seg)
    wm = (win >= lo) & (win < hi)
    sm = (srow >= lo) & (srow < hi)
    return packed.PackedResult(
        win_rows=np.where(wm, win - lo, NULLI),
        stream_seg=sseg[sm],
        stream_row=(srow - lo)[sm],
        hard_rows=tuple(
            int(r) - lo for r in res.hard_rows if lo <= int(r) < hi
        ),
    )


def converge_combined(doc_sets, *, sharded=None):
    """Stage + converge a list of per-doc blob sets as one multi-doc
    dispatch; returns (per-doc caches, result, plan staging ok)."""
    decs = [rp.decode(bs) for bs in doc_sets]
    staged = [rp.stage(d) for d in decs]
    live = [i for i, d in enumerate(decs) if len(d["client"])]
    comb, row_off = _concat_cols([staged[i][0] for i in live])
    if sharded is not None:
        splan = shard.stage(comb, n_shards=sharded)
        assert splan is not None, "sharded multi-doc staging refused"
        res = shard.converge(splan)
    else:
        plan = packed.stage(comb)
        assert plan is not None, "multi-doc staging refused"
        res = packed.converge(plan)
    caches = {}
    for pos, i in enumerate(live):
        dec, (cols, ds) = decs[i], staged[i]
        sub = split_result(res, row_off, pos)
        w, v, o = rp.gather(dec, ds, ("packed", sub))
        caches[i] = rp.materialize(dec, ds, w, v, o)
    for i in range(len(doc_sets)):
        caches.setdefault(i, {})
    return caches


@pytest.mark.parametrize("n_docs", [2, 3, 17])
def test_packed_multidoc_identical_to_per_doc_oracle(n_docs):
    """{2, 3, 17} docs with mixed LWW/YATA ops, deletes, shared raw
    client ids, one rights-bearing doc and one empty doc: the packed
    multi-doc dispatch reproduces every per-doc oracle cache."""
    doc_sets = []
    for i in range(n_docs):
        if i == 1:
            doc_sets.append([])  # empty doc rides the batch
        elif i == 2:
            doc_sets.append(doc_blobs(i, rights=True))
        else:
            doc_sets.append(doc_blobs(i, K=16 + 5 * (i % 3)))
    caches = converge_combined(doc_sets)
    for i, bs in enumerate(doc_sets):
        assert caches[i] == oracle_cache(bs), f"doc {i} diverged"


@pytest.mark.parametrize("n_docs", [2, 3, 17])
def test_sharded_multidoc_identical_to_per_doc_oracle(n_docs):
    """The same batches through the forced-2-device sharded route
    (doc-first partition): byte-identical per-doc caches."""
    doc_sets = []
    for i in range(n_docs):
        if i == 1 and n_docs > 2:
            doc_sets.append([])
        else:
            doc_sets.append(doc_blobs(
                i, K=14 + 3 * (i % 4), rights=(i == 2)
            ))
    caches = converge_combined(doc_sets, sharded=2)
    for i, bs in enumerate(doc_sets):
        assert caches[i] == oracle_cache(bs), f"doc {i} diverged"


def test_multidoc_redelivery_and_shared_ids():
    """Duplicate blobs within one doc dedup (first wins, like the
    single-doc path) while the SAME (client, clock) ids in another
    doc stay separate rows — the doc-composite id space at work."""
    a = doc_blobs(0, K=12)
    b = doc_blobs(0, K=12)  # identical ids + content in another doc
    caches = converge_combined([a + a, b])
    assert caches[0] == oracle_cache(a)
    assert caches[1] == oracle_cache(b)


def test_doc_first_shard_partition():
    """With >1 distinct doc the sharded partition keeps whole docs
    per shard (segments of one doc never split across chips)."""
    doc_sets = [doc_blobs(i, K=18) for i in range(5)]
    decs = [rp.decode(bs) for bs in doc_sets]
    staged = [rp.stage(d) for d in decs]
    comb, row_off = _concat_cols([c for c, _ in staged])
    parts, pb_tag = shard._partition(comb, 2)
    assert parts is not None and len(parts) == 2
    assert pb_tag is None  # multi-doc unions never pre-cut
    doc_col = comb["doc"]
    seen = {}
    for k, rows in enumerate(parts):
        for d in np.unique(doc_col[rows]).tolist():
            assert seen.setdefault(d, k) == k, (
                f"doc {d} split across shards"
            )
    assert len(seen) == 5


def test_server_tick_matches_oracle_and_is_fair():
    """The tick loop end to end: bin-packed batches, vectorized +
    stock unpack, per-doc caches identical to replay_trace, fairness
    ordering serving least-recently-served docs first."""
    docs = {f"d{i:02d}": doc_blobs(i, K=15 + (i % 5),
                                   rights=(i == 3))
            for i in range(12)}
    docs["empty"] = []
    srv = MultiDocServer(max_rows_per_dispatch=256)
    for d, bs in docs.items():
        srv.submit_many(d, bs)
    rep = srv.tick()
    assert rep.docs == 12  # the empty doc has nothing pending
    assert rep.dispatches < 12, "no packing happened"
    for d, bs in docs.items():
        if bs:
            assert srv.cache(d) == oracle_cache(bs), d
            assert srv.latency_s(d) is not None
    assert srv.cache("empty") == {}
    # fairness: docs served this tick are deprioritized next tick
    srv.submit_many("d00", doc_blobs(0, K=6))
    order = fair_order(["d00", "zz_new"], {
        "d00": srv._docs["d00"].served_tick
    })
    assert order == ["zz_new", "d00"]


def test_server_incremental_resubmit_reconverges():
    """New deltas for an already-converged doc re-converge its full
    history; untouched docs keep their caches."""
    a1 = doc_blobs(1, K=10)
    b = doc_blobs(2, K=10)
    srv = MultiDocServer()
    srv.submit_many("a", a1)
    srv.submit_many("b", b)
    srv.tick()
    extra = [v1.encode_update([ItemRecord(
        client=99, clock=0, parent_root="m0", key="kx", content="v",
    )], DeleteSet())]
    srv.submit_many("a", extra)
    rep = srv.tick()
    assert rep.docs == 1
    assert srv.cache("a") == oracle_cache(a1 + extra)
    assert srv.cache("b") == oracle_cache(b)


def test_flooding_tenant_sheds_alone():
    """The chaos leg: one tenant floods past its admission budget in
    a shared tick; it is shed (bounded, oldest-first) while every
    other tenant's converged bytes are IDENTICAL to an unflooded
    run."""
    tracer = set_tracer(Tracer(enabled=True))
    try:
        normal = {f"n{i}": doc_blobs(i, K=12) for i in range(6)}
        flood = [doc_blobs(50 + j, n_clients=1, K=30,
                           shared_clients=False)[0]
                 for j in range(12)]

        def run(with_flood):
            srv = MultiDocServer(
                max_rows_per_dispatch=512,
                tenant_max_pending_bytes=1200,
                tenant_max_pending_updates=3,
            )
            for d, bs in normal.items():
                srv.submit_many(d, bs)
            if with_flood:
                for blob in flood:
                    srv.submit("flooder", blob)
            srv.tick()
            return srv

        clean = run(False)
        flooded = run(True)
        assert flooded.shed_count > 0
        assert flooded.shed_bytes > 0
        counters = get_tracer().counters()
        assert counters.get("tenant.shed", 0) >= flooded.shed_count
        # the flooder degraded ALONE: neighbors byte-identical
        for d in normal:
            assert flooded.cache(d) == clean.cache(d), d
            assert flooded.digest(d) == clean.digest(d), d
        # the flooder's own queue was bounded keep-the-newest: its
        # converged state is the ADMITTED suffix
        kept = flood[-3:]
        assert flooded.cache("flooder") == oracle_cache(kept)
    finally:
        set_tracer(Tracer(enabled=False))


def test_multidoc_sentinel_attributes_fork_to_one_doc():
    """Per-doc digest beacons: a fork in one doc raises exactly one
    event naming THAT doc; equal docs agree; op-count mismatches are
    lag, never a fork."""
    tracer = set_tracer(Tracer(enabled=True))
    try:
        shared = {f"d{i}": doc_blobs(i, K=12) for i in range(4)}
        a = MultiDocServer()
        b = MultiDocServer()
        for d, bs in shared.items():
            a.submit_many(d, bs)
            b.submit_many(d, bs)
        # the fork: same op COUNT in doc d2 on b, different content
        forked = doc_blobs(2, K=12)
        forked[0] = v1.encode_update([ItemRecord(
            client=10, clock=k, parent_root="m0", key="k0",
            content=f"forked{k}",
        ) for k in range(12)], DeleteSet())
        b._docs.pop("d2")
        b.submit_many("d2", forked)
        # and lag: one doc with extra (fresh-client) ops on b only,
        # so the op counts genuinely differ
        b.submit_many("d3", [v1.encode_update([ItemRecord(
            client=777, clock=k, parent_root="m0", key="kq",
            content=k,
        ) for k in range(4)], DeleteSet())])
        a.tick()
        b.tick()
        assert a.doc_digests()["d2"]["ops"] == \
            b.doc_digests()["d2"]["ops"]
        sen = MultiDocSentinel(a, topic="t", replica="a")
        peer = MultiDocSentinel(b, topic="t", replica="b")
        events = sen.check("b", peer.beacon_payload())
        assert len(events) == 1
        assert events[0]["doc"] == "d2"
        assert events[0]["kind"] == "divergence"
        counters = get_tracer().counters()
        assert counters.get("sentinel.doc_divergence", 0) == 1
        assert counters.get("sentinel.doc_lag", 0) == 1  # d3
        assert counters.get("sentinel.agree", 0) >= 2  # d0, d1
        # a permanent fork raises once, later beacons only count
        assert sen.check("b", peer.beacon_payload()) == []
        assert get_tracer().counters().get(
            "sentinel.doc_divergence") == 2
    finally:
        set_tracer(Tracer(enabled=False))


def test_tenant_budget_units():
    """TenantBudget.trim: keep-the-newest under both limits;
    pack_batches: fairness-ordered greedy fill, oversized docs get
    their own batch."""
    from collections import deque

    q = deque([b"a" * 100, b"b" * 100, b"c" * 100])
    shed = TenantBudget(max_bytes=250, max_updates=10).trim(q)
    assert shed == [b"a" * 100]
    assert len(q) == 2
    q2 = deque([b"x", b"y", b"z"])
    shed = TenantBudget(max_bytes=1 << 20, max_updates=1).trim(q2)
    assert shed == [b"x", b"y"]
    # a single over-budget update is always kept whole
    q3 = deque([b"huge" * 100])
    assert TenantBudget(max_bytes=10, max_updates=1).trim(q3) == []
    assert len(q3) == 1

    batches = pack_batches(
        [("a", 40), ("b", 40), ("c", 50), ("d", 200)], 100
    )
    assert batches == [["a", "b"], ["c"], ["d"]]
    assert pack_batches([("big", 500)], 100) == [["big"]]


def test_cache_digest_canonical():
    assert cache_digest({"a": [1, 2], "b": {"x": 1}}) == \
        cache_digest({"b": {"x": 1}, "a": [1, 2]})
    assert cache_digest({"a": [1, 2]}) != cache_digest({"a": [2, 1]})


# ------------------------------------------------------- round 15 --
# Delta ticks: per-doc resident incremental engines inside the
# multi-tenant server. Contract: a dirty doc whose delta is
# SV-admissible converges at delta cost through its resident engine,
# BYTE-identical (canonical digest and cache) to the cold full-replay
# oracle; anything else falls back per doc to the round-14 cold path.


class DocStream:
    """Incremental doc generator whose deltas continue each client's
    clock contiguously — the SV-admissible steady-state shape. Keeps
    the YATA chain state so list ops anchor on real resident ids."""

    def __init__(self, seed, n_clients=2):
        self.seed = seed
        self.clients = [10 + c for c in range(n_clients)]
        self.clock = {c: 0 for c in self.clients}
        self.chain: list = []

    def delta(self, k_ops, *, deletes=False, mid_insert=False):
        recs = []
        for i in range(k_ops):
            c = self.clients[i % len(self.clients)]
            k = self.clock[c]
            self.clock[c] = k + 1
            if i % 3 == 0:
                recs.append(ItemRecord(
                    client=c, clock=k, parent_root="m",
                    key=f"k{(self.seed + i) % 5}",
                    content=self.seed * 1000 + k,
                ))
            elif mid_insert and len(self.chain) > 2 and i % 3 == 2:
                j = len(self.chain) // 2
                recs.append(ItemRecord(
                    client=c, clock=k, parent_root="l",
                    origin=self.chain[j - 1], right=self.chain[j],
                    content=self.seed * 1000 + k,
                ))
                self.chain.insert(j, (c, k))
            else:
                recs.append(ItemRecord(
                    client=c, clock=k, parent_root="l",
                    origin=self.chain[-1] if self.chain else None,
                    content=self.seed * 1000 + k,
                ))
                self.chain.append((c, k))
        ds = DeleteSet()
        if deletes and self.chain:
            dc, dk = self.chain[0]
            ds.add(dc, dk, 1)
        return v1.encode_update(recs, ds)


def test_delta_ticks_match_cold_oracle_every_tick():
    """The tentpole differential: N ticks of small contiguous deltas
    on resident docs — every tick's caches and canonical digests
    byte-identical to the full-replay server AND the replay_trace
    oracle, with the route evidence pinned (tick 0 cold, tick 1
    promotions, tick 2+ pure delta serves)."""
    streams = {f"d{i}": DocStream(i, n_clients=1 + i % 3)
               for i in range(5)}
    delta_srv = MultiDocServer()  # delta ticks on by default
    cold_srv = MultiDocServer(delta_ticks=False)
    history = {d: [] for d in streams}
    reports = []
    for t in range(4):
        for d, s in streams.items():
            blob = s.delta(12 if t == 0 else 3,
                           deletes=(t == 2), mid_insert=(t == 3))
            history[d].append(blob)
            delta_srv.submit(d, blob)
            cold_srv.submit(d, blob)
        reports.append((delta_srv.tick(), cold_srv.tick()))
        for d in streams:
            assert delta_srv.cache(d) == oracle_cache(history[d]), \
                (t, d)
            assert delta_srv.digest(d) == cold_srv.digest(d), (t, d)
            assert (delta_srv._docs[d].n_ops
                    == cold_srv._docs[d].n_ops), (t, d)
    assert reports[0][0].delta_docs == 0
    assert reports[1][0].promotions == 5
    for rep_d, rep_c in reports[2:]:
        assert rep_d.delta_docs == 5
        assert rep_d.delta_rows == 15  # the delta IS the staging cost
        assert rep_d.promotions == 0
        assert rep_c.delta_docs == 0   # the baseline stays cold


def test_delta_tick_redelivery_idempotent_across_ticks():
    """The same delta submitted in tick t and t+1 leaves resident
    state, cache, and canonical digest byte-identical to single
    delivery — redelivery rides the (still admissible) delta route,
    dedups inside the engine, and never falls back."""
    import copy

    s = DocStream(3)
    srv = MultiDocServer()
    b0 = s.delta(10)
    srv.submit("a", b0)
    srv.tick()                      # cold (first sight)
    b1 = s.delta(3)
    srv.submit("a", b1)
    rep1 = srv.tick()               # promotion
    assert rep1.promotions == 1
    b2 = s.delta(3)
    srv.submit("a", b2)
    rep2 = srv.tick()               # the delta route proper
    assert rep2.delta_docs == 1 and rep2.delta_rows == 3
    st = srv._docs["a"]
    digest0 = srv.digest("a")
    cache0 = copy.deepcopy(srv.cache("a"))
    n0 = st.resident.cols.n
    sv0 = dict(st.resident._next_clock)
    srv.submit("a", b2)             # redelivered in the NEXT tick
    rep3 = srv.tick()
    assert rep3.delta_docs == 1     # still the delta route
    assert srv.delta_fallback_count == 0
    assert srv.digest("a") == digest0
    assert srv.cache("a") == cache0
    assert st.resident.cols.n == n0
    assert dict(st.resident._next_clock) == sv0
    assert srv.cache("a") == oracle_cache([b0, b1, b2])


def test_offset_clock_delta_falls_back_to_cold():
    """A clock gap is inadmissible to the incremental route (the
    engine would stash what the cold oracle admits): the doc falls
    back per-doc to the cold replay — bytes identical to the oracle
    — and a history the engine cannot settle pins the doc cold."""
    s = DocStream(5, n_clients=1)
    srv = MultiDocServer()
    blobs = [s.delta(8)]
    srv.submit("a", blobs[0])
    srv.tick()
    blobs.append(s.delta(3))
    srv.submit("a", blobs[1])
    rep = srv.tick()
    assert rep.promotions == 1
    c = s.clients[0]
    s.clock[c] += 5                 # the offset: a clock gap
    blobs.append(s.delta(4))
    srv.submit("a", blobs[2])
    rep2 = srv.tick()
    assert rep2.delta_docs == 0
    assert srv.delta_fallback_count == 1
    assert srv.cache("a") == oracle_cache(blobs)
    blobs.append(s.delta(2))        # still past the gap
    srv.submit("a", blobs[3])
    rep3 = srv.tick()
    assert rep3.delta_docs == 0 and rep3.promotions == 0
    assert srv.cache("a") == oracle_cache(blobs)
    # the pin is NOT permanent: once the missing clocks arrive the
    # history settles, promotion succeeds on the next growth, and
    # the doc returns to the delta route
    gap = [v1.encode_update([ItemRecord(
        client=c, clock=k, parent_root="m", key="gapfill", content=k,
    ) for k in range(11, 16)], DeleteSet())]
    blobs.extend(gap)
    srv.submit("a", gap[0])
    rep4 = srv.tick()               # retry: history grew + settles
    assert rep4.promotions == 1
    assert srv.cache("a") == oracle_cache(blobs)
    blobs.append(s.delta(3))
    srv.submit("a", blobs[-1])
    rep5 = srv.tick()
    assert rep5.delta_docs == 1     # back on the delta route
    assert srv.cache("a") == oracle_cache(blobs)


def test_resident_budget_evicts_lru_and_reconverges():
    """The resident-memory budget: committed resident bytes never
    exceed it (peak == ledger high-water mark), overflow evicts the
    least-recently-served docs back to cold replay, and an evicted
    doc reconverges byte-identically on its next touch."""
    from crdt_tpu.models.incremental import IncrementalReplay

    tracer = set_tracer(Tracer(enabled=True))
    try:
        streams = {f"w{i}": DocStream(i, n_clients=1)
                   for i in range(6)}
        history = {d: [] for d in streams}
        budget = int(
            IncrementalReplay.estimate_resident_bytes(64) * 2.5
        )
        srv = MultiDocServer(resident_max_bytes=budget)

        def touch(docs, k):
            for d in docs:
                b = streams[d].delta(k)
                history[d].append(b)
                srv.submit(d, b)
            srv.tick()

        wave1 = ["w0", "w1", "w2"]
        wave2 = ["w3", "w4", "w5"]
        touch(wave1, 12)            # cold
        touch(wave1, 3)             # promotions (to budget room)
        touch(wave2, 12)            # cold, wave 1 idle
        touch(wave2, 3)             # promotions evict wave-1 LRU
        touch(wave2, 3)
        assert srv.eviction_count > 0
        assert srv.resident_peak_bytes() <= budget
        assert srv.resident_bytes_total() <= budget
        counters = get_tracer().counters()
        assert counters.get("tenant.resident_evictions", 0) \
            == srv.eviction_count
        evicted = [d for d in wave1
                   if srv._docs[d].resident is None]
        assert evicted, "no wave-1 resident was evicted"
        d = evicted[0]
        b = streams[d].delta(3)     # the resubmit after eviction
        history[d].append(b)
        srv.submit(d, b)
        srv.tick()
        assert srv.cache(d) == oracle_cache(history[d])
        assert srv.resident_peak_bytes() <= budget
    finally:
        set_tracer(Tracer(enabled=False))


def test_eviction_flood_writes_snapshots_and_rehydrates(tmp_path):
    """Round 21: the eviction cold-start tax fix. Same budget-flood
    shape as above, but with a snapshot store attached — every
    committed eviction leaves a snapshot behind (``snap.evict_writes``
    tracks it), and the evicted doc's next promotion REHYDRATES
    (``snap.loads`` grows) instead of replaying its full history,
    byte-identical to the oracle."""
    from crdt_tpu.models.incremental import IncrementalReplay
    from crdt_tpu.storage.snapshot import SnapshotStore

    tracer = set_tracer(Tracer(enabled=True))
    try:
        streams = {f"w{i}": DocStream(i, n_clients=1)
                   for i in range(6)}
        history = {d: [] for d in streams}
        budget = int(
            IncrementalReplay.estimate_resident_bytes(64) * 2.5
        )
        store = SnapshotStore(str(tmp_path))
        srv = MultiDocServer(resident_max_bytes=budget,
                             snap_store=store)

        def touch(docs, k):
            for d in docs:
                b = streams[d].delta(k)
                history[d].append(b)
                srv.submit(d, b)
            srv.tick()

        wave1 = ["w0", "w1", "w2"]
        wave2 = ["w3", "w4", "w5"]
        touch(wave1, 12)
        touch(wave1, 3)
        touch(wave2, 12)
        touch(wave2, 3)             # promotions evict wave-1 LRU
        touch(wave2, 3)
        assert srv.eviction_count > 0
        counters = get_tracer().counters()
        assert counters.get("snap.evict_writes", 0) \
            == srv.eviction_count
        assert counters.get("snap.evict_writes", 0) \
            <= counters.get("snap.writes", 0)
        evicted = [d for d in wave1
                   if srv._docs[d].resident is None]
        assert evicted, "no wave-1 resident was evicted"
        d = evicted[0]
        loads0 = counters.get("snap.loads", 0)
        # resubmit twice: serve-cold then promote — the promotion
        # must go through the snapshot, not a full-history rebuild
        for _ in range(2):
            b = streams[d].delta(3)
            history[d].append(b)
            srv.submit(d, b)
            srv.tick()
        assert srv.cache(d) == oracle_cache(history[d])
        if srv._docs[d].resident is not None:
            assert get_tracer().counters().get("snap.loads", 0) \
                > loads0, "re-promotion did not rehydrate"
        assert srv.resident_peak_bytes() <= budget
    finally:
        set_tracer(Tracer(enabled=False))


def test_serve_live_ingest_scheduler():
    """The round-15 live-ingest loop: a stream of update batches is
    drained across bounded ticks (ingest overlapping in-flight
    dispatches via the tick hook), every doc converges to its full-
    history oracle, and the settled history is exactly the submitted
    blobs in order — a mid-tick arrival is never marked converged
    without being converged."""
    streams = {f"s{i}": DocStream(i) for i in range(4)}
    history = {d: [] for d in streams}

    def source():
        for t in range(5):
            batch = []
            for d, s in streams.items():
                b = s.delta(8 if t == 0 else 2)
                history[d].append(b)
                batch.append((d, b))
            yield batch

    srv = MultiDocServer()
    rep = srv.serve(source(), max_ticks=12)
    assert rep.submitted == 20
    assert 0 < rep.ticks <= 12
    assert not srv.dirty_docs()
    assert rep.delta_docs > 0, "steady state never reached the " \
        "delta route"
    for d in streams:
        assert srv.cache(d) == oracle_cache(history[d]), d
        assert srv._docs[d].blobs == history[d], d


def test_doc_digests_skip_clean_docs(monkeypatch):
    """Digest caching (round-15 satellite): converging never
    digests; the first beacon computes one digest per doc; a second
    beacon over a clean population computes ZERO digests and counts
    every skip; a touched doc re-digests while clean neighbors still
    skip."""
    import crdt_tpu.models.multidoc as md

    tracer = set_tracer(Tracer(enabled=True))
    try:
        streams = {f"d{i}": DocStream(i) for i in range(4)}
        srv = MultiDocServer()
        for d, s in streams.items():
            srv.submit(d, s.delta(6))
        srv.tick()
        calls = {"n": 0}
        real = md.cache_digest

        def counting(c):
            calls["n"] += 1
            return real(c)

        monkeypatch.setattr(md, "cache_digest", counting)
        srv.doc_digests()
        assert calls["n"] == 4
        srv.doc_digests()           # clean: zero digest work
        assert calls["n"] == 4
        assert get_tracer().counters().get(
            "sentinel.doc_digest_skips") == 4
        srv.submit("d0", streams["d0"].delta(3))
        srv.tick()
        srv.doc_digests()           # only the touched doc recomputes
        assert calls["n"] == 5
    finally:
        set_tracer(Tracer(enabled=False))


def test_multidoc_stage_counts_docs_packed():
    """The staging seam counts docs per multi-doc plan — the
    amortization evidence the bench publishes."""
    tracer = set_tracer(Tracer(enabled=True))
    try:
        doc_sets = [doc_blobs(i, K=10) for i in range(3)]
        staged = [rp.stage(rp.decode(bs)) for bs in doc_sets]
        comb, _ = _concat_cols([c for c, _ in staged])
        plan = packed.stage(comb)
        assert plan is not None
        assert get_tracer().counters().get(
            "converge.docs_packed") == 3
    finally:
        set_tracer(Tracer(enabled=False))
