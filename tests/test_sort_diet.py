"""Sort diet (round 12): Pallas segmented-merge kernels, differential.

Two layers of oracle under test, both against the SAME jnp fallbacks
that production uses for non-TPU backends and past-width-guard blocks:

1. **Kernel differentials.** ``seg_argmax_scan`` and
   ``stream_scatter`` under ``CRDT_TPU_PALLAS=interpret`` must equal
   their jnp oracles (``*_jnp``) at every position — single-row runs,
   one whole-block run, random run layouts, ragged (non-tile-multiple)
   lengths, ties on the major key, and out-of-range scatter targets.
2. **Route differentials.** The fused converge driven through the
   interpret-mode kernels must produce byte-identical cache + snapshot
   to the jnp path (``CRDT_TPU_PALLAS=0``) across the one-shot,
   streaming, and incremental routes — including int16-narrowed and
   hi/lo staging edges, delete-only chunks, single-row and
   crossover-width segments, and clock ties at 2^15-1 / 2^31-1.

The width guard's fallback (a block past ``_SCAN_PALLAS_MAX`` must
take the jnp path and count ``converge.pallas_fallback``) is pinned
with a shrunken guard, not a 128k-row trace.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu.codec import v1
from crdt_tpu.core.engine import Engine
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.models import replay_trace, stream_replay
from crdt_tpu.obs import Tracer, get_tracer, set_tracer
from crdt_tpu.ops import packed
from crdt_tpu.ops import pallas_kernels as pk


@pytest.fixture
def tracer():
    old = get_tracer()
    tr = set_tracer(Tracer(enabled=True))
    try:
        yield tr
    finally:
        set_tracer(old)


# ---------------------------------------------------------------------------
# kernel differentials: interpret-mode pallas vs the jnp oracle
# ---------------------------------------------------------------------------


def _run_layout(rng, n, runs):
    """Random (client, flags) with `runs` run-start positions."""
    client = rng.integers(0, 1 << 14, n).astype(np.int32)
    flags = np.zeros(n, np.int32)
    flags[0] = 1
    if runs > 1:
        starts = rng.choice(np.arange(1, n), size=min(runs - 1, n - 1),
                            replace=False)
        flags[starts] = 1
    return client, flags


class TestSegArgmaxScan:
    @pytest.mark.parametrize("n,runs", [
        (1, 1),            # single row
        (7, 7),            # every row its own run (single-row segments)
        (128, 1),          # one whole-lane-row run
        (1000, 37),        # ragged length, random runs
        (8 * 128 + 3, 96),  # > one sublane tile, ragged
    ])
    def test_matches_jnp_oracle(self, n, runs):
        rng = np.random.default_rng(n * 1000 + runs)
        client, flags = _run_layout(rng, n, runs)
        want = np.asarray(pk.seg_argmax_scan_jnp(
            jnp.asarray(client), jnp.asarray(flags)))
        got = np.asarray(pk.seg_argmax_scan(
            jnp.asarray(client), jnp.asarray(flags), mode="interpret"))
        assert (got == want).all()

    def test_tie_keeps_earlier_position(self):
        # equal major key: the run-prefix argmax must keep the EARLIER
        # position (the sibling rule's minimum clock at equal client)
        client = jnp.asarray(np.asarray([5, 5, 5, 2], np.int32))
        flags = jnp.asarray(np.asarray([1, 0, 0, 0], np.int32))
        for mode in ("interpret", "jnp"):
            out = np.asarray(pk.seg_argmax_scan(client, flags, mode=mode))
            assert out[3] == 0, mode

    def test_run_boundaries_isolate(self):
        # a huge client in run 0 must not leak into run 1
        client = jnp.asarray(np.asarray([999, 1, 3, 2], np.int32))
        flags = jnp.asarray(np.asarray([1, 0, 1, 0], np.int32))
        for mode in ("interpret", "jnp"):
            out = np.asarray(pk.seg_argmax_scan(client, flags, mode=mode))
            assert out[1] == 0 and out[3] == 2, mode


class TestStreamScatter:
    @pytest.mark.parametrize("n", [1, 5, 128, 700, 8 * 128 + 9])
    def test_permutation_round_trip(self, n):
        rng = np.random.default_rng(n)
        pos = rng.permutation(n).astype(np.int32)
        want = np.asarray(pk.stream_scatter_jnp(jnp.asarray(pos), n))
        got = np.asarray(pk.stream_scatter(
            jnp.asarray(pos), n, mode="interpret"))
        assert (got == want).all()
        assert (np.sort(got) == np.arange(n)).all()

    def test_dropped_targets_and_holes(self):
        # -1 (invalid) and past-the-end targets drop; untargeted
        # output slots stay -1 holes — identically in both paths
        pos = jnp.asarray(np.asarray([3, -1, 0, 99, 5], np.int32))
        want = np.asarray(pk.stream_scatter_jnp(pos, 8))
        got = np.asarray(pk.stream_scatter(pos, 8, mode="interpret"))
        assert (got == want).all()
        assert got[3] == 0 and got[0] == 2 and got[5] == 4
        assert (got[[1, 2, 4, 6, 7]] == -1).all()


# ---------------------------------------------------------------------------
# route differentials: interpret-mode converge vs the jnp path,
# byte-identical cache + snapshot
# ---------------------------------------------------------------------------


def sort_diet_blobs(clock_base=0, R=5, K=16, seed=12, tie=False):
    """Map chains + list appends + right-bearing mid-inserts + deletes,
    clocks offset to straddle a chosen width boundary; ``tie`` makes
    every client reuse the SAME clock values (Lamport ties resolved by
    client id alone)."""
    rng = np.random.default_rng(seed)
    blobs = []
    for r in range(R):
        client = r + 1
        recs, chain, prev = [], [], None
        for k in range(K):
            clock = clock_base + (k if not tie else k // 2 * 2)
            clock += 0 if not tie else (k % 2)  # keep ids unique
            kind = int(rng.integers(0, 3))
            if kind == 0:
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root="m",
                    key=f"k{int(rng.integers(0, 4))}", content=k))
            elif kind == 1 and chain:
                j = int(rng.integers(0, len(chain)))
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root="text",
                    origin=chain[j - 1] if j > 0 else None,
                    right=chain[j], content=k))
                chain.insert(j, (client, clock))
            else:
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root="l",
                    origin=(client, prev) if prev is not None else None,
                    content=k))
                prev = clock
                chain.append((client, clock))
        ds = DeleteSet()
        ds.add(client, clock_base + int(rng.integers(0, K)))
        blobs.append(v1.encode_update(recs, ds))
    return blobs


def _pallas_vs_jnp(blobs, monkeypatch, *, incremental=True):
    """interpret-mode kernels vs the jnp oracle on every route:
    byte-identical cache + snapshot (and vs the scalar engine)."""
    monkeypatch.setenv("CRDT_TPU_PALLAS", "0")
    want = replay_trace(blobs, route="device")
    st_want = stream_replay(blobs, chunk_blobs=2, max_shards=3,
                            min_shard_rows=1)
    assert st_want.cache == want.cache

    monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")
    got = replay_trace(blobs, route="device")
    assert got.cache == want.cache
    assert got.snapshot == want.snapshot
    st = stream_replay(blobs, chunk_blobs=2, max_shards=3,
                       min_shard_rows=1)
    assert st.cache == want.cache and st.snapshot == want.snapshot
    if incremental:
        from crdt_tpu.models.incremental import IncrementalReplay

        inc = IncrementalReplay(capacity=1 << 13)
        inc.device_min_rows = 0  # force the device splice every chunk
        for i in range(0, len(blobs), 2):
            inc.apply(blobs[i:i + 2])
        assert inc.cache == want.cache
    return want


class TestRouteDifferentials:
    def test_small_clocks_all_routes(self, monkeypatch):
        res = _pallas_vs_jnp(sort_diet_blobs(0), monkeypatch)
        eng = Engine(10 ** 6)
        for b in sort_diet_blobs(0):
            v1.apply_update(eng, b)
        assert res.cache == eng.to_json()

    # the offset-clock tie traces skip the incremental route: its
    # engine-shaped admission stashes records until the client's SV is
    # contiguous from 0, so a trace starting at clock 2^15-8 is
    # (correctly) all-pending there — the one-shot and streaming
    # routes cover the kernel boundary behavior

    def test_clock_ties_at_int16_boundary(self, monkeypatch):
        _pallas_vs_jnp(sort_diet_blobs((1 << 15) - 8, tie=True),
                       monkeypatch, incremental=False)

    def test_clock_ties_at_int31_boundary(self, monkeypatch):
        _pallas_vs_jnp(sort_diet_blobs((1 << 31) - 8, tie=True),
                       monkeypatch, incremental=False)

    def test_delete_only_and_empty_chunks(self, monkeypatch):
        ds = DeleteSet()
        ds.add(1, 3, 4)
        blobs = sort_diet_blobs(0, R=4, K=12) + [
            v1.encode_update([], ds),
            v1.encode_update([], DeleteSet()),
        ]
        _pallas_vs_jnp(blobs, monkeypatch)

    def test_single_row_segments(self, monkeypatch):
        # one op per root: every segment is a single row, every run in
        # the kernels is width 1
        recs = [
            ItemRecord(client=1, clock=k, parent_root=f"r{k}", content=k)
            for k in range(7)
        ] + [
            ItemRecord(client=2, clock=k, parent_root=f"m{k}",
                       key="k", content=k)
            for k in range(7)
        ]
        blobs = [v1.encode_update(recs, DeleteSet())]
        _pallas_vs_jnp(blobs, monkeypatch)

    def test_int16_narrowed_staging_edges(self, monkeypatch, tracer):
        # a hi/lo (forced-wide-section) staging edge through the
        # interpret kernels: the self-referential origin makes
        # map_chain_end take the exact hi/lo stretches, and the
        # interpret path must still match jnp exactly
        n = 6
        cols = {
            "client": np.full(n, 1, np.int64),
            "clock": np.arange(n, dtype=np.int64),
            "parent_is_root": np.ones(n, bool),
            "parent_a": np.zeros(n, np.int64),
            "parent_b": np.full(n, -1, np.int64),
            "key_id": np.zeros(n, np.int64),
            "origin_client": np.full(n, -1, np.int64),
            "origin_clock": np.full(n, -1, np.int64),
            "valid": np.ones(n, bool),
        }
        cols["origin_client"][3] = 1
        cols["origin_clock"][3] = 3
        monkeypatch.setenv("CRDT_TPU_PALLAS", "0")
        want = packed.converge(packed.stage(cols))
        monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")
        plan = packed.stage(cols)
        assert dict(zip(packed.SECTION_NAMES,
                        plan.encs))["map_chain_end"] == "hilo"
        got = packed.converge(plan)
        assert list(got.win_rows) == list(want.win_rows)
        assert list(got.stream_row) == list(want.stream_row)

    def test_crossover_width_guard_falls_back(self, monkeypatch, tracer):
        # a block past the VMEM width guard must take the jnp oracle
        # path (and count the fallback) even with pallas requested
        monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")
        monkeypatch.setattr(pk, "_SCAN_PALLAS_MAX", 16)
        blobs = sort_diet_blobs(0, R=4, K=16)
        got = replay_trace(blobs, route="device")
        cnt = tracer.counters("converge.")
        assert cnt.get("converge.pallas_fallback", 0) > 0, cnt
        assert cnt.get('converge.pallas{mode="jnp"}', 0) > 0, cnt
        monkeypatch.setenv("CRDT_TPU_PALLAS", "0")
        want = replay_trace(blobs, route="device")
        assert got.cache == want.cache
        assert got.snapshot == want.snapshot

    def test_mode_counter_fires_per_dispatch(self, monkeypatch, tracer):
        monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")
        replay_trace(sort_diet_blobs(0, R=3, K=8), route="device")
        cnt = tracer.counters("converge.")
        assert cnt.get('converge.pallas{mode="interpret"}', 0) > 0, cnt
