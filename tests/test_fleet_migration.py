"""Round-24 chaos matrix: crash-safe live migration, never a fork.

The tentpole proof, pinned four ways:

- **Kill matrix** — the source process dies at EVERY step of the
  handoff ladder (drain / ship / commit / ack) and the destination
  dies mid-rehydrate; each kill lands on its own counted recovery
  rung (``migration.recovery{step=...}``), exactly ONE process
  serves the doc afterwards, and that process serves the
  pre-migration digest.
- **Partition matrix** — scripted frame drops (offer / commit / ack
  windows on ``net.faults.HandoffFaultSchedule``) resolve through
  the probe/NACK path: a lost ack completes via probe, a lost commit
  reclaims at a HIGHER epoch (the late replay is fenced off), a lost
  offer aborts cleanly.
- **Byte identity** — updates submitted mid-handoff (buffered into
  the migration tail, riding the commit frame) converge to a doc
  whose digest, state vector, snapshot-generation bytes, and
  state-as-update bytes all equal a migration-free oracle's.
- **Durability** — a committed handoff survives the destination
  dying before its first checkpoint (the commit-path tail stash,
  ``migration.tail_restores``), and a checkpoint stamped by a NEWER
  fencing epoch is refused on restore
  (``snap.fallbacks{reason=stale_epoch}`` — satellite 2).
"""

import pytest

from crdt_tpu.codec import v1
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.fleet import (
    FleetNode,
    HashRing,
    LeaseTable,
    MemFabric,
    PlacementLoop,
)
from crdt_tpu.fleet import wire
from crdt_tpu.guard.faults import MigrationCrashPlan, SimulatedCrash
from crdt_tpu.models.multidoc import MultiDocServer
from crdt_tpu.net.faults import (
    DuplicateAdviceSchedule,
    HandoffFaultSchedule,
)
from crdt_tpu.obs import Tracer, set_tracer
from crdt_tpu.obs.control import Controller
from crdt_tpu.storage.snapshot import SnapshotStore

MEMBERS = ("a", "b", "c")
DOC = "doc"  # ring-owned by "a" at vnodes=64 (test_placement pins)
SERVER_KW = {"slo_ms": 10_000.0}


@pytest.fixture(autouse=True)
def _quiet_obs():
    old = set_tracer(Tracer(enabled=False))
    yield
    set_tracer(old)


def chain_blob(client, k0, n_ops=4):
    """One doc's chained list appends (clocks k0..k0+n_ops-1)."""
    recs = []
    for j in range(n_ops):
        k = k0 + j
        recs.append(ItemRecord(
            client=client, clock=k, parent_root="l",
            origin=(client, k - 1) if k else None,
            content=client * 1000 + k,
        ))
    return v1.encode_update(recs, DeleteSet())


def make_fleet(tmp_path, *, faults=None, crash_plans=None,
               timeout_ticks=3, beacon_every=0):
    """Three FleetNodes on one MemFabric, each with its own
    SnapshotStore (the crash-revive seam)."""
    fab = MemFabric(faults=faults)
    stores, nodes = {}, {}
    for p in MEMBERS:
        stores[p] = SnapshotStore(str(tmp_path / p))
        nodes[p] = FleetNode(
            p, MEMBERS, fab, store=stores[p],
            timeout_ticks=timeout_ticks, beacon_every=beacon_every,
            crash_plan=(crash_plans or {}).get(p),
            server_kw=dict(SERVER_KW))
    return fab, nodes, stores


def run_ticks(fab, nodes, n):
    """Drive the fleet; a SimulatedCrash kills that process (its
    queue dies with it) — the driver half of MigrationCrashPlan."""
    for _ in range(n):
        for p in sorted(nodes):
            if p in fab.dead:
                continue
            try:
                nodes[p].tick()
            except SimulatedCrash:
                fab.kill(p)


def revive(fab, nodes, stores, proc, *, timeout_ticks=3,
           beacon_every=0):
    """Rebuild a killed process from its own store (lease table and
    intent blob reload in restore()) — volatile state is gone."""
    node = FleetNode(
        proc, MEMBERS, fab, store=stores[proc],
        timeout_ticks=timeout_ticks, beacon_every=beacon_every,
        server_kw=dict(SERVER_KW))
    fab.revive(proc, node)
    node.restore()
    nodes[proc] = node
    return node


def seed_doc(nodes, doc=DOC, owner="a", rounds=4):
    """Submit ``rounds`` chained blobs over as many ticks so the doc
    settles resident (warm) on its owner; returns the digest."""
    for k in range(rounds):
        r, _ = nodes[owner].submit(doc, chain_blob(7, 4 * k))
        assert r == "ok"
        for p in sorted(nodes):
            nodes[p].tick()
    return nodes[owner].server.digest(doc)


def serving(nodes, doc=DOC):
    """Who will actually serve the doc right now? (A refused serve
    counts ``fleet.fence_rejects{op=serve}`` on the refuser — the
    sweep itself exercises the fence.)"""
    return [p for p in sorted(nodes)
            if nodes[p].digest(doc) is not None]


# ---- the happy path ------------------------------------------------


class TestHappyPath:
    def test_live_migration_is_lossless_and_single_owner(self, tmp_path):
        fab, nodes, stores = make_fleet(tmp_path)
        d0 = seed_doc(nodes)
        assert serving(nodes) == ["a"]
        assert nodes["a"].migrate(DOC, "c")
        run_ticks(fab, nodes, 6)
        assert serving(nodes) == ["c"]
        assert nodes["c"].server.digest(DOC) == d0
        assert nodes["a"].migrator.completed == 1
        assert nodes["a"].migrator.recoveries == {}
        # the lease moved to epoch 2 everywhere that heard about it
        assert nodes["c"].lease.lease(DOC) == (2, "c")
        assert nodes["a"].lease.lease(DOC) == (2, "c")
        # no fork was ever even attempted
        assert all(nodes[p].lease.fork_refused == 0 for p in nodes)
        # a mis-routed submit redirects to the new owner
        r, owner = nodes["a"].submit(DOC, chain_blob(7, 16))
        assert (r, owner) == ("redirect", "c")
        assert nodes["a"].redirects == 1

    def test_migrate_refusals(self, tmp_path):
        fab, nodes, stores = make_fleet(tmp_path)
        seed_doc(nodes)
        assert not nodes["b"].migrate(DOC, "c")   # not the owner
        assert not nodes["a"].migrate(DOC, "a")   # self-move
        assert nodes["a"].migrate(DOC, "c")
        assert not nodes["a"].migrate(DOC, "b")   # already in flight
        assert nodes["a"].migrator.started == 1


# ---- the kill matrix -----------------------------------------------


KILL_CASES = [
    # (kill step, crashed proc, expected recoveries on the REVIVED
    #  process, expected recoveries on the surviving peer, winner)
    ("drain", "a", {"drain": 1}, {}, "a"),
    ("ship", "a", {"ship": 1}, {}, "a"),
    ("commit", "a", {"commit": 2}, {"commit": 1}, "a"),
    ("ack", "a", {"commit": 1, "ack": 1}, {}, "c"),
    ("rehydrate", "c", {}, {"rehydrate": 1}, "a"),
]


class TestKillMatrix:
    @pytest.mark.parametrize(
        "step,victim,rec_revived,rec_peer,winner", KILL_CASES,
        ids=[c[0] for c in KILL_CASES])
    def test_kill_at_step(self, tmp_path, step, victim, rec_revived,
                          rec_peer, winner):
        """Kill one process at exactly one ladder step; the fleet
        must converge to ONE serving owner holding the seeded
        digest, with the recovery counted on the pinned rung."""
        plans = {victim: MigrationCrashPlan(kill_at={step: 1})}
        fab, nodes, stores = make_fleet(tmp_path, crash_plans=plans)
        d0 = seed_doc(nodes)
        # durability floor: the owner checkpoints BEFORE the move
        # (the crash matrix is about ownership, not WAL loss)
        nodes["a"].checkpoint()
        assert nodes["a"].migrate(DOC, "c")
        run_ticks(fab, nodes, 4)
        assert fab.dead == {victim}, (
            f"crash plan for step {step!r} never fired")
        # let the survivor's timeouts run before the revive
        run_ticks(fab, nodes, 4)
        revived = revive(fab, nodes, stores, victim)
        run_ticks(fab, nodes, 12)
        peer = "c" if victim == "a" else "a"
        assert revived.migrator.recoveries == rec_revived
        assert nodes[peer].migrator.recoveries == rec_peer
        assert serving(nodes) == [winner]
        assert nodes[winner].server.digest(DOC) == d0
        # the fence refused the losers (the serving sweep above
        # asked every process)
        for p in nodes:
            if p != winner:
                assert nodes[p].lease.fence_rejects >= 1
        assert all(nodes[p].lease.fork_refused == 0 for p in nodes)

    def test_commit_kill_reclaims_above_the_granted_epoch(
            self, tmp_path):
        """The commit-step crash is the fork trap: src already
        granted (and persisted) the lease away. The revived source
        must NOT serve until the destination's binding NACK, and the
        reclaim lands ABOVE the failed epoch so a delayed commit
        replay can never resurrect the grant."""
        plans = {"a": MigrationCrashPlan(kill_at={"commit": 1})}
        fab, nodes, stores = make_fleet(tmp_path, crash_plans=plans)
        seed_doc(nodes)
        nodes["a"].checkpoint()
        assert nodes["a"].migrate(DOC, "c")
        run_ticks(fab, nodes, 4)
        assert fab.dead == {"a"}
        revived = revive(fab, nodes, stores, "a")
        # straight after restore: the persisted grant fences the
        # restart — it knows the doc MAY belong to c and probes
        # instead of serving
        assert revived.lease.lease(DOC) == (2, "c")
        assert revived.digest(DOC) is None
        run_ticks(fab, nodes, 12)
        # c's binding NACK proved the commit never landed: reclaim
        # at epoch 3 (> the failed grant's 2)
        assert revived.lease.lease(DOC) == (3, "a")
        assert serving(nodes) == ["a"]


# ---- the partition matrix (scripted frame drops) -------------------


DROP_CASES = [
    # (dropped kind, link, src recoveries, dst recoveries, winner,
    #  final lease epoch at the winner)
    ("commit", ("a", "c"), {"commit": 1}, {"commit": 1}, "a", 3),
    ("ack", ("c", "a"), {"ack": 1}, {}, "c", 2),
    ("offer", ("a", "c"), {"rehydrate": 1}, {}, "a", 1),
]


class TestPartitionMatrix:
    @pytest.mark.parametrize(
        "kind,link,rec_src,rec_dst,winner,epoch", DROP_CASES,
        ids=[c[0] for c in DROP_CASES])
    def test_dropped_frame_window(self, tmp_path, kind, link,
                                  rec_src, rec_dst, winner, epoch):
        faults = HandoffFaultSchedule(seed=3, windows=[{
            "src": link[0], "dst": link[1], "kinds": (kind,),
            "from_n": 1, "mode": "drop",
        }])
        fab, nodes, stores = make_fleet(tmp_path, faults=faults)
        d0 = seed_doc(nodes)
        assert nodes["a"].migrate(DOC, "c")
        run_ticks(fab, nodes, 20)
        assert faults.window_hits >= 1
        assert nodes["a"].migrator.recoveries == rec_src
        assert nodes["c"].migrator.recoveries == rec_dst
        assert serving(nodes) == [winner]
        assert nodes[winner].server.digest(DOC) == d0
        assert nodes[winner].lease.lease(DOC) == (epoch, winner)
        assert all(nodes[p].lease.fork_refused == 0 for p in nodes)

    def test_dropped_ack_completes_via_probe(self, tmp_path):
        """The lost-ack case must end COMPLETED (not reclaimed): the
        probe reply proves dst serves at the new epoch, so the
        source finishes the handoff instead of forking it back."""
        faults = HandoffFaultSchedule(seed=3, windows=[{
            "src": "c", "dst": "a", "kinds": ("ack",),
            "from_n": 1, "to_n": 1, "mode": "drop",
        }])
        fab, nodes, stores = make_fleet(tmp_path, faults=faults)
        seed_doc(nodes)
        assert nodes["a"].migrate(DOC, "c")
        run_ticks(fab, nodes, 20)
        assert nodes["a"].migrator.completed == 1
        assert DOC not in nodes["a"].server._docs  # state released


# ---- byte identity under mid-handoff traffic -----------------------


def try_submit(nodes, doc, blob):
    """A redirect-chasing client: offer the update to each process,
    following ownership redirects — exactly one accepts."""
    for p in sorted(nodes):
        r, info = nodes[p].submit(doc, blob)
        if r in ("ok", "buffered"):
            return r
    raise AssertionError("no process accepted the update")


class TestByteIdentity:
    def test_mid_handoff_tail_vs_migration_free_oracle(self, tmp_path):
        """Updates landing DURING the handoff ride the migration
        tail; afterwards the moved doc is byte-identical — digest,
        state vector, snapshot generation, state-as-update — to a
        single-server oracle fed the same blobs in the same order."""
        from crdt_tpu.storage.snapshot import encode_engine

        fab, nodes, stores = make_fleet(tmp_path)
        oracle = MultiDocServer(**SERVER_KW)
        blobs = [chain_blob(7, 4 * k) for k in range(6)]
        for k in range(3):                       # before the move
            assert try_submit(nodes, DOC, blobs[k]) == "ok"
            oracle.submit(DOC, blobs[k])
            run_ticks(fab, nodes, 1)
            oracle.tick()
        assert nodes["a"].migrate(DOC, "c")
        for k in range(3, 6):                    # during / after
            try_submit(nodes, DOC, blobs[k])
            oracle.submit(DOC, blobs[k])
            run_ticks(fab, nodes, 1)
            oracle.tick()
        run_ticks(fab, nodes, 8)
        for _ in range(8):
            oracle.tick()
        assert serving(nodes) == ["c"]
        assert nodes["a"].migrator.completed == 1
        srv = nodes["c"].server
        assert srv.digest(DOC) == oracle.digest(DOC)
        got = srv._docs[DOC].resident
        want = oracle._docs[DOC].resident
        assert got is not None and want is not None
        assert got.state_vector() == want.state_vector()
        assert got.encode_state_as_update() == \
            want.encode_state_as_update()
        assert encode_engine(got, seq=0) == encode_engine(want, seq=0)


# ---- durability: the commit-path tail stash ------------------------


class TestDstDurability:
    def test_dst_crash_after_commit_restores_from_tail_stash(
            self, tmp_path):
        """dst dies right after taking ownership, BEFORE any
        checkpoint: the commit handler stashed the doc's full
        history durably before acking, so the revived dst re-seeds
        the doc (``migration.tail_restores``) instead of losing a
        committed handoff."""
        fab, nodes, stores = make_fleet(tmp_path)
        d0 = seed_doc(nodes)
        assert nodes["a"].migrate(DOC, "c")
        # one more update mid-drain: buffers into the tail
        r, _ = nodes["a"].submit(DOC, chain_blob(7, 16))
        assert r == "buffered"
        run_ticks(fab, nodes, 8)
        assert serving(nodes) == ["c"]
        d1 = nodes["c"].server.digest(DOC)
        assert d1 != d0  # the tail blob landed
        # kill c cold (no checkpoint ever ran on it)
        fab.kill("c")
        tracer = set_tracer(Tracer(enabled=True))
        try:
            revived = revive(fab, nodes, stores, "c")
            assert tracer.counters().get(
                "migration.tail_restores", 0) == 1
        finally:
            set_tracer(Tracer(enabled=False))
        run_ticks(fab, nodes, 4)
        assert serving(nodes) == ["c"]
        assert revived.server.digest(DOC) == d1


# ---- beacons: the partitioned ex-owner heals -----------------------


class TestBeacons:
    def test_stale_owner_demotes_on_newer_epoch_beacon(self, tmp_path):
        fab, nodes, stores = make_fleet(tmp_path, beacon_every=2)
        seed_doc(nodes)
        # b returns from a partition holding a NEWER lease (epoch 5)
        # and the doc's state — the beacon must demote a, not fork
        nodes["b"].lease.grant(DOC, 5, "b")
        nodes["b"].server.submit(DOC, chain_blob(7, 0))
        run_ticks(fab, nodes, 6)
        assert nodes["a"].demotions == 1
        assert nodes["a"].lease.lease(DOC) == (5, "b")
        assert serving(nodes) == ["b"]

    def test_equal_epoch_rival_beacon_is_a_refused_fork(self, tmp_path):
        fab, nodes, stores = make_fleet(tmp_path, beacon_every=2)
        seed_doc(nodes)
        # b claims the doc at the SAME epoch a holds: a fork attempt
        nodes["b"].lease._leases[DOC] = (1, "b")  # corrupted rival
        nodes["b"].server.submit(DOC, chain_blob(7, 0))
        run_ticks(fab, nodes, 6)
        assert nodes["a"].lease.fork_refused >= 1
        assert nodes["a"].lease.lease(DOC) == (1, "a")
        assert nodes["a"].demotions == 0
        assert "a" in serving(nodes)


# ---- satellite 2: fenced checkpoint/restore ------------------------


class TestFencedRestore:
    def _seeded_server(self, store):
        srv = MultiDocServer(snap_store=store, **SERVER_KW)
        for k in range(4):
            srv.submit("w", chain_blob(7, 4 * k))
            srv.tick()
        assert srv._docs["w"].resident is not None
        return srv

    def test_restore_refuses_newer_fencing_epoch(self, tmp_path):
        """A snapshot stamped by a NEWER fencing epoch than the
        restoring process holds is poison (it was written by a later
        owner this process has not heard of): refused and counted,
        never adopted."""
        store = SnapshotStore(str(tmp_path))
        ring = HashRing(["a", "b"], vnodes=64)
        writer = LeaseTable("a", ring)
        writer.grant("w", 5, "a")
        srv = self._seeded_server(store)
        assert srv.checkpoint(fence=writer) >= 1
        # the restoring process only knows the ring default (epoch 1)
        stale = LeaseTable("a", ring)
        tracer = set_tracer(Tracer(enabled=True))
        try:
            srv2 = MultiDocServer(snap_store=store, **SERVER_KW)
            warm = srv2.restore(fence=stale)
            assert warm == 0
            assert "w" not in srv2._docs
            assert srv2.snap_fallback_count == 1
            assert tracer.counters()[
                'snap.fallbacks{reason="stale_epoch"}'] == 1
        finally:
            set_tracer(Tracer(enabled=False))

    def test_restore_admits_matching_epoch(self, tmp_path):
        store = SnapshotStore(str(tmp_path))
        ring = HashRing(["a", "b"], vnodes=64)
        writer = LeaseTable("a", ring)
        writer.grant("w", 5, "a")
        srv = self._seeded_server(store)
        d0 = srv.digest("w")
        srv.checkpoint(fence=writer)
        srv2 = MultiDocServer(snap_store=store, **SERVER_KW)
        assert srv2.restore(fence=writer) == 1
        assert srv2.digest("w") == d0
        assert srv2.snap_fallback_count == 0

    def test_unfenced_restore_unchanged(self, tmp_path):
        store = SnapshotStore(str(tmp_path))
        srv = self._seeded_server(store)
        d0 = srv.digest("w")
        srv.checkpoint()
        srv2 = MultiDocServer(snap_store=store, **SERVER_KW)
        assert srv2.restore() == 1
        assert srv2.digest("w") == d0


# ---- the placement loop (advice in, migrations out) ----------------


class TestPlacementLoop:
    def test_advice_rows_carry_seq_and_target(self):
        """Satellite 1: the controller's rebalance advice carries a
        monotonic seq (consumer dedup) and the advised destination
        when the fleet layer wires a placement hint."""
        c = Controller(cooldown_ticks=0)
        c.observe({
            "tick": 7,
            "budget": {"max_bytes": 2048, "max_updates": 4},
            "tenants": {DOC: {"burn": 1.0}},
        })
        adv = c.advice()
        assert len(adv) == 1
        assert adv[0]["seq"] == 1
        assert adv[0]["target"] is None
        ring = HashRing(MEMBERS, vnodes=64)
        c.placement_hint = lambda t: ring.least_loaded_successor(
            t, exclude=["a"], loads={"b": 9.0, "c": 1.0})
        assert c.advice()[0]["target"] == "c"

    def test_duplicated_and_replayed_advice_is_idempotent(
            self, tmp_path):
        """The chaos schedule duplicates rows within a poll and
        replays stale rows from earlier polls; the loop must start
        exactly ONE migration per distinct breach, after the
        hysteresis streak, inside the per-tick budget."""
        fab, nodes, stores = make_fleet(tmp_path)
        seed_doc(nodes)
        ring = HashRing(MEMBERS, vnodes=64)
        loop = PlacementLoop(ring, nodes.get, hysteresis=2,
                             budget_per_tick=1)
        sched = DuplicateAdviceSchedule(seed=7, duplicate=0.9,
                                        replay=0.9)
        row = {"action": "rebalance_away", "tenant": DOC,
               "proc": "a", "seq": 1, "burn": 1.3, "target": None}
        for poll in range(6):
            mangled = sched.mangle(poll, [dict(row)])
            loop.observe(poll, mangled, loads={"b": 2.0, "c": 1.0})
            run_ticks(fab, nodes, 1)
        assert sched.injected > 0
        assert loop.dup_drops > 0
        assert loop.migrations == 1
        assert nodes["a"].migrator.started == 1
        run_ticks(fab, nodes, 6)
        assert serving(nodes) == ["c"]
        acts = [r for r in loop.ledger.rows()
                if r["action"] == "migrate"]
        assert len(acts) == 1
        assert acts[0]["tenant"] == DOC and acts[0]["dst"] == "c"

    def test_in_flight_breach_is_skipped_with_a_ledger_row(
            self, tmp_path):
        fab, nodes, stores = make_fleet(tmp_path)
        seed_doc(nodes)
        ring = HashRing(MEMBERS, vnodes=64)
        loop = PlacementLoop(ring, nodes.get, hysteresis=1)
        row = {"action": "rebalance_away", "tenant": DOC,
               "proc": "a", "seq": 1, "burn": 1.3, "target": "c"}
        loop.observe(0, [dict(row)])
        assert loop.migrations == 1
        # same breach, higher seq, while the handoff is in flight
        loop.observe(1, [dict(row, seq=2)])
        assert loop.migrations == 1
        skips = [r for r in loop.ledger.rows()
                 if r["action"] == "skip"]
        assert skips and skips[-1]["why"] == "in_flight"


# ---- the frame codec -----------------------------------------------


class TestWire:
    def test_frame_round_trip(self):
        hdr = {"kind": "offer", "doc": DOC, "epoch": 2, "proc": "a"}
        payload = wire.pack_blobs([b"one", b"", b"three"])
        frame = wire.encode_frame(hdr, payload)
        dec = wire.decode_frame(frame)
        assert dec is not None
        assert dec[0] == hdr
        assert wire.unpack_blobs(dec[1]) == [b"one", b"", b"three"]

    def test_malformed_frames_counted_not_raised(self):
        tracer = set_tracer(Tracer(enabled=True))
        try:
            assert wire.decode_frame(b"garbage") is None
            assert wire.decode_frame(b"CFR1\xff\xff\xff\xff") is None
            bad_kind = wire.encode_frame({"kind": "nope"}, b"")
            assert wire.decode_frame(bad_kind) is None
            assert wire.unpack_blobs(b"\x02\x00\x00\x00") is None
            assert tracer.counters()["fleet.frames_malformed"] == 4
        finally:
            set_tracer(Tracer(enabled=False))

    def test_fabric_drops_malformed_without_counting_codec(self):
        fab = MemFabric()

        class _Sink:
            def __init__(self):
                self.got = []

            def handle(self, src, data):
                self.got.append(data)

        node = FleetNode("a", MEMBERS, fab, beacon_every=0,
                         server_kw=dict(SERVER_KW))
        fab.send("b", "a", b"not a frame")
        assert node.drain_inbox() == 1  # delivered, decode refused
        assert node.server._docs == {}
