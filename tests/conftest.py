"""Test config: force an 8-device virtual CPU mesh before JAX import.

Real hardware in CI is a single TPU chip; multi-chip sharding paths are
validated on a virtual host-platform mesh instead (see SURVEY.md §7 and
the driver's dryrun_multichip contract).
"""

import os

# forced, not setdefault: CI shells export JAX_PLATFORMS for the real
# TPU tunnel, which would put the suite on the 1-chip device and break
# every 8-device mesh test
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# the image's sitecustomize imports jax at interpreter startup (TPU
# plugin registration), so the env vars above are already baked into
# jax.config — override the lazy-read config value too; backends have
# not initialized yet at conftest time, so this still takes effect
if "jax" in __import__("sys").modules:
    import jax

    jax.config.update("jax_platforms", "cpu")

import random

import numpy as np
import pytest


def pytest_configure(config):
    # persistent XLA compilation cache: kernel tests compile each shape
    # bucket once per machine instead of once per run. Per-user,
    # ownership-verified, and SEPARATE from the TPU processes' cache
    # (a CPU backend must never load AOT artifacts cached under
    # another flag configuration — SIGILL risk; see ops/device.py)
    import jax

    from crdt_tpu.ops.device import _safe_cache_dir

    path = _safe_cache_dir(suffix="_cpu_tests")
    if path:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5
        )
    # tests drive the jitted kernels directly with packed int64 ids
    jax.config.update("jax_enable_x64", True)


@pytest.fixture(autouse=True)
def _seed_rngs():
    random.seed(0)
    np.random.seed(0)
