"""Test config: force an 8-device virtual CPU mesh before JAX import.

Real hardware in CI is a single TPU chip; multi-chip sharding paths are
validated on a virtual host-platform mesh instead (see SURVEY.md §7 and
the driver's dryrun_multichip contract).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import random

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_rngs():
    random.seed(0)
    np.random.seed(0)
