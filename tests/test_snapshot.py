"""Round 21: device-layout snapshots — corruption fuzz + ALICE matrix.

Three contract families over ``crdt_tpu.storage.snapshot``:

- **rejects fail closed** — seeded truncation / bit-flip / splice /
  header mutants over a REAL snapshot must raise ``ValueError`` only
  (never a hang, never another exception type) and leave zero
  partial state; the store-level ladder then recovers via WAL replay
  to a byte-identical digest (the ``test_codec_fuzz.py`` discipline
  applied to the snapshot wire);
- **crash-proof writes** — the ALICE matrix: a simulated kill at
  EVERY fs op of the snapshot writer's write/rename/delete sequence
  (plus torn writes), after which a reopen serves a byte-identical
  doc with zero acked-update loss (acked updates live in the WAL;
  the snapshot writer never touches it);
- **byte-identical restore** — engine -> snapshot -> rehydrate
  round-trips digest- and state-blob-identically, stays identical
  under subsequent deltas, and the server-level checkpoint/restore
  round-trips the whole resident set.
"""

import os
import random

import pytest

from crdt_tpu.codec import v1
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.guard.faults import (
    DiskFaultSchedule,
    FaultyFs,
    SimulatedCrash,
)
from crdt_tpu.models.incremental import IncrementalReplay
from crdt_tpu.models.multidoc import MultiDocServer, cache_digest
from crdt_tpu.models.replay import cold_start, replay_trace
from crdt_tpu.obs import Tracer, get_tracer, set_tracer
from crdt_tpu.storage import snapshot as sn
from crdt_tpu.storage.persistence import LogPersistence


@pytest.fixture
def tracer():
    t = set_tracer(Tracer(enabled=True))
    yield t
    set_tracer(Tracer(enabled=False))


class Stream:
    """SV-admissible incremental doc generator: map sets chaining per
    key, a YATA list chain with mid-inserts, occasional deletes —
    the union of wire shapes a resident engine holds."""

    def __init__(self, seed, n_clients=2):
        self.seed = seed
        self.clients = [10 + c for c in range(n_clients)]
        self.clock = {c: 0 for c in self.clients}
        self.chain: list = []
        self.map_tail: dict = {}

    def delta(self, k_ops, *, deletes=False) -> bytes:
        recs = []
        ds = DeleteSet()
        for i in range(k_ops):
            c = self.clients[i % len(self.clients)]
            k = self.clock[c]
            self.clock[c] = k + 1
            if i % 3 == 0:
                key = f"k{(self.seed + i) % 5}"
                recs.append(ItemRecord(
                    client=c, clock=k, parent_root="m", key=key,
                    origin=self.map_tail.get(key),
                    content=self.seed * 1000 + k,
                ))
                self.map_tail[key] = (c, k)
            elif len(self.chain) > 2 and i % 3 == 2:
                j = len(self.chain) // 2
                recs.append(ItemRecord(
                    client=c, clock=k, parent_root="l",
                    origin=self.chain[j - 1], right=self.chain[j],
                    content=self.seed * 1000 + k,
                ))
                self.chain.insert(j, (c, k))
            else:
                recs.append(ItemRecord(
                    client=c, clock=k, parent_root="l",
                    origin=self.chain[-1] if self.chain else None,
                    content=self.seed + k,
                ))
                self.chain.append((c, k))
        if deletes and len(self.chain) > 4:
            dc, dk = self.chain[1]
            ds.add(dc, dk, 1)
        return v1.encode_update(recs, ds)


def _engine(n_deltas=30, k=8, seed=1):
    s = Stream(seed)
    blobs = [s.delta(k, deletes=(i % 7 == 6)) for i in range(n_deltas)]
    eng = IncrementalReplay()
    eng.apply(blobs)
    assert not eng._pending and not eng._rootless
    return eng, blobs, s


# ---------------------------------------------------------------------------
# round-trip
# ---------------------------------------------------------------------------


class TestRoundTrip:
    def test_rehydrate_byte_identical_and_stays_identical(self):
        eng, blobs, s = _engine()
        payload = sn.encode_engine(eng, seq=17)
        snap = sn.decode_payload(payload)
        assert snap.seq == 17
        assert snap.n == eng.cols.n
        eng2 = sn.rehydrate(snap)
        assert cache_digest(eng2.cache) == cache_digest(eng.cache)
        assert eng2.encode_state_as_update() == \
            eng.encode_state_as_update()
        # the rehydrated engine must keep converging identically
        tail = [s.delta(8, deletes=(i == 2)) for i in range(6)]
        ref = IncrementalReplay()
        ref.apply(blobs + tail)
        eng.apply(tail)
        eng2.apply(tail)
        assert cache_digest(eng.cache) == cache_digest(ref.cache)
        assert cache_digest(eng2.cache) == cache_digest(ref.cache)
        assert eng2.encode_state_as_update() == \
            ref.encode_state_as_update()

    def test_deterministic_encode(self):
        eng, _, _ = _engine(n_deltas=10)
        assert sn.encode_engine(eng, seq=3) == \
            sn.encode_engine(eng, seq=3)

    def test_refuses_unsettled_engine(self):
        eng = IncrementalReplay()
        # a gapped clock stashes as pending
        eng.apply([v1.encode_update([ItemRecord(
            client=5, clock=9, parent_root="m", key="k",
            content=1)], DeleteSet())])
        assert eng._pending
        with pytest.raises(ValueError):
            sn.encode_engine(eng)


# ---------------------------------------------------------------------------
# corruption fuzz (rejects fail closed; ladder recovers)
# ---------------------------------------------------------------------------


def _mutants(payload, rng, n=220):
    """Seeded truncation / bit-flip / splice / header mutants."""
    hdr = len(sn.MAGIC) + 4
    for _ in range(n):
        b = bytearray(payload)
        op = rng.randrange(4)
        if op == 0 and len(b) > 1:  # truncation
            yield bytes(b[: rng.randrange(1, len(b))])
        elif op == 1:  # bit flips anywhere
            for _ in range(rng.randrange(1, 4)):
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            yield bytes(b)
        elif op == 2:  # splice with self at random offsets
            cut = rng.randrange(1, len(b) + 1)
            yield bytes(b[:cut]) + payload[rng.randrange(len(payload)):]
        else:  # header-targeted flips (magic/len/table region)
            lo = rng.randrange(0, hdr + 64)
            b[min(lo, len(b) - 1)] ^= 0xFF
            yield bytes(b)


class TestCorruptionFuzz:
    def test_mutants_reject_value_error_only(self):
        eng, _, _ = _engine(n_deltas=12)
        payload = sn.encode_engine(eng, seq=1)
        rng = random.Random(20260806)
        rejected = survived = 0
        for m in _mutants(payload, rng):
            try:
                snap = sn.decode_payload(m)
            except ValueError:
                rejected += 1
                continue
            except Exception as exc:  # noqa: BLE001 - the contract
                pytest.fail(f"non-ValueError escape: {exc!r}")
            # a mutant that still parses must rehydrate or reject
            # cleanly — never crash the promotion seam
            survived += 1
            try:
                eng2 = sn.rehydrate(snap)
                eng2.cache
            except ValueError:
                pass
        assert rejected > 100  # the corpus really exercised rejects

    def test_targeted_header_mutants(self):
        eng, _, _ = _engine(n_deltas=8)
        payload = sn.encode_engine(eng, seq=1)
        with pytest.raises(ValueError, match="magic"):
            sn.decode_payload(b"NOTASNAP" + payload[8:])
        with pytest.raises(ValueError, match="truncated"):
            sn.decode_payload(payload[: len(payload) // 2])
        with pytest.raises(ValueError, match="truncated"):
            sn.decode_payload(payload[:10])
        # header crc catches a table flip
        b = bytearray(payload)
        b[len(sn.MAGIC) + 6] ^= 0x01
        with pytest.raises(ValueError):
            sn.decode_payload(bytes(b))
        # payload crc catches a tail flip
        b = bytearray(payload)
        b[-3] ^= 0x10
        with pytest.raises(ValueError, match="crc|digest"):
            sn.decode_payload(bytes(b))

    def test_store_ladder_falls_back_to_wal_byte_identical(
            self, tmp_path, tracer):
        lp = LogPersistence(str(tmp_path / "s.kvlog"))
        store = sn.SnapshotStore(str(tmp_path / "snaps"))
        s = Stream(3)
        for i in range(20):
            lp.store_update("d", s.delta(6, deletes=(i % 9 == 8)))
        eng = IncrementalReplay()
        eng.apply(lp.get_all_updates("d"))
        assert sn.compact_with_snapshot(lp, "d", eng, store)
        for _ in range(4):
            lp.store_update("d", s.delta(6))
        ref = IncrementalReplay()
        ref.apply(lp.get_all_updates("d"))
        ref_blob = ref.encode_state_as_update()

        fast, path = cold_start("d", lp, store)
        assert path == "snapshot"
        assert fast.encode_state_as_update() == ref_blob

        # every mutant of the on-disk file recovers via WAL replay
        snap_files = [n for n in os.listdir(str(tmp_path / "snaps"))
                      if n.endswith(".snap")]
        assert len(snap_files) == 1
        p = os.path.join(str(tmp_path / "snaps"), snap_files[0])
        pristine = open(p, "rb").read()
        rng = random.Random(77)
        fb0 = tracer.counters().get("snap.fallbacks{reason=\"crc\"}", 0)
        for m in list(_mutants(pristine, rng, n=24)):
            with open(p, "wb") as f:
                f.write(m)
            eng2, _ = cold_start("d", lp, store)
            assert eng2.encode_state_as_update() == ref_blob
        with open(p, "wb") as f:
            f.write(pristine)
        counters = tracer.counters()
        assert sum(v for k, v in counters.items()
                   if k.startswith("snap.fallbacks")) > 0, counters
        assert fb0 == 0
        lp.close()

    def test_tmp_leftover_of_torn_rename_is_ignored(self, tmp_path):
        eng, _, _ = _engine(n_deltas=8)
        store = sn.SnapshotStore(str(tmp_path))
        payload = sn.encode_engine(eng, seq=2)
        assert store.write("d", payload, 2)
        # a torn rename leaves the NEXT generation as .tmp only
        with open(str(tmp_path / ("d-%020d.snap.tmp" % 3)), "wb") as f:
            f.write(b"half a snapshot")
        snap, seq = store.load_latest("d")
        assert seq == 2
        assert snap.n == eng.cols.n


# ---------------------------------------------------------------------------
# the ALICE crash-point matrix over the snapshot writer
# ---------------------------------------------------------------------------


class TestSnapshotAliceMatrix:
    """Kill the snapshot writer at EVERY fs op of a two-generation
    write workload (crash-before-op at each index, torn variant at
    each write op). After every kill: the store reopens to a valid
    old/new generation or none, cold start converges byte-identical
    to pure WAL replay, and no acked update is lost."""

    def _workload(self, root, fs):
        """Two generations + a checkpoint sidecar through one fs."""
        store = sn.SnapshotStore(root, fs=fs)
        eng1, blobs1, s = _engine(n_deltas=10, seed=5)
        store.write("d", sn.encode_engine(eng1, seq=10), 10)
        tail = [s.delta(8) for _ in range(4)]
        eng2 = IncrementalReplay()
        eng2.apply(blobs1 + tail)
        store.write("d", sn.encode_engine(eng2, seq=14), 14)
        return blobs1 + tail

    def test_matrix(self, tmp_path, tracer):
        # clean run enumerates the op sequence (the matrix axis)
        clean_fs = FaultyFs(sn.Fs(), DiskFaultSchedule())
        blobs = self._workload(str(tmp_path / "clean"), clean_fs)
        n_ops = len(clean_fs.ops)
        assert n_ops >= 10  # 2 generations x (write..fsync_dir) + unlink
        assert ("unlink", ) [0] in [v for v, _ in clean_fs.ops][0:0] \
            or any(v == "unlink" for v, _ in clean_fs.ops)

        ref = IncrementalReplay()
        ref.apply(blobs)
        ref_blob = ref.encode_state_as_update()
        ref_digest = cache_digest(ref.cache)

        scenarios = [("crash", i) for i in range(n_ops)]
        write_ops = [i for i, (verb, _) in enumerate(clean_fs.ops)
                     if verb == "write"]
        scenarios += [("torn", i) for i in write_ops]

        for kind, i in scenarios:
            root = str(tmp_path / f"{kind}_{i}")
            lp = LogPersistence(os.path.join(root, "wal.kvlog"))
            for b in blobs:
                lp.store_update("d", b)  # every update is acked
            if kind == "crash":
                sched = DiskFaultSchedule(crash_at=(i, 0))
            else:
                sched = DiskFaultSchedule(fail_writes=(), torn=0.0)
                sched.fail_writes = set()
                sched.crash_at = None
                # deterministic torn at exactly op i
                sched.decide = (  # type: ignore[method-assign]
                    lambda n, _i=i: "torn" if n == _i else None)
            fs = FaultyFs(sn.Fs(), sched)
            try:
                self._workload(root, fs)
            except SimulatedCrash:
                pass
            except OSError:
                pass  # torn write surfaces as EIO; writer degraded
            # reopen: a fresh store over whatever the crash left
            eng, path = cold_start(
                "d", lp, sn.SnapshotStore(root))
            assert eng.encode_state_as_update() == ref_blob, (kind, i)
            assert cache_digest(eng.cache) == ref_digest, (kind, i)
            assert not eng._pending and not eng._rootless, (kind, i)
            lp.close()

    def test_enospc_eio_degrade_keep_serving(self, tmp_path, tracer):
        """Disk faults at the snapshot seam degrade (write refused,
        counted, serving continues from WAL) and heal on retry."""
        eng, blobs, _ = _engine(n_deltas=8)
        lp = LogPersistence(str(tmp_path / "wal.kvlog"))
        for b in blobs:
            lp.store_update("d", b)
        sched = DiskFaultSchedule(fail_writes={0},
                                  fail_errno=__import__("errno").ENOSPC)
        fs = FaultyFs(sn.Fs(), sched)
        store = sn.SnapshotStore(str(tmp_path / "snaps"), fs=fs)
        payload = sn.encode_engine(eng, seq=len(blobs))
        assert store.write("d", payload, len(blobs)) is False
        c = tracer.counters()
        assert c.get('snap.write_errors{reason="io"}', 0) == 1
        # WAL serving unaffected
        eng2, path = cold_start("d", lp, store)
        assert path == "wal"
        assert eng2.encode_state_as_update() == \
            eng.encode_state_as_update()
        # the disk healed: the retried write lands and loads
        assert store.write("d", payload, len(blobs)) is True
        eng3, path = cold_start("d", lp, store)
        assert path == "snapshot"
        assert eng3.encode_state_as_update() == \
            eng.encode_state_as_update()
        lp.close()

    def test_store_budget_refuses_politely(self, tmp_path, tracer):
        eng, blobs, _ = _engine(n_deltas=8)
        payload = sn.encode_engine(eng, seq=1)
        store = sn.SnapshotStore(
            str(tmp_path), max_bytes=len(payload) // 2)
        assert store.write("d", payload, 1) is False
        c = tracer.counters()
        assert c.get('snap.write_errors{reason="budget"}', 0) == 1
        assert store.load_latest("d") is None

    def test_rider_crash_between_snapshot_and_compact(
            self, tmp_path, tracer):
        """The rider's ordering contract: the snapshot lands at the
        seq the WAL compaction will use BEFORE old keys die, so a
        kill in the window leaves snapshot + full old WAL — and the
        tail query returns nothing stale."""
        lp = LogPersistence(str(tmp_path / "wal.kvlog"))
        s = Stream(9)
        for _ in range(12):
            lp.store_update("d", s.delta(6))
        eng = IncrementalReplay()
        eng.apply(lp.get_all_updates("d"))
        ref_blob = eng.encode_state_as_update()
        store = sn.SnapshotStore(str(tmp_path / "snaps"))
        # crash the WAL compact (write index 0 of the WAL kv is the
        # compact batch after 12 appends? no — kill via kv seam is
        # round 10's matrix; here simulate by snapshotting WITHOUT
        # compacting: the window state is snapshot + full old WAL)
        seq = lp._seq_for("d")
        lp._next_seq["d"] = seq
        assert store.write("d", sn.encode_engine(eng, seq=seq), seq)
        # reopen in the window: snapshot covers the whole WAL, the
        # tail (seq strictly greater) is empty, digest identical
        assert lp.get_updates_since("d", seq) == []
        eng2, path = cold_start("d", lp, store)
        assert path == "snapshot"
        assert eng2.encode_state_as_update() == ref_blob
        # now the compact completes; still identical, and appends
        # after it are served as tail
        sn.compact_with_snapshot(lp, "d", eng, store)
        for _ in range(3):
            lp.store_update("d", s.delta(6))
        ref2 = IncrementalReplay()
        ref2.apply(lp.get_all_updates("d"))
        eng3, path = cold_start("d", lp, store)
        assert path == "snapshot"
        assert eng3.encode_state_as_update() == \
            ref2.encode_state_as_update()
        lp.close()


# ---------------------------------------------------------------------------
# server-level seams (eviction tax + checkpoint/restore)
# ---------------------------------------------------------------------------


class TestServerSeams:
    def _warm_server(self, store, n_docs=3, rounds=4):
        srv = MultiDocServer(snap_store=store)
        streams = {f"doc{i}": Stream(i) for i in range(n_docs)}
        for _ in range(rounds):
            for d, s in streams.items():
                srv.submit_many(d, [s.delta(6) for _ in range(3)])
            srv.tick()
        return srv, streams

    def test_eviction_writes_snapshot_and_rehydrates(
            self, tmp_path, tracer):
        """The round-15 eviction-flood pin extended: an evicted doc
        leaves a snapshot behind, and its resubmit re-promotes by
        rehydrating + applying only the tail — byte-identical to the
        full-history oracle."""
        store = sn.SnapshotStore(str(tmp_path))
        srv, streams = self._warm_server(store)
        warm = [d for d, st in srv._docs.items()
                if st.resident is not None]
        assert warm
        victim = warm[0]
        srv._evict_resident(victim)
        c = tracer.counters()
        assert c.get("snap.evict_writes", 0) == 1
        assert c.get("snap.writes", 0) >= 1
        loads0 = c.get("snap.loads", 0)
        # resubmit: the next promotion must load, not rebuild
        for _ in range(2):
            srv.submit_many(
                victim, [streams[victim].delta(6) for _ in range(3)])
            srv.tick()
        st = srv._docs[victim]
        assert st.resident is not None
        assert tracer.counters().get("snap.loads", 0) > loads0
        oracle = replay_trace(st.blobs)
        assert cache_digest(srv._cache_of(st)) == \
            cache_digest(oracle.cache)

    def test_checkpoint_restore_whole_resident_set(
            self, tmp_path, tracer):
        store = sn.SnapshotStore(str(tmp_path))
        srv, streams = self._warm_server(store, n_docs=4)
        n = srv.checkpoint()
        assert n == len([d for d, st in srv._docs.items()
                         if st.resident is not None])
        assert tracer.counters().get("tenant.checkpoint_docs") == n

        srv2 = MultiDocServer(snap_store=store)
        warm = srv2.restore()
        assert warm == n
        for d in srv._docs:
            assert cache_digest(srv2._cache_of(srv2._docs[d])) == \
                cache_digest(srv._cache_of(srv._docs[d])), d
        # the restored set keeps serving identically
        for d, s in streams.items():
            blob = s.delta(6)
            srv.submit(d, blob)
            srv2.submit(d, blob)
        srv.tick()
        srv2.tick()
        for d in srv._docs:
            assert cache_digest(srv2._cache_of(srv2._docs[d])) == \
                cache_digest(srv._cache_of(srv._docs[d])), d

    def test_restore_with_damaged_snapshot_serves_cold(
            self, tmp_path, tracer):
        store = sn.SnapshotStore(str(tmp_path))
        srv, _ = self._warm_server(store, n_docs=2)
        assert srv.checkpoint() >= 1
        # damage every snapshot generation; sidecars stay
        for name in os.listdir(str(tmp_path)):
            if name.endswith(".snap"):
                p = os.path.join(str(tmp_path), name)
                b = bytearray(open(p, "rb").read())
                b[len(b) // 2] ^= 0xFF
                with open(p, "wb") as f:
                    f.write(bytes(b))
        srv2 = MultiDocServer(snap_store=store)
        assert srv2.restore() == 0  # nothing warm...
        for d in srv._docs:  # ...but every doc's history survived
            assert cache_digest(replay_trace(
                srv2._docs[d].blobs).cache) == \
                cache_digest(srv._cache_of(srv._docs[d])), d
        # and serving from the cold rung converges identically
        streams = {}
        for d in srv._docs:
            s = Stream(int(d[3:]) + 50)
            s.clients = [90, 91]  # fresh writers, clocks from 0
            s.clock = {c: 0 for c in s.clients}
            streams[d] = s
        for d, s in streams.items():
            blob = s.delta(6)
            srv.submit(d, blob)
            srv2.submit(d, blob)
        srv.tick()
        srv2.tick()
        for d in srv._docs:
            assert cache_digest(srv2._cache_of(srv2._docs[d])) == \
                cache_digest(srv._cache_of(srv._docs[d])), d
