"""Tier-1 guard over the bench pipeline accounting.

``bench.py --smoke`` replays a tiny trace through all three contenders
(numpy baseline, one-shot device pipeline, streaming executor) on the
CPU backend, asserts equality, and prints one JSON line with the
per-phase + overlap accounting. Running it here catches accounting
regressions — a phase silently re-serializing, a lane dropping out of
the busy sum, the streamed path diverging — without a full scale run.
"""

import json
import os
import subprocess
import sys


def test_bench_smoke_mode():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial a tunnel
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--smoke"],
        env=env, capture_output=True, text=True, timeout=240, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["ok"] is True
    assert out["platform"] == "cpu"
    ph = out["stream_phases_s"]
    # every pipeline lane accounted, overlap metrics present
    for key in ("decode", "converge", "materialize", "busy_sum_s",
                "wall_s", "wall_vs_phases", "overlap_efficiency"):
        assert key in ph, key
    assert ph["busy_sum_s"] > 0
    assert 0.0 <= ph["overlap_efficiency"] <= 1.0
    # the serial contenders' phase dicts stay r05-shaped
    for key in ("decode", "pack", "converge", "materialize", "compact"):
        assert key in out["phases_device_s"], key
