"""Tier-1 guard over the bench pipeline accounting + observability.

``bench.py --smoke`` replays a tiny trace through all three contenders
(numpy baseline, one-shot device pipeline, streaming executor) on the
CPU backend, asserts equality, and prints one JSON line with the
per-phase + overlap accounting. Running it here catches accounting
regressions — a phase silently re-serializing, a lane dropping out of
the busy sum, the streamed path diverging — without a full scale run.

The observability half: the smoke runs with tracing enabled and
writes a BENCH_OUT-shaped artifact embedding the full tracer report,
and this test asserts the DOCUMENTED hot-path spans (README
"Observability" registry) are present with real p50/p99 data — so the
instrumentation cannot silently rot out of the hot path.
"""

import json
import os
import pathlib
import subprocess
import sys

import numpy as np

# the hot-path span registry tier-1 pins (README "Observability"):
# any rename or dropped hook fails here, not in a future postmortem
HOT_PATH_SPANS = (
    "decode", "pack", "converge.dispatch", "converge.fetch",
    "gather", "materialize", "compact", "persist", "persist.compact",
)


def test_bench_smoke_mode(tmp_path):
    # CI points BENCH_SMOKE_ARTIFACT at the workspace so THIS run's
    # obs snapshot uploads as the workflow artifact — the smoke is
    # expensive enough that CI must not run it a second time just to
    # place the file somewhere known
    art = (pathlib.Path(os.environ["BENCH_SMOKE_ARTIFACT"])
           if os.environ.get("BENCH_SMOKE_ARTIFACT")
           else tmp_path / "smoke_bench_out.json")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial a tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SMOKE_OUT"] = str(art)
    env["BENCH_TRACE"] = "1"  # pin: an exported BENCH_TRACE=0 must
    #                           not turn this into a confusing failure
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--smoke"],
        env=env, capture_output=True, text=True, timeout=240, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["ok"] is True
    assert out["platform"] == "cpu"
    ph = out["stream_phases_s"]
    # every pipeline lane accounted, overlap metrics present
    for key in ("decode", "converge", "materialize", "busy_sum_s",
                "wall_s", "wall_vs_phases", "overlap_efficiency"):
        assert key in ph, key
    assert ph["busy_sum_s"] > 0
    assert 0.0 <= ph["overlap_efficiency"] <= 1.0
    # the serial contenders' phase dicts stay r05-shaped
    for key in ("decode", "pack", "converge", "materialize", "compact"):
        assert key in out["phases_device_s"], key

    # the BENCH_OUT-shaped artifact embeds a non-empty tracer report
    # with the documented hot-path spans (p50/p99 per span)
    assert out.get("tracer_spans_ok") is True
    full = json.loads(art.read_text())
    report = full["tracer"]
    assert report["spans"], "embedded tracer report is empty"
    for name in HOT_PATH_SPANS:
        span = report["spans"].get(name)
        assert span is not None, f"hot-path span {name!r} missing"
        assert span["count"] > 0
        for k in ("p50_s", "p90_s", "p99_s", "max_s", "total_s"):
            assert k in span, (name, k)
        assert span["p50_s"] <= span["p99_s"] + 1e-12
        assert span["p99_s"] <= span["max_s"] + 1e-12

    # the byte-accounting registry (transfer diet): counters, latency
    # histograms, and the narrowing gauge must all be live, or the
    # xfer regression gate reads nothing and the diet can rot
    for cname in ("xfer.h2d_bytes", "xfer.h2d_puts", "xfer.d2h_bytes",
                  "xfer.d2h_fetches"):
        assert report["counters"].get(cname, 0) > 0, cname
    for sname in ("xfer.h2d", "xfer.d2h"):
        span = report["spans"].get(sname)
        assert span is not None and span["count"] > 0, sname
    assert "xfer.narrowed_ratio" in report["gauges"]
    # per-column chosen widths recorded (the width histogram)
    assert any(k.startswith("xfer.col_width{") for k in
               report["counters"]), "per-column width histogram missing"
    # the smoke device leg's own xfer digest rides the stdout line
    assert out["xfer"]["h2d_bytes"] > 0
    assert out["xfer"]["d2h_bytes"] > 0

    # the round-12 kernel-dispatch registry (sort diet): every fused
    # converge counts its static kernel-mode decision
    # (converge.pallas{mode=...}), so the ablation evidence and the
    # metrics_diff gates always have data to read
    assert out.get("kernel_registry_ok") is True
    assert any(k.startswith('converge.pallas{mode=')
               for k in report["counters"]), \
        "converge.pallas mode counter missing from tracer report"

    # the round-13 sharded-converge registry: the smoke runs a 2-way
    # sharded converge on its forced 2-device mesh, byte-identical to
    # the single-chip leg, and the shard.* evidence the multichip
    # regression gate reads must be live
    assert out.get("shard_registry_ok") is True
    for cname in ("shard.dispatches", "shard.boundary_bytes"):
        assert report["counters"].get(cname, 0) > 0, cname
    assert "shard.shards" in report["gauges"]
    assert "converge.wyllie_rounds" in report["gauges"]

    # the round-23 subtree-split registry: the smoke replays a small
    # branching-tree + deep-map-chain doc at a tiny width — a shape
    # the round-13 chain split refused outright — byte-identical to
    # the split-disabled plan (asserted inside the leg, which also
    # requires the cut counts to fire), and the gauges the --conflict
    # regression gate reads stay in the registry (the final report
    # carries the LAST staging's values, so only presence pins here;
    # the flag rides the artifact — the stdout line's 1500-byte
    # budget drops it, like phases_numpy_s)
    assert full.get("subtree_split_ok") is True
    assert "converge.subtree_cuts" in report["gauges"]
    assert "converge.map_chain_cuts" in report["gauges"]
    assert "converge.map_rounds" in report["gauges"]

    # the round-14 multi-tenant registry: the smoke runs a tiny
    # mixed-tenant batch through MultiDocServer, digest-identical to
    # the per-doc baseline, and publishes the gated keys + tenant.*
    # counters the multitenant regression gate reads
    assert out.get("multitenant_registry_ok") is True
    mt = out["multitenant"]
    for key in ("docs_converged_per_s", "p99_per_doc_ms",
                "dispatches_per_tick", "speedup"):
        assert isinstance(mt.get(key), (int, float)), key
    assert mt["oracle_identical"] is True
    for cname in ("converge.docs_packed", "tenant.submitted",
                  "tenant.docs_converged", "tenant.shed",
                  "tenant.shed_bytes"):
        assert report["counters"].get(cname, 0) > 0, cname
    assert "tenant.pending_bytes" in report["gauges"]
    assert "tenant.dispatch_docs" in report["gauges"]

    # the round-15 delta-tick registry: the smoke runs a tiny
    # steady-state leg (small deltas on resident docs + a rolling
    # eviction flood) digest-identical to the full-replay oracle,
    # and the delta-route / resident-ledger / digest-skip evidence
    # the steady regression gates read must be live
    assert out.get("mt_incremental_registry_ok") is True
    mts = out["multitenant"]["steady"]
    for key in ("docs_per_s", "speedup", "delta_docs_per_tick"):
        assert isinstance(mts.get(key), (int, float)), key
    assert mts["oracle_identical"] is True
    for cname in ("tenant.delta_docs", "tenant.delta_rows",
                  "tenant.promotions", "tenant.resident_evictions",
                  "sentinel.doc_digest_skips"):
        assert report["counters"].get(cname, 0) > 0, cname
    assert "tenant.resident_bytes" in report["gauges"]
    assert "tenant.resident_docs" in report["gauges"]

    # the round-20 pooled-resident registry: the smoke runs a tiny
    # all-warm device-forced leg — every doc's device round batches
    # into ONE pooled dispatch per tick (the gated dispatch floor),
    # byte-identical to the unpooled route, with the tenant.pool_*
    # evidence live. The gated keys ride the ARTIFACT (the stdout
    # line's 1500-byte budget drops them, like phases_numpy_s)
    assert out.get("mt_pooled_registry_ok") is True
    fsteady = full["multitenant"]["steady"]
    for key in ("device_dispatches_per_tick", "pool_peak_bytes"):
        assert isinstance(fsteady.get(key), (int, float)), key
    assert fsteady["device_dispatches_per_tick"] <= 2
    assert report["counters"].get("tenant.pool_dispatches", 0) > 0
    assert "tenant.pool_bytes" in report["gauges"]
    assert "tenant.pool_docs" in report["gauges"]

    # the round-21 snapshot registry: the smoke runs a tiny coldstart
    # leg (snapshot join digest-identical to WAL replay, corruption
    # falls back counted, server checkpoint/restore round-trips) with
    # the snap.* write/load/fallback evidence live
    assert out.get("snap_registry_ok") is True
    for cname in ("snap.writes", "snap.loads", "snap.bytes",
                  "tenant.checkpoint_docs"):
        assert report["counters"].get(cname, 0) > 0, cname
    assert any(k.startswith("snap.fallbacks{")
               for k in report["counters"]), "snap.fallbacks missing"
    assert "snap.write_ms" in report["gauges"]
    assert "snap.load_ms" in report["gauges"]

    # the round-18 observability-v2 registries: the SLO ledger lit
    # breaches/burn-rate/route-mix (the chaos flood leg runs with
    # slo_ms=0 and shed==breach is asserted inside the leg), the
    # tick timeline recorded the multitenant ticks with live
    # overlap/stall gauges and a schema-valid Perfetto export, and
    # the disabled-tracer span cost stays pinned (obs is free when
    # off — the measured bound is generous for CI boxes)
    assert out.get("slo_registry_ok") is True
    assert out.get("timeline_registry_ok") is True
    assert report["counters"].get("slo.breaches", 0) > 0
    assert "slo.burn_rate" in report["gauges"]
    assert any(k.startswith("slo.route_shed{")
               for k in report["counters"]), "route mix missing"
    for sname in ("slo.ingest_to_converged", "slo.ingest_to_served"):
        span = report["spans"].get(sname)
        assert span is not None and span["count"] > 0, sname
    assert report["counters"].get("timeline.ticks", 0) > 0
    assert "timeline.overlap_efficiency" in report["gauges"]
    assert "timeline.stall_ms" in report["gauges"]
    assert isinstance(out.get("obs_disabled_span_ns"), (int, float))
    assert out["obs_disabled_span_ns"] < 5000
    assert out["multitenant"]["steady"]["slo_ms"] > 0

    # the round-19 distributed-tracing registries: the traced
    # loopback swarm lit the per-route hop-lag histograms, the
    # birth-to-visibility span, the context byte/overhead accounting
    # (with a hostile context counted, not fatal), and the
    # self-scrape collector leg federated this process with full
    # path reconstruction
    assert out.get("propagation_registry_ok") is True
    assert out.get("collector_registry_ok") is True
    for cname in ("propagation.contexts_sent",
                  "propagation.contexts_received",
                  "propagation.context_bytes",
                  "propagation.traced_update_bytes",
                  "propagation.malformed_contexts",
                  "collector.scrapes"):
        assert report["counters"].get(cname, 0) > 0, cname
    assert "propagation.wire_overhead_ratio" in report["gauges"]
    assert report["gauges"].get("collector.procs") == 1
    assert report["gauges"].get("collector.pair_rate") == 1.0
    for sname in ('replica.hop_lag{route="direct"}',
                  'replica.hop_lag{route="sync_answer"}',
                  'replica.hop_lag{route="anti_entropy"}',
                  "replica.birth_to_visibility"):
        span = report["spans"].get(sname)
        assert span is not None and span["count"] > 0, sname

    # the round-22 control-plane registry: the smoke drives a
    # deterministic synthetic squeeze/skip/restore schedule through
    # a Controller (ledger bounded with drop accounting, replay
    # byte-identical) plus a tiny cadence-checkpoint server leg, so
    # every control.* counter/gauge the regression gates read is
    # live, and the decision ledger artifact uploads from CI
    assert out.get("control_registry_ok") is True
    for cname in ("control.decisions", "control.cooldown_skips",
                  "control.ledger_dropped", "snap.cadence_writes"):
        assert report["counters"].get(cname, 0) > 0, cname
    for cname in ('control.decisions{rule="budget_squeeze"}',
                  'control.decisions{rule="budget_restore"}'):
        assert report["counters"].get(cname, 0) > 0, cname
    assert any(k.startswith("control.setpoint{knob=")
               for k in report["gauges"]), "setpoint gauges missing"

    # the guard-layer registry (README "Overload & failure policy"):
    # (kernel_ablation_leg is pinned in-process below — the smoke
    # subprocess stays on its <30s budget)
    # each degradation ladder fired once in the smoke and its
    # counters are live, so the robustness regression gate
    # (tools/metrics_diff.py GUARD_PREFIXES) always has data to read
    assert out.get("guard_registry_ok") is True
    for cname in ("guard.inbox_shed", "guard.inbox_shed_bytes",
                  "engine.pending_evictions", "persist.retries",
                  "persist.degraded_writes", "persist.recovered_updates",
                  "device.retries", "device.fallback"):
        assert report["counters"].get(cname, 0) > 0, cname
    # degraded flipped on AND recovered during the leg
    assert report["gauges"].get("persist.degraded") == 0


def test_kernel_ablation_leg_shape():
    """The round-12 per-primitive ablation rig (bench.kernel_ablation_
    leg) must keep producing the gated keys — sort_ms / map_winners_ms
    / rank_ms with both paths, and the sort_map_speedup acceptance
    number — on a tiny trace, so the evidence pipeline can't rot
    between full bench runs."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    import bench
    from crdt_tpu.compat import enable_x64

    blobs = bench.build_trace(30, 20)
    dec = bench.decode_stage(blobs)
    cols, _ = bench.column_stage(dec)

    def b2b(fn, reps=2, outer=1):
        import jax
        import time

        jax.block_until_ready(fn())
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps * 1e3

    with enable_x64(True):
        out = bench.kernel_ablation_leg(cols, b2b, 0.0)
    for prim in ("sort_ms", "map_winners_ms", "rank_ms"):
        assert set(out[prim]) == {"jnp", "pallas"}, prim
        assert out[prim]["jnp"] > 0 and out[prim]["pallas"] > 0
    assert out["sort_map_speedup"] > 0
    assert out["shape"] == int(np.count_nonzero(cols["valid"]))
    assert out["mode"] in ("pallas", "interpret", "jnp")
