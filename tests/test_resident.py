"""Resident device state: HBM-resident union + incremental rebuilds.

VERDICT r1 item #8: converge over new ops + resident state instead of
re-uploading the full union per dispatch, and keep the product path's
per-update host work proportional to the touched parents.
"""

import numpy as np

from crdt_tpu.ops.resident import ResidentColumns


def _map_cols(client, clocks, parents, keys):
    n = len(clocks)
    return {
        "client": np.full(n, client, np.int32),
        "clock": np.asarray(clocks, np.int64),
        "parent_is_root": np.ones(n, bool),
        "parent_a": np.asarray(parents, np.int64),
        "parent_b": np.full(n, -1, np.int64),
        "key_id": np.asarray(keys, np.int32),
        "origin_client": np.full(n, -1, np.int32),
        "origin_clock": np.full(n, -1, np.int64),
        "valid": np.ones(n, bool),
    }


class TestResidentColumns:
    def test_append_and_converge_rounds(self):
        rc = ResidentColumns(capacity=512)
        # round 1: client 1 writes keys 0..7 of map 0
        rc.append(_map_cols(1, range(8), [0] * 8, range(8)))
        # round 2: client 2 overwrites keys 0..3
        rc.append(_map_cols(2, range(4), [0] * 4, range(4)))
        assert rc.n == 12
        maps_out, _ = rc.converge(num_segments=512)
        order = np.asarray(maps_out[0])
        winners = np.asarray(maps_out[2])
        won_rows = {int(order[w]) for w in winners if w >= 0}
        # 8 distinct keys -> 8 winners; keys 0..3 won by client 2's
        # rows (appended at offsets 8..11)
        assert len(won_rows) == 8
        assert {8, 9, 10, 11} <= won_rows
        assert {4, 5, 6, 7} <= won_rows  # uncontested client-1 keys

    def test_growth_preserves_rows(self):
        rc = ResidentColumns(capacity=512)
        for r in range(5):
            rc.append(_map_cols(r + 1, range(200), [0] * 200, range(200)))
        assert rc.n == 1000 and rc.capacity >= 1024
        client_col = np.asarray(rc._bufs[0])
        valid_col = np.asarray(rc._bufs[8])
        assert valid_col[: rc.n].all() and not valid_col[rc.n :].any()
        # each round's 200 rows kept their (dense) client id through
        # the growth: raw r+1 arrived in ascending order -> dense r
        for r in range(5):
            dense = rc.dense_client(r + 1)
            assert dense == r
            assert (client_col[r * 200 : (r + 1) * 200] == dense).all()

    def test_sequences_converge_resident(self):
        rc = ResidentColumns(capacity=512)
        # two clients append chains to list 0 (parent_a=0, key_id=-1)
        for client in (1, 2):
            n = 6
            cols = _map_cols(client, range(n), [0] * n, [0] * n)
            cols["key_id"] = np.full(n, -1, np.int32)
            cols["origin_client"] = np.asarray(
                [-1] + [client] * (n - 1), np.int32
            )
            cols["origin_clock"] = np.asarray(
                [-1] + list(range(n - 1)), np.int64
            )
            rc.append(cols)
        _, seq_out = rc.converge(num_segments=512)
        rank = np.asarray(seq_out[2])
        assert int((rank >= 0).sum()) == 12  # all 12 items ranked
        seq_len = np.asarray(seq_out[3])
        assert int(seq_len.sum()) == 12


class TestIncrementalRebuild:
    def test_second_apply_touches_only_new_parents(self):
        """After a big first sync, a 1-op update must do O(1) spec
        interning — not re-walk the document."""
        from crdt_tpu.api.doc import Crdt
        from crdt_tpu.core.engine import Engine

        src_out = []
        src = Crdt(1, on_update=lambda u, m: src_out.append(u))
        for i in range(300):
            src.set("big", f"k{i}", i)
        src.push("list", ["a", "b", "c"])
        dst = Crdt(2, device_merge=True)
        dst.apply_updates(src_out)
        src_out.clear()

        calls = []
        orig = Engine._parent_spec_of_row

        def counting(self, row):
            calls.append(row)
            return orig(self, row)

        Engine._parent_spec_of_row = counting
        try:
            src.set("small", "x", 1)
            dst.apply_update(src_out[-1])
        finally:
            Engine._parent_spec_of_row = orig
        # 2 new rows (ix entry + the set) -> 2 spec lookups, not 300+
        assert len(calls) <= 4, f"walked {len(calls)} rows for a 2-row delta"
        assert dst.c["small"] == {"x": 1}
        assert dict(dst.c) == dict(src.c)

    def test_interleaved_local_and_remote_stay_identical(self):
        """Local scalar ops between incremental rebuilds must not
        diverge the two modes."""
        from crdt_tpu.api.doc import Crdt

        outs = {}
        a_out, b_out = [], []
        for dev in (False, True):
            a = Crdt(1, on_update=lambda u, m: a_out.append(u))
            b = Crdt(2, on_update=lambda u, m: b_out.append(u),
                     device_merge=dev)
            a_out.clear(), b_out.clear()
            for round_ in range(4):
                a.set("m", f"k{round_}", round_)
                a.push("l", [f"a{round_}"])
                for u in a_out:
                    b.apply_update(u)
                a_out.clear()
                b.push("l", [f"b{round_}"])  # local op between rebuilds
                b.set("m", "shared", round_)
                for u in b_out:
                    a.apply_update(u)
                b_out.clear()
            assert dict(a.c) == dict(b.c)
            outs[dev] = (dict(a.c), a.encode_state_as_update(),
                         b.encode_state_as_update())
        # caches AND full encoded states (tombstones included) match
        assert outs[False] == outs[True]


class TestClientInterning:
    def test_large_ids_and_out_of_order_arrival(self):
        """Random 31-bit client ids must not alias in the packed-id
        kernels, and a raw id arriving BETWEEN already-interned ids
        must trigger the on-device relabel that keeps dense ids
        monotone in the raw order (LWW compares client ids)."""
        rc = ResidentColumns(capacity=512)
        big, mid, small = 2**31 - 1, 2**20 + 7, 5
        # same key written by big then small then MID (arrives last,
        # lands between the other two in raw order)
        rc.append(_map_cols(big, [0], [0], [3]))
        rc.append(_map_cols(small, [0], [0], [3]))
        rc.append(_map_cols(mid, [0], [0], [3]))
        assert rc.dense_client(small) == 0
        assert rc.dense_client(mid) == 1
        assert rc.dense_client(big) == 2
        maps_out, _ = rc.converge(num_segments=512)
        order = np.asarray(maps_out[0])
        winners = np.asarray(maps_out[2])
        won_rows = [int(order[w]) for w in winners if w >= 0]
        assert won_rows == [0], "largest RAW client (row 0) must win"

    def test_preregistered_clients_never_relabel(self, monkeypatch):
        import crdt_tpu.ops.resident as resident

        def boom(*a, **k):
            raise AssertionError("relabel ran despite pre-registration")

        monkeypatch.setattr(resident, "_relabel", boom)
        clients = [2**30 + 11, 17, 2**25]
        rc = ResidentColumns(capacity=512, clients=clients)
        for c in clients:  # arrival order irrelevant once registered
            rc.append(_map_cols(c, [0], [0], [1]))
        maps_out, _ = rc.converge(num_segments=512)
        winners = np.asarray(maps_out[2])
        assert (winners >= 0).sum() == 1


class TestFusedAppendConverge:
    def test_matches_append_then_converge(self):
        import numpy as np

        cols = _map_cols(1, range(12), [0] * 12, list(range(6)) * 2)
        a = ResidentColumns(capacity=512)
        a.append(cols)
        sep = a.converge(num_segments=512)
        b = ResidentColumns(capacity=512)
        fused = b.append_converge(cols, num_segments=512)
        for x, y in zip(sep, fused):
            for ax, ay in zip(x, y):
                np.testing.assert_array_equal(np.asarray(ax), np.asarray(ay))
        assert a.n == b.n == 12

    def test_empty_delta_falls_back_to_converge(self):
        rc = ResidentColumns(capacity=512)
        rc.append(_map_cols(1, range(4), [0] * 4, range(4)))
        out = rc.append_converge(
            {k: v[:0] for k, v in _map_cols(1, [], [], []).items()},
            num_segments=512,
        )
        import numpy as np

        winners = np.asarray(out[0][2])
        assert (winners >= 0).sum() == 4

    def test_growing_fused_append_keeps_segment_default(self):
        """A fused append that grows capacity must size its default
        segment count from the POST-growth capacity."""
        import numpy as np

        rc = ResidentColumns(capacity=512)
        rc.append(_map_cols(1, range(512), [0] * 512, range(512)))
        grow = _map_cols(2, range(600), [1] * 600, range(600))
        fused = rc.append_converge(grow)  # default num_segments
        ref = ResidentColumns(capacity=512)
        ref.append(_map_cols(1, range(512), [0] * 512, range(512)))
        ref.append(grow)
        sep = ref.converge()
        for x, y in zip(sep, fused):
            for ax, ay in zip(x, y):
                np.testing.assert_array_equal(np.asarray(ax), np.asarray(ay))
