"""The observability layer (crdt_tpu/obs): histogram math, tracer
thread-safety, flight recorder, Prometheus exposition, divergence
sentinel, trace-id propagation, jax_profile hardening — and the
round-18 serving surfaces: per-tenant SLO ledger (breach exactness
under the seeded flood), tick-timeline profiler (ring wraparound +
Perfetto schema), the HTTP scrape endpoint (live during serve()),
and the obsq CLI round-trip."""

import json
import sys
import threading
import time

import pytest

from crdt_tpu.obs import (
    FlightRecorder,
    Tracer,
    get_recorder,
    get_tracer,
    set_recorder,
    set_tracer,
    snapshot_json,
    to_prometheus,
)
from crdt_tpu.obs.tracer import BUCKET_EDGES_S, N_BUCKETS, bucket_index


@pytest.fixture
def installed():
    """Enabled global tracer + recorder, restored afterwards."""
    old_t, old_r = get_tracer(), get_recorder()
    tr = set_tracer(Tracer(enabled=True))
    rec = set_recorder(FlightRecorder(enabled=True))
    try:
        yield tr, rec
    finally:
        set_tracer(old_t)
        set_recorder(old_r)


# ---------------------------------------------------------------------------
# histogram bucket math (the edges are a contract: Prometheus les)
# ---------------------------------------------------------------------------


class TestHistogramBuckets:
    def test_edges_are_powers_of_two_microseconds(self):
        assert BUCKET_EDGES_S[0] == 1e-6
        for k in range(1, N_BUCKETS):
            assert BUCKET_EDGES_S[k] == 2 * BUCKET_EDGES_S[k - 1]

    def test_bucket_index_at_edges_is_upper_inclusive(self):
        # an observation exactly AT an edge lands in that edge's bucket
        for k in (0, 1, 5, 17, N_BUCKETS - 1):
            assert bucket_index(BUCKET_EDGES_S[k]) == k
        # just above an edge spills into the next bucket
        for k in (0, 3, 20):
            assert bucket_index(BUCKET_EDGES_S[k] * 1.0000001) == k + 1

    def test_below_floor_and_overflow(self):
        assert bucket_index(0.0) == 0
        assert bucket_index(-1.0) == 0  # clock skew: clamp, not crash
        assert bucket_index(5e-7) == 0
        assert bucket_index(1e9) == N_BUCKETS  # +Inf bucket

    def test_single_observation_quantiles_equal_max(self):
        tr = Tracer(enabled=True)
        tr.observe("x", 3e-6)  # inside (2e-6, 4e-6]: edge=4e-6 > max
        s = tr.report()["spans"]["x"]
        # the bucket-edge estimate is clamped to the observed max
        assert s["p50_s"] == s["p99_s"] == s["max_s"] == 3e-6

    def test_tail_separates_from_median(self):
        tr = Tracer(enabled=True)
        for _ in range(99):
            tr.observe("x", 1e-3)
        tr.observe("x", 1.0)
        s = tr.report()["spans"]["x"]
        assert s["count"] == 100
        assert s["p50_s"] <= 2e-3       # median in the 1ms bucket
        assert s["max_s"] == 1.0
        assert s["p99_s"] <= 2e-3       # rank 99 of 100 still 1ms...
        tr.observe("x", 1.0)            # ...until the tail thickens
        tr.observe("x", 1.0)
        s = tr.report()["spans"]["x"]
        assert s["p99_s"] >= 0.5        # now p99 lives in the 1s bucket

    def test_quantiles_are_monotone_and_bounded(self):
        import random

        rng = random.Random(7)
        tr = Tracer(enabled=True)
        for _ in range(500):
            tr.observe("x", rng.uniform(1e-6, 0.1))
        s = tr.report()["spans"]["x"]
        assert s["min_s"] <= s["p50_s"] * 2  # bucket resolution slack
        assert s["p50_s"] <= s["p90_s"] <= s["p99_s"] <= s["max_s"]

    def test_report_keeps_legacy_schema(self):
        tr = Tracer(enabled=True)
        with tr.span("merge"):
            pass
        s = tr.report()["spans"]["merge"]
        for k in ("count", "total_s", "mean_s", "max_s"):
            assert k in s  # the pinned pre-obs surface
        assert s["count"] == 1


# ---------------------------------------------------------------------------
# thread-safety: the satellite the old tracer failed
# ---------------------------------------------------------------------------


class TestTracerThreadSafety:
    def test_concurrent_spans_and_counters_are_exact(self):
        """8 threads hammer one tracer; totals must be EXACT. The old
        tracer's unlocked read-modify-write dicts lost updates under
        preemption (models/streaming.py decodes on a thread pool into
        the process-global tracer), which this pins at a switch
        interval tight enough to make the race near-certain."""
        tr = Tracer(enabled=True)
        threads, per = 8, 3000
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-6)
        try:
            def work():
                for _ in range(per):
                    tr.count("ops")
                    with tr.span("phase"):
                        pass
                    tr.observe("lag", 1e-5)

            ts = [threading.Thread(target=work) for _ in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            sys.setswitchinterval(old_interval)
        rep = tr.report()
        assert rep["counters"]["ops"] == threads * per
        assert rep["spans"]["phase"]["count"] == threads * per
        assert rep["spans"]["lag"]["count"] == threads * per
        # histogram buckets must account for every observation too
        assert sum(
            rep["spans"]["lag"]["buckets"].values()
        ) == threads * per

    def test_streaming_decode_pool_records_spans(self, installed):
        """The real seam: the chunked thread-pooled decode records
        into the process-global tracer from pool threads."""
        tr, _ = installed
        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord

        blobs = [
            v1.encode_update(
                [ItemRecord(client=c + 1, clock=k, parent_root="m",
                            key=f"k{k}", content=k)
                 for k in range(4)],
                DeleteSet(),
            )
            for c in range(8)
        ]
        from crdt_tpu.models.streaming import _Phases, stream_decode

        dec = stream_decode(blobs, chunk_blobs=2, ph=_Phases())
        assert len(dec["client"]) > 0
        spans = tr.report()["spans"]
        assert spans["decode"]["count"] >= len(blobs) // 2


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_wraparound_keeps_newest(self):
        fr = FlightRecorder(capacity=4, enabled=True)
        for i in range(10):
            fr.record("k", i=i)
        assert len(fr) == 4
        assert fr.recorded == 10
        assert [e["i"] for e in fr.events()] == [6, 7, 8, 9]
        # timestamps monotone oldest-first
        ts = [e["ts"] for e in fr.events()]
        assert ts == sorted(ts)

    def test_jsonl_dump_roundtrips(self, tmp_path):
        fr = FlightRecorder(capacity=8, enabled=True)
        fr.record("update.send", topic="t", size=12, digest="aa")
        fr.record("update.recv", topic="t", size=12, digest="aa")
        path = tmp_path / "dump.jsonl"
        text = fr.dump_jsonl(str(path))
        assert path.read_text() == text
        lines = [json.loads(ln) for ln in text.splitlines()]
        assert [e["kind"] for e in lines] == ["update.send", "update.recv"]
        assert all("ts" in e for e in lines)

    def test_disabled_records_nothing(self):
        fr = FlightRecorder(capacity=4, enabled=False)
        fr.record("k")
        assert len(fr) == 0 and fr.dump_jsonl() == ""

    def test_kind_filter(self):
        fr = FlightRecorder(enabled=True)
        fr.record("a")
        fr.record("b")
        fr.record("a")
        assert len(fr.events("a")) == 2


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


class TestPrometheusExport:
    def test_types_and_name_sanitization(self):
        tr = Tracer(enabled=True)
        tr.count("router.relay-sends")       # dot + dash -> _
        tr.gauge("9pending", 3)              # leading digit -> prefix
        with tr.span("converge.dispatch"):
            pass
        text = to_prometheus(tr.report())
        assert "# TYPE crdt_router_relay_sends counter" in text
        assert "crdt_router_relay_sends 1" in text
        assert "# TYPE crdt__9pending gauge" in text
        assert (
            "# TYPE crdt_converge_dispatch_seconds histogram" in text
        )
        assert "crdt_converge_dispatch_seconds_count 1" in text

    def test_histogram_buckets_cumulative_with_inf(self):
        tr = Tracer(enabled=True)
        tr.observe("x", 1e-6)
        tr.observe("x", 2e-6)
        tr.observe("x", 2e-6)
        tr.observe("x", 1e9)  # overflow
        text = to_prometheus(tr.report())
        assert 'crdt_x_seconds_bucket{le="1e-06"} 1' in text
        assert 'crdt_x_seconds_bucket{le="2e-06"} 3' in text
        assert 'crdt_x_seconds_bucket{le="+Inf"} 4' in text
        assert "crdt_x_seconds_count 4" in text
        # cumulative counts never decrease
        counts = [
            int(ln.rsplit(" ", 1)[1])
            for ln in text.splitlines() if "_bucket{" in ln
        ]
        assert counts == sorted(counts)

    def test_labeled_counters_pass_through(self):
        tr = Tracer(enabled=True)
        tr.count("bytes", 7, labels={"peer": "abc", "topic": "t"})
        text = to_prometheus(tr.report())
        assert 'crdt_bytes{peer="abc",topic="t"} 7' in text

    def test_one_type_line_per_metric_across_label_sets(self):
        # a duplicate TYPE line for one metric name is a fatal
        # exposition parse error: label variants group under ONE
        tr = Tracer(enabled=True)
        tr.count("bytes", 1, labels={"peer": "a"})
        tr.count("bytes", 2, labels={"peer": "b"})
        tr.gauge("depth", 3, labels={"topic": "x"})
        tr.gauge("depth", 4, labels={"topic": "y"})
        text = to_prometheus(tr.report())
        assert text.count("# TYPE crdt_bytes counter") == 1
        assert text.count("# TYPE crdt_depth gauge") == 1
        assert 'crdt_bytes{peer="a"} 1' in text
        assert 'crdt_bytes{peer="b"} 2' in text

    def test_json_snapshot_matches_report(self):
        tr = Tracer(enabled=True)
        tr.count("x")
        assert json.loads(snapshot_json(tr.report())) == json.loads(
            json.dumps(tr.report())
        )


# ---------------------------------------------------------------------------
# jax_profile hardening
# ---------------------------------------------------------------------------


class TestJaxProfile:
    def test_capture_works_on_cpu(self, tmp_path):
        import jax.numpy as jnp

        from crdt_tpu.utils.trace import jax_profile

        with jax_profile(str(tmp_path)):
            (jnp.arange(16) + 1).block_until_ready()

    def test_body_failure_stops_profiler(self, tmp_path):
        """A crash inside the block must stop the trace: the NEXT
        capture would otherwise fail with 'profiler already running'
        (the pre-obs bug class this satellite fixes)."""
        import jax.numpy as jnp

        from crdt_tpu.utils.trace import jax_profile

        with pytest.raises(RuntimeError, match="boom"):
            with jax_profile(str(tmp_path / "a")):
                raise RuntimeError("boom")
        with jax_profile(str(tmp_path / "b")):  # must not raise
            (jnp.arange(4) * 2).block_until_ready()

    def test_clear_error_without_profiler(self, monkeypatch):
        import types

        from crdt_tpu.utils.trace import jax_profile

        monkeypatch.setitem(
            sys.modules, "jax", types.SimpleNamespace()
        )
        with pytest.raises(RuntimeError, match="profiler unavailable"):
            with jax_profile("/tmp/never"):
                pass


# ---------------------------------------------------------------------------
# divergence sentinel + trace-id propagation (loopback fabric)
# ---------------------------------------------------------------------------


def _pair(net=None, **kw):
    from crdt_tpu.net import LoopbackNetwork, LoopbackRouter, Replica

    net = net or LoopbackNetwork()
    r1 = Replica(LoopbackRouter(net, "a"), topic="t", client_id=1, **kw)
    r2 = Replica(LoopbackRouter(net, "b"), topic="t", client_id=2, **kw)
    net.run()
    return net, r1, r2


class TestDivergenceSentinel:
    def test_silent_on_fault_free_run(self, installed):
        net, r1, r2 = _pair()
        for i in range(6):
            (r1 if i % 2 else r2).set("kv", f"k{i}", i)
        net.run()
        assert dict(r1.c) == dict(r2.c)
        r1.beacon()
        r2.beacon()
        net.run()
        assert r1.sentinel.events == [] and r2.sentinel.events == []
        tr, _ = installed
        assert tr.counters()["sentinel.agree"] >= 2
        # mutate again (invalidates the cached digest), re-beacon:
        # still silent, still agreeing on the NEW state
        r1.set("kv", "fresh", 99)
        net.run()
        r1.beacon()
        r2.beacon()
        net.run()
        assert r1.sentinel.events == [] and r2.sentinel.events == []
        assert tr.counters()["sentinel.agree"] >= 4

    def test_fires_on_injected_state_fork(self, installed):
        from crdt_tpu.net.faults import ForkFault

        tr, rec = installed
        net, r1, r2 = _pair()
        r1.set("kv", "k", 1)
        net.run()
        assert dict(r1.c) == dict(r2.c)
        # seeded fork: same id, different content, equal SVs — the
        # sync protocol sees nothing; only the beacon can
        fork = ForkFault(seed=3)
        fork.inject([r1, r2])
        assert r1.doc.state_vector() == r2.doc.state_vector()
        assert dict(r1.c) != dict(r2.c)
        r1.beacon()
        net.run()
        assert len(r2.sentinel.events) == 1
        ev = r2.sentinel.events[0]
        assert ev["kind"] == "divergence"
        assert ev["peer"] == "a" and ev["topic"] == "t"
        assert ev["local_digest"] != ev["peer_digest"]
        # the event carries a flight-recorder dump with the fork in it
        kinds = [
            json.loads(ln)["kind"]
            for ln in ev["flight_recorder"].splitlines()
        ]
        assert "fault.fork" in kinds
        assert tr.counters()["sentinel.divergence"] == 1
        # a permanent fork is raised ONCE per peer: later beacons of
        # the same fork bump the counter but never re-event (no
        # unbounded event/dump growth on a long-lived divergence)
        r1.beacon()
        net.run()
        assert len(r2.sentinel.events) == 1
        assert tr.counters()["sentinel.divergence"] == 2

    def test_sv_lag_stays_silent(self, installed):
        """Unequal SVs (ops in flight) are lag, not divergence."""
        net, r1, r2 = _pair()
        r1.set("kv", "k", 1)
        # beacon BEFORE delivery: r2's SV is behind
        r1.beacon()
        net.run()
        assert r2.sentinel.events == []
        tr, _ = installed
        assert tr.counters().get("sentinel.divergence", 0) == 0

    def test_deterministic_fork_schedule(self):
        from crdt_tpu.net.faults import ForkFault

        a, b = ForkFault(seed=9), ForkFault(seed=9)
        assert (a.client, a.key) == (b.client, b.key)
        assert ForkFault(seed=10).client != a.client or \
            ForkFault(seed=10).key != a.key


class TestTraceIdPropagation:
    def test_tid_rides_updates_and_measures_lag(self, installed):
        tr, rec = installed
        net, r1, r2 = _pair()
        r1.set("kv", "k", 1)
        r2.push("log", "e")
        net.run()
        assert dict(r1.c) == dict(r2.c)
        sent = [tuple(e["tid"]) for e in rec.events("update.send")]
        recv = [
            tuple(e["tid"]) for e in rec.events("update.recv")
            if e.get("tid")
        ]
        assert sent and set(sent) <= set(recv)
        # tid = (client, seq, ts): origin client rides the stamp
        clients = {t[0] for t in sent}
        assert clients == {1, 2}
        spans = tr.report()["spans"]
        assert spans["replica.propagation_lag"]["count"] >= 2
        assert spans["replica.convergence_lag"]["count"] >= 2
        assert "replica.propagation_lag_s" in tr.report()["gauges"]

    def test_anti_entropy_beacon_detects_fork_on_udp(self, installed):
        """The acceptance pin: under a seeded fault schedule plus a
        seeded state fork, the sentinel riding the REAL anti-entropy
        cadence (UDP routers, chaos faults on the wire) raises a
        divergence event carrying a flight-recorder dump; the
        fault-free run stays silent."""
        from crdt_tpu.net.faults import (
            FaultSchedule, ForkFault, install_faults, pump_until,
        )
        from crdt_tpu.net.replica import Replica
        from crdt_tpu.net.udp_router import UdpRouter

        def run(forked):
            routers = [UdpRouter() for _ in range(2)]
            routers[1].add_peer(*routers[0].addr)
            try:
                pump_until(
                    routers,
                    lambda: all(len(r.peers) == 1 for r in routers),
                    timeout_s=30.0,
                )
                reps = [
                    Replica(r, topic="room", client_id=i + 1,
                            probe_retry_s=0.05, anti_entropy_s=0.05)
                    for i, r in enumerate(routers)
                ]
                pump_until(
                    routers,
                    lambda: all(
                        len(r.peers_on("room")) == 1 for r in routers
                    ),
                    timeout_s=30.0,
                )
                if forked:
                    # chaos on the wire + the fork fault itself
                    for r in routers:
                        install_faults(
                            r, FaultSchedule(11, drop=0.05, delay=0.05)
                        )
                    ForkFault(seed=11).inject(reps)
                reps[0].set("kv", "x", 1)
                pump_until(
                    routers,
                    lambda: "kv" in reps[1].c,
                    timeout_s=30.0,
                )
                if forked:
                    pump_until(
                        routers,
                        lambda: any(r.sentinel.events for r in reps),
                        timeout_s=30.0,
                    )
                    events = [
                        e for r in reps for e in r.sentinel.events
                    ]
                    assert events[0]["kind"] == "divergence"
                    assert events[0]["flight_recorder"]
                else:
                    # let several anti-entropy/beacon rounds fire
                    deadline = time.monotonic() + 0.5
                    while time.monotonic() < deadline:
                        for r in routers:
                            r.poll()
                        time.sleep(0.002)
                    assert all(not r.sentinel.events for r in reps)
                    assert any(
                        r.sentinel.beacons_checked > 0 for r in reps
                    )
            finally:
                for r in routers:
                    r.close()

        run(forked=False)
        run(forked=True)


# ---------------------------------------------------------------------------
# round 18: tracer hardening (quantile edges, disabled-path freedom)
# ---------------------------------------------------------------------------


class TestTracerEdges:
    def test_quantile_unknown_span_is_zero(self):
        assert Tracer(enabled=True).quantile("nothing", 0.5) == 0.0

    def test_quantile_edges_single_sample(self):
        tr = Tracer(enabled=True)
        tr.observe("x", 3e-3)
        # one observation answers itself at EVERY q, 0 and 1 included
        for q in (0.0, 0.5, 0.99, 1.0):
            assert tr.quantile("x", q) == 3e-3

    def test_quantile_q0_and_q1(self):
        tr = Tracer(enabled=True)
        for v in (1e-6, 1e-3, 1.0):
            tr.observe("x", v)
        # q=0 is the rank-1 (minimum-bucket) estimate: the first
        # bucket's upper edge, never above the min's bucket edge
        assert tr.quantile("x", 0.0) <= 2e-6
        # q=1 (and beyond) is the observed max exactly
        assert tr.quantile("x", 1.0) == 1.0
        assert tr.quantile("x", 2.0) == 1.0

    def test_observe_disabled_records_nothing(self):
        tr = Tracer(enabled=False)
        tr.observe("x", 1.0)
        tr.count("c")
        tr.gauge("g", 2.0)
        rep = tr.report()
        assert rep["spans"] == {} and rep["counters"] == {} \
            and rep["gauges"] == {}

    def test_disabled_span_is_shared_object(self):
        tr = Tracer(enabled=False)
        assert tr.span("a") is tr.span("b")

    def test_histogram_summary_matches_report(self):
        from crdt_tpu.obs.tracer import Histogram

        tr = Tracer(enabled=True)
        h = Histogram()
        for v in (1e-5, 2e-4, 3e-3):
            tr.observe("x", v)
            h.add(v)
        assert tr.report()["spans"]["x"] == h.summary()


# ---------------------------------------------------------------------------
# round 18: Prometheus sanitization-collision disambiguation
# ---------------------------------------------------------------------------


class TestPrometheusCollisions:
    def test_distinct_keys_never_merge(self):
        import zlib

        tr = Tracer(enabled=True)
        tr.count("guard.a-b", 3)
        tr.count("guard.a_b", 4)
        text = to_prometheus(tr.report())
        # both raw keys sanitize to crdt_guard_a_b: each colliding
        # member gets its deterministic crc32 suffix, no silent merge
        tag1 = zlib.crc32(b"counters:guard.a-b") & 0xFFFFFFFF
        tag2 = zlib.crc32(b"counters:guard.a_b") & 0xFFFFFFFF
        assert f"crdt_guard_a_b_{tag1:08x} 3" in text
        assert f"crdt_guard_a_b_{tag2:08x} 4" in text
        assert "\ncrdt_guard_a_b 3" not in text
        assert "\ncrdt_guard_a_b 4" not in text
        # deterministic: a fresh render is byte-identical
        assert to_prometheus(tr.report()) == text

    def test_counter_gauge_name_clash_disambiguated(self):
        tr = Tracer(enabled=True)
        tr.count("depth", 1)
        tr.gauge("depth", 2.0)
        text = to_prometheus(tr.report())
        # pre-fix this emitted TWO TYPE lines for crdt_depth (a fatal
        # exposition parse error); now each section owns its series
        names = [
            ln.split()[2] for ln in text.splitlines()
            if ln.startswith("# TYPE")
        ]
        assert len(names) == len(set(names))
        assert any(n.startswith("crdt_depth_") for n in names)

    def test_collision_free_names_unchanged(self):
        tr = Tracer(enabled=True)
        tr.count("tenant.shed", 5)
        tr.gauge("tenant.pending_bytes", 7)
        with tr.span("converge.dispatch"):
            pass
        text = to_prometheus(tr.report())
        assert "crdt_tenant_shed 5" in text
        assert "crdt_tenant_pending_bytes 7" in text
        assert "crdt_converge_dispatch_seconds_count 1" in text

    def test_labeled_variants_share_one_series(self):
        tr = Tracer(enabled=True)
        tr.count("slo.breaches", 1, labels={"tenant": "a"})
        tr.count("slo.breaches", 2, labels={"tenant": "b"})
        text = to_prometheus(tr.report())
        assert text.count("# TYPE crdt_slo_breaches counter") == 1
        assert 'crdt_slo_breaches{tenant="a"} 1' in text
        assert 'crdt_slo_breaches{tenant="b"} 2' in text


# ---------------------------------------------------------------------------
# round 18: per-tenant SLO ledger
# ---------------------------------------------------------------------------


class TestSLOLedger:
    def test_breach_counting_and_routes(self, installed):
        from crdt_tpu.obs.slo import SLOLedger

        led = SLOLedger(slo_ms=10.0)
        led.converged("t1", [0.001, 0.020], "delta")
        led.served("t1", [0.001, 0.020])  # one over the 10ms bar
        led.shed("t1", 3)                 # sheds are breaches
        rep = led.report()
        t1 = rep["tenants"]["t1"]
        assert t1["breaches"] == 1 + 3
        assert t1["routes"] == {"delta": 1, "cold": 0,
                                "fallback": 0, "shed": 3}
        assert t1["ingest_to_served"]["count"] == 2
        assert t1["ingest_to_converged"]["count"] == 2
        # window: [F, T, T, T, T] -> burn 0.8
        assert t1["burn_rate"] == 0.8
        assert rep["total_breaches"] == 4
        tr, _ = installed
        assert tr.counters()["slo.breaches"] == 4
        assert tr.counters()['slo.breaches{tenant="t1"}'] == 4
        assert tr.counters()['slo.route_shed{tenant="t1"}'] == 3

    def test_env_objective(self, monkeypatch):
        from crdt_tpu.obs.slo import SLOLedger

        monkeypatch.setenv("CRDT_TPU_SLO_MS", "5")
        assert SLOLedger().slo_ms == 5.0
        monkeypatch.setenv("CRDT_TPU_SLO_MS", "garbage")
        assert SLOLedger().slo_ms == 250.0

    def test_zero_objective_breaches_everything(self):
        from crdt_tpu.obs.slo import SLOLedger

        led = SLOLedger(slo_ms=0.0)
        led.served("t", [1e-9, 1e-6])
        assert led.breaches("t") == 2

    def test_flood_breaches_pin_admission_oracle(self, installed):
        """The acceptance exactness pin: under the seeded round-14
        flood, the flooding tenant's breach count equals its shed
        count equals the admission oracle (submitted minus the
        admitted suffix the budget kept), while every neighbor shows
        ZERO breaches — diagnosable from the ledger alone."""
        from crdt_tpu.models.multidoc import MultiDocServer

        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord

        def blob(c, k0, n=4):
            return v1.encode_update(
                [ItemRecord(client=c, clock=k0 + i, parent_root="m",
                            key=f"k{i}", content=k0 + i)
                 for i in range(n)],
                DeleteSet(),
            )

        # a generous objective: nothing served on time breaches, so
        # EVERY breach is a shed — exactly countable
        srv = MultiDocServer(tenant_max_pending_bytes=1 << 20,
                             tenant_max_pending_updates=4,
                             slo_ms=1e9)
        neighbors = [f"n{i}" for i in range(3)]
        for i, d in enumerate(neighbors):
            assert srv.submit(d, blob(10 + i, 0)) == 0
        flooder = "flood!"
        submitted, shed_oracle = 0, 0
        for j in range(23):  # 23 blobs into a 4-update budget
            shed_oracle += srv.submit(flooder, blob(99, j * 4))
            submitted += 1
        assert shed_oracle == submitted - 4  # keep-the-newest suffix
        srv.tick()
        assert not srv.dirty_docs()
        assert srv.slo.breaches(flooder) == shed_oracle
        routes = srv.slo.route_counts(flooder)
        assert routes["shed"] == shed_oracle
        rep = srv.slo.report()
        ften = rep["tenants"][flooder]
        # the admitted suffix (4 blobs) was served, late never
        assert ften["ingest_to_served"]["count"] == 4
        for d in neighbors:
            assert srv.slo.breaches(d) == 0
            assert rep["tenants"][d]["breaches"] == 0
            assert rep["tenants"][d]["ingest_to_served"]["count"] == 1
        # the labeled shed attribution rode the guard layer too
        tr, rec = installed
        key = 'tenant.shed{tenant=%s}' % '"flood!"'
        assert tr.counters()[key] == shed_oracle
        shed_events = rec.events("tenant.shed")
        assert shed_events and all(
            e["doc"] == flooder for e in shed_events
        )
        assert sum(e["count"] for e in shed_events) == shed_oracle


# ---------------------------------------------------------------------------
# round 18: tick-timeline profiler
# ---------------------------------------------------------------------------


def _fake_tick(tl, base, i):
    """One synthetic tick with two overlapping dispatch windows."""
    tl.tick_begin(i)
    t0 = tl._cur["t0"]
    tl.add_phase("pack", t0, t0 + 0.010)
    a = tl.dispatch_begin(t=t0 + 0.010)
    tl.add_phase("pack", t0 + 0.012, t0 + 0.020)
    b = tl.dispatch_begin(t=t0 + 0.020)
    tl.dispatch_end(a, t0 + 0.022, t0 + 0.030)
    tl.dispatch_end(b, t0 + 0.030, t0 + 0.041)
    return tl.tick_end()


class TestTickTimeline:
    def test_disabled_is_noop(self):
        from crdt_tpu.obs.timeline import TickTimeline

        tl = TickTimeline(enabled=False)
        tl.tick_begin(1)
        with tl.phase("x"):
            pass
        assert tl.dispatch_begin() is None
        assert tl.tick_end() is None
        assert len(tl) == 0
        # the disabled phase() is one shared object — no allocation
        assert tl.phase("a") is tl.phase("b")

    def test_ring_wraparound_keeps_newest(self):
        from crdt_tpu.obs.timeline import TickTimeline

        tl = TickTimeline(capacity=4, enabled=True)
        for i in range(10):
            tl.tick_begin(i)
            with tl.phase("p"):
                pass
            tl.tick_end()
        assert len(tl) == 4
        assert tl.recorded == 10
        assert [r["tick"] for r in tl.records()] == [6, 7, 8, 9]

    def test_overlap_and_stall_accounting(self):
        from crdt_tpu.obs.timeline import TickTimeline

        tl = TickTimeline(enabled=True)
        rec = _fake_tick(tl, 0.0, 1)
        # stall = the two fetch waits: 8ms + 11ms
        assert rec["stall_ms"] == pytest.approx(19.0, abs=0.2)
        # lanes: pack 18ms + merged dispatch window [10,41]=31ms;
        # busy 49ms over a ~41ms wall -> efficiency strictly > 0
        assert rec["lanes"]["pack"] == pytest.approx(0.018, abs=1e-6)
        assert rec["lanes"]["dispatch"] == pytest.approx(
            0.031, abs=1e-6
        )
        assert rec["overlap_efficiency"] > 0.0
        assert len(rec["dispatches"]) == 2

    def test_overlap_of_bounds(self):
        from crdt_tpu.obs.timeline import overlap_of

        # fully serial: wall == busy sum
        assert overlap_of({"a": 1.0, "b": 1.0}, 2.0) == 0.0
        # fully hidden: wall == longest lane
        assert overlap_of({"a": 1.0, "b": 1.0}, 1.0) == 1.0
        # degenerate single lane: clamped, not divide-by-zero
        assert overlap_of({"a": 1.0}, 1.0) == 1.0
        assert overlap_of({}, 0.5) == 0.0

    def test_perfetto_schema(self):
        from crdt_tpu.obs.timeline import TickTimeline

        tl = TickTimeline(enabled=True)
        for i in range(3):
            _fake_tick(tl, 0.0, i)
        pf = tl.to_perfetto()
        assert set(pf) == {"traceEvents", "displayTimeUnit"}
        evs = pf["traceEvents"]
        assert evs, "no events exported"
        for ev in evs:
            for k in ("name", "ph", "ts", "pid", "tid"):
                assert k in ev, (ev, k)
            assert ev["ph"] in ("X", "M", "C")
            if ev["ph"] == "X":
                assert ev["dur"] >= 0
        # dispatch windows live on the device track (tid 2)
        disp = [e for e in evs
                if e["ph"] == "X" and e["name"].startswith("dispatch")]
        assert disp and all(e["tid"] == 2 for e in disp)
        # the whole thing is valid JSON end to end
        assert json.loads(tl.perfetto_json())["traceEvents"]

    def test_perfetto_json_writes_file(self, tmp_path):
        from crdt_tpu.obs.timeline import TickTimeline

        tl = TickTimeline(enabled=True)
        _fake_tick(tl, 0.0, 0)
        p = tmp_path / "trace.json"
        text = tl.perfetto_json(str(p))
        assert p.read_text() == text


# ---------------------------------------------------------------------------
# round 18: HTTP scrape endpoint + the serve() acceptance run
# ---------------------------------------------------------------------------


def _mt_blob(c, k0, n=4):
    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    return v1.encode_update(
        [ItemRecord(client=c, clock=k0 + i, parent_root="m",
                    key=f"k{i}", content=k0 + i)
         for i in range(n)],
        DeleteSet(),
    )


@pytest.fixture
def timeline_installed():
    from crdt_tpu.obs import TickTimeline, get_timeline, set_timeline

    old = get_timeline()
    tl = set_timeline(TickTimeline(enabled=True))
    try:
        yield tl
    finally:
        set_timeline(old)


class TestObsHTTP:
    def _get(self, url):
        from urllib.request import urlopen

        with urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()

    def test_endpoints_smoke(self, installed, timeline_installed):
        from crdt_tpu.obs import ObsHTTPServer

        tr, rec = installed
        tr.count("tenant.submitted", 3)
        tr.gauge("tenant.pending_bytes", 64)
        with tr.span("converge.dispatch"):
            pass
        rec.record("update.send", topic="room", digest="aa",
                   tid=[1, 1, 0.0], hop=0)
        rec.record("update.recv", topic="room", digest="aa",
                   tid=[1, 1, 0.0], hop=1, peer="p1")
        rec.record("tenant.shed", doc="flood!", count=2, bytes=99)
        with ObsHTTPServer(port=0, snapshot_extra=lambda: {
            "slo": {"slo_ms": 250.0},
        }) as obs:
            st, metrics = self._get(obs.url + "/metrics")
            assert st == 200
            assert "crdt_tenant_submitted 3" in metrics
            assert "crdt_converge_dispatch_seconds_count 1" in metrics

            st, snap = self._get(obs.url + "/snapshot")
            assert st == 200
            data = json.loads(snap)
            assert data["tracer"]["counters"]["tenant.submitted"] == 3
            assert data["slo"]["slo_ms"] == 250.0

            # filters: kind, doc (matches doc OR topic), peer, limit
            st, ev = self._get(obs.url + "/events?kind=tenant.shed")
            assert st == 200
            lines = [json.loads(ln) for ln in ev.splitlines()]
            assert [e["kind"] for e in lines] == ["tenant.shed"]
            assert lines[0]["doc"] == "flood!"
            st, ev = self._get(obs.url + "/events?doc=room")
            kinds = {json.loads(ln)["kind"]
                     for ln in ev.splitlines()}
            assert kinds == {"update.send", "update.recv"}
            st, ev = self._get(obs.url + "/events?peer=p1")
            assert len(ev.splitlines()) == 1
            st, ev = self._get(
                obs.url + "/events?doc=room&limit=1"
            )
            assert len(ev.splitlines()) == 1

            st, tl_text = self._get(obs.url + "/timeline")
            assert st == 200
            assert "traceEvents" in json.loads(tl_text)

        # unknown path: 404 with the route list, not a crash
        obs2 = ObsHTTPServer(port=0).start()
        try:
            from urllib.error import HTTPError

            with pytest.raises(HTTPError) as ei:
                self._get(obs2.url + "/nope")
            assert ei.value.code == 404
        finally:
            obs2.stop()

    def test_serve_flood_slo_timeline_scrapeable_live(
            self, installed, timeline_installed):
        """The round-18 acceptance pin: a seeded serve() run under
        the round-14 flood scenario yields (a) a per-tenant SLO
        report whose flooding-tenant breach/shed counts equal the
        admission oracle while neighbors hold zero, (b) a
        schema-valid Perfetto timeline whose double-buffered ticks
        show overlap_efficiency > 0, (c) all of it scraped LIVE from
        the HTTP endpoint while serve() is mid-run."""
        from urllib.request import urlopen

        from crdt_tpu.models.multidoc import MultiDocServer
        from crdt_tpu.obs import ObsHTTPServer

        tl = timeline_installed
        neighbors = [f"n{i}" for i in range(6)]
        flooder = "flood!"
        srv = MultiDocServer(
            # small dispatches: >=3 async batches per cold tick, so
            # the double-buffer has windows to overlap
            max_rows_per_dispatch=60,
            tenant_max_pending_bytes=1 << 20,
            tenant_max_pending_updates=4,
            slo_ms=1e9,  # served-on-time never breaches: breach==shed
        )
        obs = ObsHTTPServer(port=0, snapshot_extra=lambda: {
            "slo": srv.slo.report(),
        }).start()
        live: dict = {}
        oracle = {"submitted": 0, "shed": 0}

        def source():
            # batch 1: neighbors' histories (30 ops each)
            yield [(d, _mt_blob(10 + i, 0, 30))
                   for i, d in enumerate(neighbors)]
            # mid-run scrape: serve()'s ingest hook pulls this batch
            # while tick 1's dispatches are still in flight
            with urlopen(obs.url + "/metrics", timeout=10) as r:
                live["metrics"] = (r.status, r.read().decode())
            with urlopen(obs.url + "/snapshot", timeout=10) as r:
                live["snapshot"] = json.loads(r.read().decode())
            # batch 2: the flood — 23 blobs into a 4-update budget
            yield [(flooder, _mt_blob(99, j * 4)) for j in range(23)]
            yield [(d, _mt_blob(10 + i, 30, 2))
                   for i, d in enumerate(neighbors)]

        class _Counting:
            def __init__(self, it):
                self.it = it

            def __iter__(self):
                return self

            def __next__(self):
                batch = next(self.it)
                oracle["submitted"] += len(batch)
                return batch

        rep = srv.serve(_Counting(source()), max_ticks=10)
        obs_stop_exc = None
        try:
            # (c) live scrape happened mid-run and carried real data
            assert live["metrics"][0] == 200
            assert "crdt_tenant_submitted" in live["metrics"][1]
            assert "slo" in live["snapshot"]
            # (a) SLO exactness against the admission oracle
            shed_oracle = srv.shed_count
            assert shed_oracle == 23 - 4  # keep-the-newest suffix
            assert srv.slo.breaches(flooder) == shed_oracle
            assert srv.slo.route_counts(flooder)["shed"] == \
                shed_oracle
            for d in neighbors:
                assert srv.slo.breaches(d) == 0
            sr = srv.slo.report()
            assert sr["total_breaches"] == shed_oracle
            assert sr["tenants"][flooder]["burn_rate"] > 0.5
            # every tenant's serves are in the route mix
            assert rep.docs == sum(
                sum(t["routes"][r] for r in
                    ("delta", "cold", "fallback"))
                for t in sr["tenants"].values()
            )
            # (b) the double-buffered legs overlapped, measurably
            recs = tl.records()
            dbl = [r for r in recs if len(r["dispatches"]) > 1]
            assert dbl, "no double-buffered tick recorded"
            for r in dbl:
                assert r["overlap_efficiency"] > 0.0, r
            pf = tl.to_perfetto()
            for ev in pf["traceEvents"]:
                for k in ("name", "ph", "ts", "pid", "tid"):
                    assert k in ev
                if ev["ph"] == "X":
                    assert ev["dur"] >= 0
            names = {e["name"] for e in pf["traceEvents"]}
            assert any(n.startswith("dispatch(") for n in names)
            assert "prepare" in names and "settle" in names
            # the endpoint serves the SAME timeline
            with urlopen(obs.url + "/timeline", timeout=10) as r:
                served_pf = json.loads(r.read().decode())
            assert len(served_pf["traceEvents"]) >= len(
                pf["traceEvents"]
            )
        finally:
            try:
                obs.stop()
            except Exception as exc:  # pragma: no cover
                obs_stop_exc = exc
        assert obs_stop_exc is None


# ---------------------------------------------------------------------------
# round 18: obsq CLI round-trip
# ---------------------------------------------------------------------------


class TestObsqCLI:
    @pytest.fixture
    def dumps(self, tmp_path):
        a = FlightRecorder(enabled=True)
        b = FlightRecorder(enabled=True)
        # process A originates two updates; B receives them one hop
        # later (ts offsets are synthetic but monotone per ring)
        a.record("update.send", topic="room", replica="A", size=10,
                 digest="d1", tid=[1, 1, 100.0], hop=0)
        a.record("update.send", topic="room", replica="A", size=12,
                 digest="d2", tid=[1, 2, 100.5], hop=0)
        b.record("update.recv", topic="room", replica="B", peer="A",
                 size=10, digest="d1", tid=[1, 1, 100.0], hop=1)
        b.record("update.recv", topic="room", replica="B", peer="A",
                 size=12, digest="d2", tid=[1, 2, 100.5], hop=1)
        b.record("divergence", topic="room", replica="B", peer="A",
                 local_digest="xx", peer_digest="yy")
        pa, pb = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        a.dump_jsonl(str(pa))
        b.dump_jsonl(str(pb))
        return str(pa), str(pb)

    def _run(self, capsys, *argv):
        import obsq_under_test as obsq

        rc = obsq.main(list(argv))
        out = capsys.readouterr().out
        return rc, out

    @pytest.fixture(autouse=True)
    def _import_obsq(self, monkeypatch):
        import importlib
        import os
        import sys as _sys

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        monkeypatch.syspath_prepend(os.path.join(repo, "tools"))
        mod = importlib.import_module("obsq")
        _sys.modules.setdefault("obsq_under_test", mod)

    def test_filter_by_kind_doc_tid(self, capsys, dumps):
        pa, pb = dumps
        rc, out = self._run(capsys, "filter", pa, pb,
                            "--kind", "update.recv")
        assert rc == 0
        evs = [json.loads(ln) for ln in out.splitlines()]
        assert len(evs) == 2
        assert all(e["kind"] == "update.recv" for e in evs)
        assert all(e["_src"] == "b.jsonl" for e in evs)
        # --doc matches the topic field; --tid is a client:seq prefix
        rc, out = self._run(capsys, "filter", pa, pb,
                            "--doc", "room", "--tid", "1:2")
        evs = [json.loads(ln) for ln in out.splitlines()]
        assert {e["kind"] for e in evs} == \
            {"update.send", "update.recv"}
        assert all(e["tid"][:2] == [1, 2] for e in evs)
        rc, out = self._run(capsys, "filter", pa,
                            "--doc", "elsewhere")
        assert rc == 0 and out.strip() == ""

    def test_summary(self, capsys, dumps):
        rc, out = self._run(capsys, "summary", *dumps)
        assert rc == 0
        s = json.loads(out)
        assert s["events"] == 5
        assert s["kinds"]["update.send"] == 2
        assert s["kinds"]["divergence"] == 1
        assert s["sources"] == {"a.jsonl": 2, "b.jsonl": 3}

    def test_latency_pairs_across_dumps(self, capsys, dumps):
        rc, out = self._run(capsys, "latency", *dumps)
        assert rc == 0
        lat = json.loads(out)
        assert lat["sends"] == 2
        assert lat["pairs"] == 2
        assert lat["unmatched_recv"] == 0
        assert lat["propagation"]["count"] == 2
        assert lat["hops"] == {"1": 2}

    def test_diverge_correlates(self, capsys, dumps):
        rc, out = self._run(capsys, "diverge", *dumps,
                            "--context", "2")
        assert rc == 0
        dv = json.loads(out)
        assert dv["divergences"] == 1
        ev = dv["events"][0]
        assert ev["divergence"]["local_digest"] == "xx"
        assert set(ev["context"]) == {"a.jsonl", "b.jsonl"}
        assert all(len(c) <= 2 for c in ev["context"].values())
        # both sides saw d1/d2 before the fork — the common tail
        assert "d2" in ev["last_common_digests"]

    def test_unreadable_input_exits_2(self, capsys, tmp_path):
        import obsq_under_test as obsq

        rc = obsq.main(["summary", str(tmp_path / "missing.jsonl")])
        assert rc == 2


class TestLabelEscaping:
    def test_hostile_label_values_cannot_corrupt_exposition(self):
        """Round 18 made label values caller-controlled (doc ids ->
        tenant= labels): quotes, backslashes and newlines must escape
        per the exposition spec, never inject lines or break parse."""
        tr = Tracer(enabled=True)
        tr.count("tenant.shed", 1, labels={"tenant": 'a"b'})
        tr.count("tenant.shed", 2, labels={"tenant": "c\\d"})
        tr.count("tenant.shed", 3,
                 labels={"tenant": "evil\nfake_metric 9"})
        text = to_prometheus(tr.report())
        assert 'crdt_tenant_shed{tenant="a\\"b"} 1' in text
        assert 'crdt_tenant_shed{tenant="c\\\\d"} 2' in text
        # the newline is escaped INTO the value: no injected line
        assert "\nfake_metric 9" not in text
        assert 'tenant="evil\\nfake_metric 9"' in text
        # every non-comment line still parses as `name{...} value`
        for ln in text.splitlines():
            if ln.startswith("#"):
                continue
            float(ln.rsplit(" ", 1)[1])  # the value field parses
            assert ln.count("{") <= 1

    def test_slo_ledger_with_hostile_doc_id(self, installed):
        from crdt_tpu.obs.slo import SLOLedger

        led = SLOLedger(slo_ms=0.0)
        led.served('doc"with"quotes', [1e-3])
        text = to_prometheus()
        assert 'tenant="doc\\"with\\"quotes"' in text


class TestEventsLimitSemantics:
    def test_limit_zero_returns_nothing(self):
        from crdt_tpu.obs.http import _filter_events

        evs = [{"kind": "a"}, {"kind": "b"}, {"kind": "c"}]
        assert _filter_events(evs, {"limit": ["0"]}) == []
        assert _filter_events(evs, {"limit": ["2"]}) == evs[-2:]
        # over-large and garbage limits degrade to "all"
        assert _filter_events(evs, {"limit": ["99"]}) == evs
        assert _filter_events(evs, {"limit": ["x"]}) == evs


class TestObsqExitCodes:
    def test_malformed_jsonl_exits_2(self, tmp_path, capsys,
                                     monkeypatch):
        import importlib
        import os

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        monkeypatch.syspath_prepend(os.path.join(repo, "tools"))
        obsq = importlib.import_module("obsq")
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"kind": "a"}\nnot json at all\n')
        rc = obsq.main(["summary", str(bad)])
        assert rc == 2
        err = capsys.readouterr().err
        assert "not JSONL" in err
