"""The observability layer (crdt_tpu/obs): histogram math, tracer
thread-safety, flight recorder, Prometheus exposition, divergence
sentinel, trace-id propagation, jax_profile hardening."""

import json
import sys
import threading
import time

import pytest

from crdt_tpu.obs import (
    FlightRecorder,
    Tracer,
    get_recorder,
    get_tracer,
    set_recorder,
    set_tracer,
    snapshot_json,
    to_prometheus,
)
from crdt_tpu.obs.tracer import BUCKET_EDGES_S, N_BUCKETS, bucket_index


@pytest.fixture
def installed():
    """Enabled global tracer + recorder, restored afterwards."""
    old_t, old_r = get_tracer(), get_recorder()
    tr = set_tracer(Tracer(enabled=True))
    rec = set_recorder(FlightRecorder(enabled=True))
    try:
        yield tr, rec
    finally:
        set_tracer(old_t)
        set_recorder(old_r)


# ---------------------------------------------------------------------------
# histogram bucket math (the edges are a contract: Prometheus les)
# ---------------------------------------------------------------------------


class TestHistogramBuckets:
    def test_edges_are_powers_of_two_microseconds(self):
        assert BUCKET_EDGES_S[0] == 1e-6
        for k in range(1, N_BUCKETS):
            assert BUCKET_EDGES_S[k] == 2 * BUCKET_EDGES_S[k - 1]

    def test_bucket_index_at_edges_is_upper_inclusive(self):
        # an observation exactly AT an edge lands in that edge's bucket
        for k in (0, 1, 5, 17, N_BUCKETS - 1):
            assert bucket_index(BUCKET_EDGES_S[k]) == k
        # just above an edge spills into the next bucket
        for k in (0, 3, 20):
            assert bucket_index(BUCKET_EDGES_S[k] * 1.0000001) == k + 1

    def test_below_floor_and_overflow(self):
        assert bucket_index(0.0) == 0
        assert bucket_index(-1.0) == 0  # clock skew: clamp, not crash
        assert bucket_index(5e-7) == 0
        assert bucket_index(1e9) == N_BUCKETS  # +Inf bucket

    def test_single_observation_quantiles_equal_max(self):
        tr = Tracer(enabled=True)
        tr.observe("x", 3e-6)  # inside (2e-6, 4e-6]: edge=4e-6 > max
        s = tr.report()["spans"]["x"]
        # the bucket-edge estimate is clamped to the observed max
        assert s["p50_s"] == s["p99_s"] == s["max_s"] == 3e-6

    def test_tail_separates_from_median(self):
        tr = Tracer(enabled=True)
        for _ in range(99):
            tr.observe("x", 1e-3)
        tr.observe("x", 1.0)
        s = tr.report()["spans"]["x"]
        assert s["count"] == 100
        assert s["p50_s"] <= 2e-3       # median in the 1ms bucket
        assert s["max_s"] == 1.0
        assert s["p99_s"] <= 2e-3       # rank 99 of 100 still 1ms...
        tr.observe("x", 1.0)            # ...until the tail thickens
        tr.observe("x", 1.0)
        s = tr.report()["spans"]["x"]
        assert s["p99_s"] >= 0.5        # now p99 lives in the 1s bucket

    def test_quantiles_are_monotone_and_bounded(self):
        import random

        rng = random.Random(7)
        tr = Tracer(enabled=True)
        for _ in range(500):
            tr.observe("x", rng.uniform(1e-6, 0.1))
        s = tr.report()["spans"]["x"]
        assert s["min_s"] <= s["p50_s"] * 2  # bucket resolution slack
        assert s["p50_s"] <= s["p90_s"] <= s["p99_s"] <= s["max_s"]

    def test_report_keeps_legacy_schema(self):
        tr = Tracer(enabled=True)
        with tr.span("merge"):
            pass
        s = tr.report()["spans"]["merge"]
        for k in ("count", "total_s", "mean_s", "max_s"):
            assert k in s  # the pinned pre-obs surface
        assert s["count"] == 1


# ---------------------------------------------------------------------------
# thread-safety: the satellite the old tracer failed
# ---------------------------------------------------------------------------


class TestTracerThreadSafety:
    def test_concurrent_spans_and_counters_are_exact(self):
        """8 threads hammer one tracer; totals must be EXACT. The old
        tracer's unlocked read-modify-write dicts lost updates under
        preemption (models/streaming.py decodes on a thread pool into
        the process-global tracer), which this pins at a switch
        interval tight enough to make the race near-certain."""
        tr = Tracer(enabled=True)
        threads, per = 8, 3000
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-6)
        try:
            def work():
                for _ in range(per):
                    tr.count("ops")
                    with tr.span("phase"):
                        pass
                    tr.observe("lag", 1e-5)

            ts = [threading.Thread(target=work) for _ in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            sys.setswitchinterval(old_interval)
        rep = tr.report()
        assert rep["counters"]["ops"] == threads * per
        assert rep["spans"]["phase"]["count"] == threads * per
        assert rep["spans"]["lag"]["count"] == threads * per
        # histogram buckets must account for every observation too
        assert sum(
            rep["spans"]["lag"]["buckets"].values()
        ) == threads * per

    def test_streaming_decode_pool_records_spans(self, installed):
        """The real seam: the chunked thread-pooled decode records
        into the process-global tracer from pool threads."""
        tr, _ = installed
        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord

        blobs = [
            v1.encode_update(
                [ItemRecord(client=c + 1, clock=k, parent_root="m",
                            key=f"k{k}", content=k)
                 for k in range(4)],
                DeleteSet(),
            )
            for c in range(8)
        ]
        from crdt_tpu.models.streaming import _Phases, stream_decode

        dec = stream_decode(blobs, chunk_blobs=2, ph=_Phases())
        assert len(dec["client"]) > 0
        spans = tr.report()["spans"]
        assert spans["decode"]["count"] >= len(blobs) // 2


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_wraparound_keeps_newest(self):
        fr = FlightRecorder(capacity=4, enabled=True)
        for i in range(10):
            fr.record("k", i=i)
        assert len(fr) == 4
        assert fr.recorded == 10
        assert [e["i"] for e in fr.events()] == [6, 7, 8, 9]
        # timestamps monotone oldest-first
        ts = [e["ts"] for e in fr.events()]
        assert ts == sorted(ts)

    def test_jsonl_dump_roundtrips(self, tmp_path):
        fr = FlightRecorder(capacity=8, enabled=True)
        fr.record("update.send", topic="t", size=12, digest="aa")
        fr.record("update.recv", topic="t", size=12, digest="aa")
        path = tmp_path / "dump.jsonl"
        text = fr.dump_jsonl(str(path))
        assert path.read_text() == text
        lines = [json.loads(ln) for ln in text.splitlines()]
        assert [e["kind"] for e in lines] == ["update.send", "update.recv"]
        assert all("ts" in e for e in lines)

    def test_disabled_records_nothing(self):
        fr = FlightRecorder(capacity=4, enabled=False)
        fr.record("k")
        assert len(fr) == 0 and fr.dump_jsonl() == ""

    def test_kind_filter(self):
        fr = FlightRecorder(enabled=True)
        fr.record("a")
        fr.record("b")
        fr.record("a")
        assert len(fr.events("a")) == 2


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


class TestPrometheusExport:
    def test_types_and_name_sanitization(self):
        tr = Tracer(enabled=True)
        tr.count("router.relay-sends")       # dot + dash -> _
        tr.gauge("9pending", 3)              # leading digit -> prefix
        with tr.span("converge.dispatch"):
            pass
        text = to_prometheus(tr.report())
        assert "# TYPE crdt_router_relay_sends counter" in text
        assert "crdt_router_relay_sends 1" in text
        assert "# TYPE crdt__9pending gauge" in text
        assert (
            "# TYPE crdt_converge_dispatch_seconds histogram" in text
        )
        assert "crdt_converge_dispatch_seconds_count 1" in text

    def test_histogram_buckets_cumulative_with_inf(self):
        tr = Tracer(enabled=True)
        tr.observe("x", 1e-6)
        tr.observe("x", 2e-6)
        tr.observe("x", 2e-6)
        tr.observe("x", 1e9)  # overflow
        text = to_prometheus(tr.report())
        assert 'crdt_x_seconds_bucket{le="1e-06"} 1' in text
        assert 'crdt_x_seconds_bucket{le="2e-06"} 3' in text
        assert 'crdt_x_seconds_bucket{le="+Inf"} 4' in text
        assert "crdt_x_seconds_count 4" in text
        # cumulative counts never decrease
        counts = [
            int(ln.rsplit(" ", 1)[1])
            for ln in text.splitlines() if "_bucket{" in ln
        ]
        assert counts == sorted(counts)

    def test_labeled_counters_pass_through(self):
        tr = Tracer(enabled=True)
        tr.count("bytes", 7, labels={"peer": "abc", "topic": "t"})
        text = to_prometheus(tr.report())
        assert 'crdt_bytes{peer="abc",topic="t"} 7' in text

    def test_one_type_line_per_metric_across_label_sets(self):
        # a duplicate TYPE line for one metric name is a fatal
        # exposition parse error: label variants group under ONE
        tr = Tracer(enabled=True)
        tr.count("bytes", 1, labels={"peer": "a"})
        tr.count("bytes", 2, labels={"peer": "b"})
        tr.gauge("depth", 3, labels={"topic": "x"})
        tr.gauge("depth", 4, labels={"topic": "y"})
        text = to_prometheus(tr.report())
        assert text.count("# TYPE crdt_bytes counter") == 1
        assert text.count("# TYPE crdt_depth gauge") == 1
        assert 'crdt_bytes{peer="a"} 1' in text
        assert 'crdt_bytes{peer="b"} 2' in text

    def test_json_snapshot_matches_report(self):
        tr = Tracer(enabled=True)
        tr.count("x")
        assert json.loads(snapshot_json(tr.report())) == json.loads(
            json.dumps(tr.report())
        )


# ---------------------------------------------------------------------------
# jax_profile hardening
# ---------------------------------------------------------------------------


class TestJaxProfile:
    def test_capture_works_on_cpu(self, tmp_path):
        import jax.numpy as jnp

        from crdt_tpu.utils.trace import jax_profile

        with jax_profile(str(tmp_path)):
            (jnp.arange(16) + 1).block_until_ready()

    def test_body_failure_stops_profiler(self, tmp_path):
        """A crash inside the block must stop the trace: the NEXT
        capture would otherwise fail with 'profiler already running'
        (the pre-obs bug class this satellite fixes)."""
        import jax.numpy as jnp

        from crdt_tpu.utils.trace import jax_profile

        with pytest.raises(RuntimeError, match="boom"):
            with jax_profile(str(tmp_path / "a")):
                raise RuntimeError("boom")
        with jax_profile(str(tmp_path / "b")):  # must not raise
            (jnp.arange(4) * 2).block_until_ready()

    def test_clear_error_without_profiler(self, monkeypatch):
        import types

        from crdt_tpu.utils.trace import jax_profile

        monkeypatch.setitem(
            sys.modules, "jax", types.SimpleNamespace()
        )
        with pytest.raises(RuntimeError, match="profiler unavailable"):
            with jax_profile("/tmp/never"):
                pass


# ---------------------------------------------------------------------------
# divergence sentinel + trace-id propagation (loopback fabric)
# ---------------------------------------------------------------------------


def _pair(net=None, **kw):
    from crdt_tpu.net import LoopbackNetwork, LoopbackRouter, Replica

    net = net or LoopbackNetwork()
    r1 = Replica(LoopbackRouter(net, "a"), topic="t", client_id=1, **kw)
    r2 = Replica(LoopbackRouter(net, "b"), topic="t", client_id=2, **kw)
    net.run()
    return net, r1, r2


class TestDivergenceSentinel:
    def test_silent_on_fault_free_run(self, installed):
        net, r1, r2 = _pair()
        for i in range(6):
            (r1 if i % 2 else r2).set("kv", f"k{i}", i)
        net.run()
        assert dict(r1.c) == dict(r2.c)
        r1.beacon()
        r2.beacon()
        net.run()
        assert r1.sentinel.events == [] and r2.sentinel.events == []
        tr, _ = installed
        assert tr.counters()["sentinel.agree"] >= 2
        # mutate again (invalidates the cached digest), re-beacon:
        # still silent, still agreeing on the NEW state
        r1.set("kv", "fresh", 99)
        net.run()
        r1.beacon()
        r2.beacon()
        net.run()
        assert r1.sentinel.events == [] and r2.sentinel.events == []
        assert tr.counters()["sentinel.agree"] >= 4

    def test_fires_on_injected_state_fork(self, installed):
        from crdt_tpu.net.faults import ForkFault

        tr, rec = installed
        net, r1, r2 = _pair()
        r1.set("kv", "k", 1)
        net.run()
        assert dict(r1.c) == dict(r2.c)
        # seeded fork: same id, different content, equal SVs — the
        # sync protocol sees nothing; only the beacon can
        fork = ForkFault(seed=3)
        fork.inject([r1, r2])
        assert r1.doc.state_vector() == r2.doc.state_vector()
        assert dict(r1.c) != dict(r2.c)
        r1.beacon()
        net.run()
        assert len(r2.sentinel.events) == 1
        ev = r2.sentinel.events[0]
        assert ev["kind"] == "divergence"
        assert ev["peer"] == "a" and ev["topic"] == "t"
        assert ev["local_digest"] != ev["peer_digest"]
        # the event carries a flight-recorder dump with the fork in it
        kinds = [
            json.loads(ln)["kind"]
            for ln in ev["flight_recorder"].splitlines()
        ]
        assert "fault.fork" in kinds
        assert tr.counters()["sentinel.divergence"] == 1
        # a permanent fork is raised ONCE per peer: later beacons of
        # the same fork bump the counter but never re-event (no
        # unbounded event/dump growth on a long-lived divergence)
        r1.beacon()
        net.run()
        assert len(r2.sentinel.events) == 1
        assert tr.counters()["sentinel.divergence"] == 2

    def test_sv_lag_stays_silent(self, installed):
        """Unequal SVs (ops in flight) are lag, not divergence."""
        net, r1, r2 = _pair()
        r1.set("kv", "k", 1)
        # beacon BEFORE delivery: r2's SV is behind
        r1.beacon()
        net.run()
        assert r2.sentinel.events == []
        tr, _ = installed
        assert tr.counters().get("sentinel.divergence", 0) == 0

    def test_deterministic_fork_schedule(self):
        from crdt_tpu.net.faults import ForkFault

        a, b = ForkFault(seed=9), ForkFault(seed=9)
        assert (a.client, a.key) == (b.client, b.key)
        assert ForkFault(seed=10).client != a.client or \
            ForkFault(seed=10).key != a.key


class TestTraceIdPropagation:
    def test_tid_rides_updates_and_measures_lag(self, installed):
        tr, rec = installed
        net, r1, r2 = _pair()
        r1.set("kv", "k", 1)
        r2.push("log", "e")
        net.run()
        assert dict(r1.c) == dict(r2.c)
        sent = [tuple(e["tid"]) for e in rec.events("update.send")]
        recv = [
            tuple(e["tid"]) for e in rec.events("update.recv")
            if e.get("tid")
        ]
        assert sent and set(sent) <= set(recv)
        # tid = (client, seq, ts): origin client rides the stamp
        clients = {t[0] for t in sent}
        assert clients == {1, 2}
        spans = tr.report()["spans"]
        assert spans["replica.propagation_lag"]["count"] >= 2
        assert spans["replica.convergence_lag"]["count"] >= 2
        assert "replica.propagation_lag_s" in tr.report()["gauges"]

    def test_anti_entropy_beacon_detects_fork_on_udp(self, installed):
        """The acceptance pin: under a seeded fault schedule plus a
        seeded state fork, the sentinel riding the REAL anti-entropy
        cadence (UDP routers, chaos faults on the wire) raises a
        divergence event carrying a flight-recorder dump; the
        fault-free run stays silent."""
        from crdt_tpu.net.faults import (
            FaultSchedule, ForkFault, install_faults, pump_until,
        )
        from crdt_tpu.net.replica import Replica
        from crdt_tpu.net.udp_router import UdpRouter

        def run(forked):
            routers = [UdpRouter() for _ in range(2)]
            routers[1].add_peer(*routers[0].addr)
            try:
                pump_until(
                    routers,
                    lambda: all(len(r.peers) == 1 for r in routers),
                    timeout_s=30.0,
                )
                reps = [
                    Replica(r, topic="room", client_id=i + 1,
                            probe_retry_s=0.05, anti_entropy_s=0.05)
                    for i, r in enumerate(routers)
                ]
                pump_until(
                    routers,
                    lambda: all(
                        len(r.peers_on("room")) == 1 for r in routers
                    ),
                    timeout_s=30.0,
                )
                if forked:
                    # chaos on the wire + the fork fault itself
                    for r in routers:
                        install_faults(
                            r, FaultSchedule(11, drop=0.05, delay=0.05)
                        )
                    ForkFault(seed=11).inject(reps)
                reps[0].set("kv", "x", 1)
                pump_until(
                    routers,
                    lambda: "kv" in reps[1].c,
                    timeout_s=30.0,
                )
                if forked:
                    pump_until(
                        routers,
                        lambda: any(r.sentinel.events for r in reps),
                        timeout_s=30.0,
                    )
                    events = [
                        e for r in reps for e in r.sentinel.events
                    ]
                    assert events[0]["kind"] == "divergence"
                    assert events[0]["flight_recorder"]
                else:
                    # let several anti-entropy/beacon rounds fire
                    deadline = time.monotonic() + 0.5
                    while time.monotonic() < deadline:
                        for r in routers:
                            r.poll()
                        time.sleep(0.002)
                    assert all(not r.sentinel.events for r in reps)
                    assert any(
                        r.sentinel.beacons_checked > 0 for r in reps
                    )
            finally:
                for r in routers:
                    r.close()

        run(forked=False)
        run(forked=True)
