"""Grand differential: every consumer of a trace agrees, always.

Random API-level traces (maps, sequences, nested arrays, deletes,
batches) from several writers, delivered with shuffles and duplicates,
flow through every merge surface the framework offers:

  - a scalar-mode document,
  - a device-mode document (CRDT_TPU_DEVICE semantics),
  - the firehose replay (crdt_tpu.models.replay_trace),
  - a fresh document rebuilt from the replay's compacted snapshot.

All four must land on the identical plain-JSON state, and the two
documents must be byte-identical (encoded state, delete sets). This is
the round-trip integration net over everything: codec (native + wire),
engine, kernels, device rebuild, resident union, materialization,
compaction.
"""

import random

from crdt_tpu.api.doc import Crdt
from crdt_tpu.models import replay_trace


def _random_trace(seed, n_writers=3, ops=40):
    rng = random.Random(seed)
    outs = [[] for _ in range(n_writers)]
    docs = []
    for i in range(n_writers):
        out = outs[i]
        # odd seeds use realistic random 31-bit client ids (the shape
        # that has repeatedly exposed packed-int64 aliasing bugs)
        cid = i + 1 if seed % 2 == 0 else rng.getrandbits(31)
        docs.append(Crdt(cid, on_update=lambda u, m, o=out: o.append(u)))

    def deliver_some():
        # partial, shuffled cross-delivery (records stay causal per
        # writer; duplicates exercise idempotence)
        blobs = [u for out in outs for u in out]
        rng.shuffle(blobs)
        take = blobs[: rng.randint(0, len(blobs))]
        for d in docs:
            for u in take:
                d.apply_update(u)

    for step in range(ops):
        d = docs[rng.randrange(n_writers)]
        op = rng.random()
        if op < 0.3:
            d.set("m", f"k{rng.randrange(8)}", rng.randrange(100))
        elif op < 0.45:
            d.delete("m", f"k{rng.randrange(8)}")
        elif op < 0.6:
            d.push("l", [step])
        elif op < 0.7:
            n = len(d.c.get("l", []))
            d.insert("l", rng.randint(0, n), f"i{step}")
        elif op < 0.78:
            n = len(d.c.get("l", []))
            if n:
                d.cut("l", rng.randrange(n))
        elif op < 0.88:
            d.set("cfg", "tags", f"t{step}", array_method=rng.choice(
                ["push", "unshift"]))
        elif op < 0.94:
            d.set("m", f"b{step}", step, batch=True)
            d.push("l", [f"b{step}"], batch=True)
            d.exec_batch()
        else:
            deliver_some()

    blobs = [u for out in outs for u in out]
    # delivery with duplication of a random prefix
    dup = blobs[: rng.randint(0, len(blobs))]
    return blobs + dup


def test_grand_differential():
    for seed in range(6):
        blobs = _random_trace(seed)
        scalar = Crdt(900 + seed, device_merge=False)
        device = Crdt(900 + seed, device_merge=True)
        scalar.apply_updates(blobs)
        device.apply_updates(blobs)

        assert dict(scalar.c) == dict(device.c), f"seed {seed}: doc modes"
        assert (
            scalar.encode_state_as_update() == device.encode_state_as_update()
        ), f"seed {seed}: encoded state"
        assert scalar.engine.delete_set() == device.engine.delete_set()

        res = replay_trace(blobs)
        assert res.cache == dict(scalar.c), f"seed {seed}: replay cache"

        fresh = Crdt(800 + seed)
        fresh.apply_update(res.snapshot)
        assert dict(fresh.c) == res.cache, f"seed {seed}: snapshot"
