"""Round-22 control plane: deterministic rules, auditable ledger.

The tentpole contract, pinned four ways:

- **Determinism** — the controller reads no wall clock, visits
  tenants in sorted order, and indexes every window/cooldown by the
  server's tick number, so replaying a recorded sensor trace through
  ``Controller.replay`` yields a ledger whose ``to_jsonl()`` is
  BYTE-identical to the original (pure-synthetic and server-driven).
- **Hysteresis** — an oscillating burn signal cannot flap a setpoint
  faster than ``cooldown_ticks`` (consecutive ledger rows for one
  knob are at least a cooldown apart, and the blocked attempts are
  counted as ``control.cooldown_skips``), and one clean tick is
  never enough to restore (``restore_after``).
- **Containment** — the seeded flood chaos leg: with the controller
  ON the flooding tenant is squeezed, trimmed, protected, and
  restored after the flood drains, while every NEIGHBOR digest stays
  byte-identical to a controller-OFF oracle run fed the same
  submissions.
- **Audit** — the ledger ring is bounded with drop accounting, the
  ``/control`` endpoint serves the report, the fleet collector
  federates proc-tagged advice, ``obsq control`` answers tick-ranged
  tenant queries offline (with an SLO join), and each decision lands
  on the Perfetto tick timeline as a ``cat: control`` instant.

Plus the satellite: checkpoint cadence through the actuation path —
a restart replays at most one cadence of WAL tail.
"""

import importlib
import json
import os
import sys as _sys
import urllib.request

import pytest

from crdt_tpu.codec import v1
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.models.multidoc import MultiDocServer
from crdt_tpu.obs import (
    FleetCollector,
    ObsHTTPServer,
    SLOLedger,
    TickTimeline,
    Tracer,
    set_timeline,
    set_tracer,
)
from crdt_tpu.obs.control import Actuation, Controller
from crdt_tpu.storage.snapshot import SnapshotStore


@pytest.fixture(autouse=True)
def _quiet_obs():
    """Each test starts from disabled process-global tracer/timeline
    (tests that want them enabled install their own)."""
    old_tracer = set_tracer(Tracer(enabled=False))
    old_tl = set_timeline(TickTimeline(enabled=False))
    yield
    set_tracer(old_tracer)
    set_timeline(old_tl)


def _sensors(tick, burn, *, tenant="flood!", shed=0, pending=0,
             max_rows=0, pending_total=0, settled=0):
    return {
        "tick": tick,
        "max_rows": max_rows,
        "pending_bytes": pending_total,
        "settled_bytes": settled,
        "budget": {"max_bytes": 2048, "max_updates": 4},
        "tenants": {tenant: {"burn": burn, "shed": shed,
                             "pending_bytes": pending}},
    }


def flood_blob(i):
    """One independent single-record update (own client, no origin:
    shedding any subset never orphans a survivor), sized between the
    divided-by-4 budget and the static 2048-byte one."""
    return v1.encode_update([ItemRecord(
        client=10_000 + i, clock=0, parent_root="m",
        key=f"f{i}", content="f" * 700,
    )], DeleteSet())


def chain_blob(client, k0, n_ops=4):
    """One tenant's chained list appends (clocks k0..k0+n_ops-1)."""
    recs = []
    for j in range(n_ops):
        k = k0 + j
        recs.append(ItemRecord(
            client=client, clock=k, parent_root="l",
            origin=(client, k - 1) if k else None,
            content=client * 1000 + k,
        ))
    return v1.encode_update(recs, DeleteSet())


# ---- the pure rule engine -------------------------------------------


class TestRules:
    def test_squeeze_then_restore_with_hysteresis(self):
        c = Controller(cooldown_ticks=0, restore_after=3)
        act = c.observe(_sensors(0, 1.0))
        assert isinstance(act, Actuation)
        assert act.tenant_limits == {"flood!": (512, 1)}
        assert act.protect == frozenset({"flood!"})
        assert [r["rule"] for r in act.rows] == ["budget_squeeze"]
        # two clean ticks are NOT enough (restore_after=3)
        for t in (1, 2):
            act = c.observe(_sensors(t, 0.0))
            assert act.rows == [] and c.overrides()
        act = c.observe(_sensors(3, 0.0))
        assert [r["rule"] for r in act.rows] == ["budget_restore"]
        assert act.tenant_limits == {} and act.protect == frozenset()
        row = act.rows[0]
        assert row["tenant"] == "flood!"
        assert row["old"] == [512, 1] and row["new"] == [2048, 4]

    def test_dirty_tick_resets_clean_streak(self):
        c = Controller(cooldown_ticks=0, restore_after=2)
        c.observe(_sensors(0, 1.0))
        c.observe(_sensors(1, 0.0))
        c.observe(_sensors(2, 0.9))  # streak resets
        c.observe(_sensors(3, 0.0))
        assert c.overrides()  # one clean tick since the reset
        act = c.observe(_sensors(4, 0.0))
        assert [r["rule"] for r in act.rows] == ["budget_restore"]

    def test_hysteresis_pin_no_flap_faster_than_cooldown(self):
        """The ISSUE pin: a burn square wave cannot flap the tenant
        budget faster than ``cooldown_ticks``, and every blocked
        attempt is counted."""
        cd = 5
        c = Controller(cooldown_ticks=cd, restore_after=1)
        for t in range(40):
            c.observe(_sensors(t, 1.0 if t % 2 == 0 else 0.0))
        ticks = [r["tick"] for r in c.ledger.rows()
                 if r["knob"] == "tenant_budget"]
        assert len(ticks) >= 4
        assert all(b - a >= cd for a, b in zip(ticks, ticks[1:]))
        assert c.cooldown_skips > 0
        # the flap cadence is exactly the cooldown here: squeeze at
        # 0, restore at 5, squeeze at 10, ...
        assert ticks[:4] == [0, cd, 2 * cd, 3 * cd]

    def test_ledger_bounded_with_drop_accounting(self):
        c = Controller(cooldown_ticks=5, restore_after=1,
                       ledger_capacity=2)
        for t in range(40):
            c.observe(_sensors(t, 1.0 if t % 2 == 0 else 0.0))
        rows = c.ledger.rows()
        assert len(rows) == 2
        assert c.ledger.total == c.decisions
        assert c.ledger.dropped == c.ledger.total - 2
        assert c.ledger.dropped > 0
        # tail keeps the NEWEST rows
        assert [r["tick"] for r in rows] == \
            [r["tick"] for r in c.ledger.tail(8)]
        assert rows[-1]["tick"] == max(r["tick"] for r in rows)

    def test_rows_pacing_squeeze_floor_restore(self):
        c = Controller(cooldown_ticks=0, restore_after=2,
                       pace_pending_bytes=1000, rows_floor=4)
        seen = []
        for t in range(4):  # sustained pressure: 16 -> 8 -> 4, floor
            act = c.observe(_sensors(t, 0.0, max_rows=16,
                                     pending_total=5000))
            seen.append(act.max_rows)
        assert seen == [8, 4, None, None]  # floor holds, no churn
        # calm below half the threshold for restore_after ticks
        c.observe(_sensors(4, 0.0, max_rows=16, pending_total=100))
        act = c.observe(_sensors(5, 0.0, max_rows=16,
                                 pending_total=100))
        assert act.max_rows == 16
        rules = [r["rule"] for r in c.ledger.rows()]
        assert rules == ["rows_squeeze", "rows_squeeze",
                         "rows_restore"]

    def test_rows_pacing_off_without_threshold(self):
        c = Controller(cooldown_ticks=0)
        act = c.observe(_sensors(0, 0.0, max_rows=16,
                                 pending_total=1 << 30))
        assert act.max_rows is None and act.rows == []

    def test_checkpoint_cadence_by_ticks_and_bytes(self):
        c = Controller(checkpoint_every_ticks=4)
        fires = [c.observe(_sensors(t, 0.0)).checkpoint
                 for t in range(9)]
        assert fires == [False, False, False, False, True,
                         False, False, False, True]
        b = Controller(checkpoint_every_bytes=100)
        assert not b.observe(_sensors(0, 0.0, settled=60)).checkpoint
        act = b.observe(_sensors(1, 0.0, settled=120)).checkpoint
        assert act  # 120 - 0 >= 100
        # the odometer mark moved: 40 more settled bytes are not due
        assert not b.observe(_sensors(2, 0.0, settled=160)).checkpoint
        by = [r["sensors"]["by"] for r in b.ledger.rows()]
        assert by == ["bytes"]

    def test_replay_is_byte_identical_and_report_shape(self):
        c = Controller(cooldown_ticks=3, restore_after=2,
                       ledger_capacity=2,
                       pace_pending_bytes=1000, rows_floor=2,
                       checkpoint_every_ticks=5)
        for t in range(14):
            burn = 1.0 if t in (0, 4, 5, 6) else 0.0
            c.observe(_sensors(t, burn, shed=4 * t,
                               max_rows=16,
                               pending_total=5000 if t < 3 else 0,
                               settled=64 * t))
        assert c.decisions > 4 and c.cooldown_skips > 0
        assert c.ledger.dropped == c.ledger.total - 2
        r = Controller.replay(list(c.trace), **c.config())
        assert r.ledger.to_jsonl() == c.ledger.to_jsonl()
        assert r.decisions == c.decisions
        assert r.cooldown_skips == c.cooldown_skips
        rep = c.report(limit=1)
        assert rep["config"] == c.config()
        assert len(rep["rows"]) == 1
        assert rep["ledger_dropped"] == c.ledger.dropped
        json.dumps(rep)  # JSON-ready end to end

    def test_advice_rows_for_squeezed_tenants(self):
        c = Controller(cooldown_ticks=0)
        assert c.advice() == []
        c.observe({
            "tick": 7,
            "budget": {"max_bytes": 2048, "max_updates": 4},
            "tenants": {"b!": {"burn": 1.0},
                        "a!": {"burn": 0.9},
                        "ok": {"burn": 0.0}},
        })
        adv = c.advice()
        assert [a["tenant"] for a in adv] == ["a!", "b!"]  # sorted
        assert all(a["action"] == "rebalance_away" and
                   a["since_tick"] == 7 for a in adv)

    def test_counters_and_setpoint_gauges(self):
        tracer = set_tracer(Tracer(enabled=True))
        c = Controller(cooldown_ticks=3, restore_after=2,
                       ledger_capacity=2)
        for t in range(14):
            c.observe(_sensors(t, 1.0 if t in (0, 4, 5, 6) else 0.0))
        counters = tracer.counters()
        assert counters["control.decisions"] == c.decisions
        assert counters["control.cooldown_skips"] == c.cooldown_skips
        assert counters["control.ledger_dropped"] == c.ledger.dropped
        assert counters['control.decisions{rule="budget_squeeze"}'] \
            >= 1
        assert counters['control.decisions{rule="budget_restore"}'] \
            >= 1
        assert any(k.startswith("control.setpoint{knob=")
                   for k in tracer.report()["gauges"])


# ---- the server integration (chaos flood vs OFF oracle) -------------


def _flood_run(on, *, flood_ticks=4, calm_ticks=16, neighbors=2):
    ctrl = (Controller(cooldown_ticks=4, restore_after=2)
            if on else None)
    srv = MultiDocServer(
        tenant_max_pending_bytes=2048,
        tenant_max_pending_updates=4,
        slo_ms=1e9,  # serves never breach: sheds drive burn
        control=ctrl,
    )
    srv.slo = SLOLedger(1e9, burn_window=16)
    docs = [f"n{i}" for i in range(neighbors)]
    clocks = {d: 0 for d in docs}
    clocks["flood!"] = 0
    nblob = 0
    burns = []
    for t in range(flood_ticks + calm_ticks):
        if t < flood_ticks:
            for _ in range(8):
                srv.submit("flood!", flood_blob(nblob))
                nblob += 1
        else:
            srv.submit("flood!", chain_blob(500, clocks["flood!"], 2))
            clocks["flood!"] += 2
        for i, d in enumerate(docs):
            srv.submit(d, chain_blob(600 + i, clocks[d], 3))
            clocks[d] += 3
        srv.tick()
        burns.append(srv.slo.report()["tenants"].get(
            "flood!", {}).get("burn_rate", 0.0))
    return srv, ctrl, docs, burns, flood_ticks


@pytest.mark.slow
class TestServerChaos:
    def test_flood_squeezed_neighbors_byte_identical_to_oracle(self):
        srv_on, ctrl, docs, burns_on, ft = _flood_run(True)
        srv_off, _, _, burns_off, _ = _flood_run(False)
        rules = [r["rule"] for r in ctrl.ledger.rows()]
        assert "budget_squeeze" in rules
        assert "budget_restore" in rules
        assert not ctrl.overrides()  # restored by the end
        # the flooder never starves (keep-the-newest trim serves one
        # blob per flood tick) but its burn breaches during the flood
        # and drains below the restore threshold within the window
        assert burns_on[ft - 1] >= ctrl.burn_hi
        recovery = next(k for k in range(len(burns_on) - ft)
                        if burns_on[ft + k] <= ctrl.burn_lo)
        assert recovery <= 16
        # neighbors: byte-identical to the controller-OFF oracle
        for d in docs:
            assert srv_on.digest(d) == srv_off.digest(d)
        # ... and the flood was actually contained: the squeezed run
        # sheds MORE flooder updates than the static-budget oracle
        assert srv_on.shed_count > srv_off.shed_count

    def test_server_driven_ledger_replays_byte_identical(self):
        _, ctrl, _, _, _ = _flood_run(True)
        replayed = Controller.replay(list(ctrl.trace),
                                     **ctrl.config())
        assert replayed.ledger.to_jsonl() == ctrl.ledger.to_jsonl()

    def test_squeeze_trims_backlog_and_protects_docs(self):
        ctrl = Controller(cooldown_ticks=4, restore_after=2)
        srv = MultiDocServer(
            tenant_max_pending_bytes=2048,
            tenant_max_pending_updates=4,
            slo_ms=1e9, control=ctrl,
        )
        srv.slo = SLOLedger(1e9, burn_window=16)
        for t in range(2):
            for i in range(8):
                srv.submit("flood!", flood_blob(8 * t + i))
            srv.tick()
        assert ctrl.overrides() == {"flood!": (512, 1)}
        assert srv.budget.overrides() == {"flood!": (512, 1)}
        assert srv._protected == {"flood!"}
        # immediate containment: the backlog fits the SQUEEZED budget
        st = srv._docs["flood!"]
        backlog = sum(len(b) for b in st.pending)
        assert len(st.pending) <= 1 and backlog <= 717

    def test_timeline_instants_and_perfetto_category(self):
        tl = set_timeline(TickTimeline(enabled=True))
        _flood_run(True, flood_ticks=2, calm_ticks=0)
        names = [n for rec in tl.records()
                 for n, _, _ in rec.get("instants", ())]
        assert "control:budget_squeeze" in names
        trace = tl.to_perfetto(pid=7)
        inst = [e for e in trace["traceEvents"]
                if e.get("ph") == "i" and e.get("cat") == "control"]
        assert inst and inst[0]["name"].startswith("control:")
        assert inst[0]["args"]["knob"] == "tenant_budget"


# ---- checkpoint cadence + restart (satellite 1) ---------------------


@pytest.mark.slow
class TestCadenceRestart:
    def test_cadence_checkpoints_bound_the_wal_tail(self, tmp_path):
        cadence, total = 3, 10
        store = SnapshotStore(str(tmp_path))
        srv = MultiDocServer(snap_store=store,
                             checkpoint_every_ticks=cadence)
        assert srv.control is not None  # cadence implies a controller
        blobs = [chain_blob(42, 4 * k) for k in range(total)]
        for b in blobs:
            srv.submit("w", b)
            srv.tick()
        assert srv.cadence_checkpoints >= total // cadence
        manifest = json.loads(store.get_blob("checkpoint.manifest"))
        seq = manifest["w"]["seq"]
        # the restart bound: at most one cadence of WAL tail
        assert total - cadence <= seq <= total
        # restart: fresh server restores the snapshot and replays
        # ONLY the tail — the digest matches a full-history oracle
        srv2 = MultiDocServer(snap_store=SnapshotStore(str(tmp_path)))
        assert srv2.restore() == 1
        assert len(srv2._docs["w"].blobs) == 1  # consolidated
        for b in blobs[seq:]:
            srv2.submit("w", b)
        srv2.tick()
        oracle = MultiDocServer(snap_store=None)
        for b in blobs:
            oracle.submit("w", b)
        oracle.tick()
        assert srv2.digest("w") == oracle.digest("w")

    def test_cadence_by_bytes_fires_on_settled_odometer(self,
                                                        tmp_path):
        blob = chain_blob(7, 0)
        srv = MultiDocServer(snap_store=SnapshotStore(str(tmp_path)),
                             checkpoint_every_bytes=2 * len(blob))
        for k in range(8):
            srv.submit("w", chain_blob(7, 4 * k))
            srv.tick()
        assert srv.cadence_checkpoints >= 2
        by = [r["sensors"]["by"]
              for r in srv.control.ledger.rows()]
        assert set(by) == {"bytes"}


# ---- /control endpoint + fleet federation ---------------------------


def _squeezed_controller():
    c = Controller(cooldown_ticks=2)
    for t in range(4):
        c.observe(_sensors(t, 1.0, shed=8 * (t + 1), pending=4096))
    return c


class TestControlEndpoint:
    def test_control_report_served_with_limit(self):
        ctrl = _squeezed_controller()
        obs = ObsHTTPServer(port=0, control=ctrl).start()
        try:
            body = urllib.request.urlopen(
                obs.url + "/control", timeout=5).read()
            rep = json.loads(body)
            assert rep["decisions"] == ctrl.decisions
            assert rep["setpoints"]["tenants"] == {
                "flood!": [512, 1]}
            assert rep["advice"][0]["action"] == "rebalance_away"
            assert rep["rows"]
            one = json.loads(urllib.request.urlopen(
                obs.url + "/control?limit=1", timeout=5).read())
            assert len(one["rows"]) == 1
        finally:
            obs.stop()

    def test_control_404_without_controller(self):
        obs = ObsHTTPServer(port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(obs.url + "/control",
                                       timeout=5)
            assert ei.value.code == 404
        finally:
            obs.stop()

    def test_fleet_collector_federates_advice_and_ledger_tail(self):
        ctrl = _squeezed_controller()
        obs = ObsHTTPServer(port=0, control=ctrl).start()
        plain = ObsHTTPServer(port=0).start()  # control-less: 404 ok
        try:
            col = FleetCollector()
            col.add_proc("p1", obs.url)
            col.add_proc("p2", plain.url)
            ok = col.scrape()
            assert ok == {"p1": True, "p2": True}
            rep = col.fleet_report()
            assert rep["control"]["p1"]["rows"]
            assert rep["control"].get("p2") in (None, {})
            adv = [a for a in rep["advice"] if a["proc"] == "p1"]
            assert adv and adv[0]["action"] == "rebalance_away"
            assert adv[0]["tenant"] == "flood!"
        finally:
            obs.stop()
            plain.stop()


# ---- obsq control (satellite 2) -------------------------------------


class TestObsqControl:
    @pytest.fixture(autouse=True)
    def _import_obsq(self, monkeypatch):
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        monkeypatch.syspath_prepend(os.path.join(repo, "tools"))
        mod = importlib.import_module("obsq")
        _sys.modules.setdefault("obsq_under_test", mod)
        self.obsq = mod

    def _run(self, capsys, *argv):
        rc = self.obsq.main(list(argv))
        return rc, capsys.readouterr().out

    def _dump(self, tmp_path):
        ctrl = Controller(cooldown_ticks=3, restore_after=2)
        for t in range(10):
            burn = 1.0 if t in (0, 4) else 0.0
            ctrl.observe(_sensors(t, burn, shed=4 * t))
        path = str(tmp_path / "ledger.jsonl")
        n = ctrl.ledger.dump_jsonl(path)
        assert n == ctrl.ledger.total
        return path, ctrl

    def test_tenant_and_tick_range_filter(self, tmp_path, capsys):
        path, ctrl = self._dump(tmp_path)
        rc, out = self._run(capsys, "control", path,
                            "--tenant", "flood!")
        assert rc == 0
        rows = [json.loads(ln) for ln in out.splitlines()]
        assert rows and all(r["tenant"] == "flood!" for r in rows)
        assert [r["tick"] for r in rows] == \
            sorted(r["tick"] for r in rows)
        lo, hi = rows[0]["tick"], rows[0]["tick"]
        rc, out = self._run(capsys, "control", path,
                            "--tick-range", f"{lo}:{hi}")
        assert rc == 0
        windowed = [json.loads(ln) for ln in out.splitlines()]
        assert windowed and all(lo <= r["tick"] <= hi
                                for r in windowed)
        assert len(windowed) < len(ctrl.ledger.rows())

    def test_slo_join_answers_why(self, tmp_path, capsys):
        """The ISSUE's audit question: *why did tenant T's budget
        drop at tick N* — the row carries the decision AND the
        tenant's SLO summary, joined offline."""
        path, _ = self._dump(tmp_path)
        slo = SLOLedger(1e9, burn_window=8)
        for _ in range(6):
            slo.shed("flood!", 1)
        slo_path = str(tmp_path / "slo.json")
        with open(slo_path, "w") as f:
            json.dump(slo.report(), f)
        rc, out = self._run(capsys, "control", path,
                            "--tenant", "flood!", "--slo", slo_path)
        assert rc == 0
        rows = [json.loads(ln) for ln in out.splitlines()]
        assert rows
        assert rows[0]["slo"]["burn_rate"] == 1.0
        assert rows[0]["rule"] == "budget_squeeze"

    def test_live_control_url_source(self, tmp_path, capsys):
        ctrl = _squeezed_controller()
        obs = ObsHTTPServer(port=0, control=ctrl).start()
        try:
            rc, out = self._run(capsys, "control", obs.url)
            assert rc == 0
            rows = [json.loads(ln) for ln in out.splitlines()]
            assert rows and rows[0]["rule"] == "budget_squeeze"
            assert all("_src" in r for r in rows)
        finally:
            obs.stop()

    def test_unreadable_input_exits_2(self, tmp_path, capsys):
        rc, _ = self._run(capsys, "control",
                          str(tmp_path / "missing.jsonl"))
        assert rc == 2
