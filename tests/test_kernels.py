"""Differential tests: device kernels vs the host oracle on the same
columnar inputs (SURVEY.md §7 stage 3: validate every kernel against
stage 2)."""

import random

import jax.numpy as jnp
import numpy as np

from crdt_tpu.core.engine import Engine
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.ops import deleteset as ds_ops
from crdt_tpu.ops import statevec
from crdt_tpu.ops.device import (
    NULLI,
    dense_ranks_sorted,
    lexsort,
    pack_id,
    pointer_double,
    searchsorted_ids,
    unpack_id,
)
from crdt_tpu.ops.merge import merge_records


# ---------------------------------------------------------------------------
# primitive helpers
# ---------------------------------------------------------------------------

def test_pack_unpack_roundtrip():
    clients = jnp.array([0, 1, 2**20, -1], jnp.int32)
    clocks = jnp.array([0, 5, 2**35, -1], jnp.int64)
    packed = pack_id(clients, clocks)
    assert int(packed[3]) == NULLI
    c, k = unpack_id(packed)
    assert list(c[:3]) == [0, 1, 2**20]
    assert list(k[:3]) == [0, 5, 2**35]
    # ordering: (client, clock) lexicographic == packed numeric
    a = pack_id(jnp.array([1], jnp.int32), jnp.array([2**39], jnp.int64))
    b = pack_id(jnp.array([2], jnp.int32), jnp.array([0], jnp.int64))
    assert int(a[0]) < int(b[0])


def test_lexsort_matches_numpy():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 5, 100)
    b = rng.integers(0, 5, 100)
    c = rng.integers(0, 5, 100)
    got = np.asarray(lexsort([jnp.asarray(a), jnp.asarray(b), jnp.asarray(c)]))
    want = np.lexsort((c, b, a))  # numpy: last key most significant
    assert np.array_equal(got, want)


def test_dense_ranks():
    key = jnp.array([3, 3, 5, 7, 7, 7])
    assert list(dense_ranks_sorted(key)) == [0, 0, 1, 2, 2, 2]


def test_searchsorted_ids():
    ids = jnp.array([2, 4, 9], jnp.int64)
    q = jnp.array([4, 3, 9, -1], jnp.int64)
    assert list(searchsorted_ids(ids, q)) == [1, NULLI, 2, NULLI]


def test_pointer_double_chain():
    # chain 0->1->2->3 (self-loop at 3), plus isolated 4
    f = jnp.array([1, 2, 3, 3, 4], jnp.int32)
    assert list(pointer_double(f)) == [3, 3, 3, 3, 4]


# ---------------------------------------------------------------------------
# state vector kernels
# ---------------------------------------------------------------------------

def test_statevec_build_and_diff():
    client = jnp.array([0, 0, 1, 2, 0], jnp.int32)
    clock = jnp.array([0, 1, 0, 4, 99], jnp.int64)
    valid = jnp.array([1, 1, 1, 1, 0], bool)
    sv = statevec.build(client, clock, valid, 4)
    assert list(sv) == [2, 1, 5, 0]
    mask = statevec.diff_mask(client, clock, valid, jnp.array([1, 0, 5, 0], jnp.int64))
    assert list(mask) == [False, True, True, False, False]


def test_statevec_merge_missing():
    svs = jnp.array([[3, 0], [1, 2], [0, 0]], jnp.int64)
    assert list(statevec.merge(svs)) == [3, 2]
    miss = statevec.missing(svs)
    # replica 0 has 2 clocks replica 1 lacks; r1 has 2 clocks r0 lacks
    assert miss[0, 1] == 2 and miss[1, 0] == 2
    assert miss[0, 2] == 3 and miss[2, 0] == 0
    assert miss[0, 0] == 0


# ---------------------------------------------------------------------------
# delete-set kernel
# ---------------------------------------------------------------------------

def test_deleteset_mask_matches_host():
    rng = random.Random(5)
    ds = DeleteSet()
    for _ in range(30):
        ds.add(rng.randrange(4), rng.randrange(50), rng.randint(1, 5))
    ds.normalize()
    items = [(rng.randrange(4), rng.randrange(70)) for _ in range(300)]
    c, s, e = ds_ops.ranges_to_device(ds)
    mask = ds_ops.apply_mask(
        jnp.array([i[0] for i in items], jnp.int32),
        jnp.array([i[1] for i in items], jnp.int64),
        jnp.ones(len(items), bool),
        jnp.array(c, jnp.int32),
        jnp.array(s, jnp.int64),
        jnp.array(e, jnp.int64),
    )
    for (cl, ck), m in zip(items, np.asarray(mask)):
        assert bool(m) == ds.contains(cl, ck), (cl, ck)


def test_deleteset_mask_empty():
    mask = ds_ops.apply_mask(
        jnp.array([0], jnp.int32),
        jnp.array([0], jnp.int64),
        jnp.array([True]),
        jnp.array([], jnp.int32),
        jnp.array([], jnp.int64),
        jnp.array([], jnp.int64),
    )
    assert not bool(mask[0])


# ---------------------------------------------------------------------------
# LWW merge kernel vs oracle
# ---------------------------------------------------------------------------

def union_of(engines):
    """Records + delete-set union as a full-state gossip fan-in would see."""
    recs, ds = [], DeleteSet()
    for e in engines:
        recs.extend(e.records_since(None))
        ds = ds.merge(e.delete_set())
    return recs, ds


def oracle_merge(engines):
    o = Engine(10**6)
    for e in engines:
        o.apply_records(e.records_since(None), e.delete_set())
    return o


def check_against_oracle(engines):
    recs, ds = union_of(engines)
    got = merge_records(recs, ds)
    oracle = oracle_merge(engines)
    want = oracle.map_winner_table()
    got_ids = {k: (v[0].id, v[1]) for k, v in got.items()}
    assert got_ids == want
    return got, oracle


def test_merge_single_replica():
    e = Engine(1)
    e.map_set("m", "a", 1)
    e.map_set("m", "b", 2)
    e.map_set("m", "a", 3)
    e.map_delete("m", "b")
    check_against_oracle([e])


def test_merge_concurrent_two_replicas():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "k", "a1")
    b.map_set("m", "k", "b1")
    b.map_set("m", "j", "b2")
    check_against_oracle([a, b])


def test_merge_with_causal_chains():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "k", "v1")
    b.apply_records(a.records_since(None), a.delete_set())
    b.map_set("m", "k", "v2")  # causally after, lower-client-wins check
    a.map_set("m", "k", "v3")  # concurrent with b's
    check_against_oracle([a, b])


def test_merge_delete_visibility():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "k", "v")
    b.apply_records(a.records_since(None), a.delete_set())
    b.map_delete("m", "k")
    got, _ = check_against_oracle([a, b])
    (rec, visible) = got[(("root", "m"), "k")]
    assert not visible


def test_merge_nested_map_parents():
    from crdt_tpu.core.store import TYPE_MAP

    a = Engine(1)
    a.map_set_type("m", "sub", TYPE_MAP)
    spec = a.map_entry_spec("m", "sub")
    a.map_set("", "inner", 42, parent=spec)
    check_against_oracle([a])


def test_merge_fuzz_vs_oracle():
    rng = random.Random(321)
    for trial in range(10):
        n = rng.choice([2, 3, 6])
        engines = [Engine(i + 1) for i in range(n)]
        for _ in range(150):
            e = rng.choice(engines)
            op = rng.randrange(4)
            if op == 0:
                e.map_set("m", rng.choice("abcdef"), rng.randrange(1000))
            elif op == 1:
                e.map_delete("m", rng.choice("abcdef"))
            elif op == 2:
                e.map_set(rng.choice("xyz"), rng.choice("ab"), rng.randrange(10))
            else:
                src = rng.choice(engines)
                if src is not e:
                    e.apply_records(src.records_since(None), src.delete_set())
        check_against_oracle(engines)


def test_merge_idempotent_duplicates():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "k", 1)
    b.map_set("m", "k", 2)
    recs, ds = union_of([a, b])
    got = merge_records(recs + recs + recs, ds)  # triplicate union
    oracle = oracle_merge([a, b])
    got_ids = {k: (v[0].id, v[1]) for k, v in got.items()}
    assert got_ids == oracle.map_winner_table()


def test_merge_same_client_null_origin_duplicates():
    """Raw records (not API-generated): one client sets the same key
    twice with NULL origins. The Yjs integrate break rule places the
    later write BEFORE the earlier one, so the chain tail — the winner
    — is the max client's MINIMUM clock. Regression for the bench-scale
    divergence (kernel picked max clock)."""
    from crdt_tpu.core.records import ItemRecord

    recs = [
        ItemRecord(client=5, clock=0, parent_root="m", key="k", content="a"),
        ItemRecord(client=5, clock=1, parent_root="m", key="k", content="b"),
        ItemRecord(client=3, clock=0, parent_root="m", key="k", content="c"),
        ItemRecord(client=5, clock=2, parent_root="m", key="k", content="d"),
    ]
    oracle = Engine(10**6)
    oracle.apply_records(recs, DeleteSet())
    want = oracle.map_winner_table()
    got = merge_records(recs)
    got_ids = {k: (v[0].id, v[1]) for k, v in got.items()}
    assert got_ids == want
    assert want[(("root", "m"), "k")][0] == (5, 0)


def test_sequence_same_client_null_origin_duplicates():
    """Same break-rule shape for sequences: two same-client items with
    the same null origin order by DESCENDING clock, so the host replay
    (not the client-asc device key) must rank the group."""
    from crdt_tpu.core.records import ItemRecord
    from crdt_tpu.ops.yata import order_sequences

    recs = [
        ItemRecord(client=2, clock=0, parent_root="arr", content="x"),
        ItemRecord(client=2, clock=1, parent_root="arr", content="y"),
        ItemRecord(client=1, clock=0, parent_root="arr", content="z"),
    ]
    oracle = Engine(10**6)
    oracle.apply_records(recs, DeleteSet())
    want = [pid for pid in oracle.seq_order_table().get(("root", "arr"), [])]
    got = order_sequences(recs)[("root", "arr")]
    assert got == want
    assert got == [(1, 0), (2, 1), (2, 0)]


def test_merge_fuzz_same_client_duplicates_vs_oracle():
    """Fuzz raw MAP record streams where clients repeat (random origins
    within the key chain or null). Sequence-side dup-client coverage:
    the prepend-storm fuzz below and tests/test_yata_kernel.py."""
    from crdt_tpu.core.records import ItemRecord

    rng = random.Random(99)
    for trial in range(5):
        recs = []
        clocks = {}
        for _ in range(120):
            client = rng.randrange(1, 5)
            clock = clocks.get(client, 0)
            clocks[client] = clock + 1
            key = rng.choice("ab")
            prior = [r for r in recs if r.key == key and r.parent_root == "m"]
            origin = rng.choice([None] + [p.id for p in prior[-3:]])
            recs.append(
                ItemRecord(
                    client=client, clock=clock, parent_root="m", key=key,
                    origin=origin, content=clock,
                )
            )
        oracle = Engine(10**6)
        oracle.apply_records(recs, DeleteSet())
        got = merge_records(recs)
        got_ids = {k: (v[0].id, v[1]) for k, v in got.items()}
        assert got_ids == oracle.map_winner_table(), f"trial {trial}"


def test_sequence_fuzz_prepend_storm_vs_oracle():
    """Dup-client groups WITH right-origin attachments: repeated
    prepends from few clients make every origin group contain multiple
    items per client whose rights are members (the host-replay path
    the dup-client routing must take)."""
    from crdt_tpu.ops.yata import order_sequences

    rng = random.Random(777)
    for trial in range(5):
        engines = [Engine(i + 1) for i in range(3)]
        for step in range(60):
            e = rng.choice(engines)
            if rng.random() < 0.6:
                e.seq_insert("arr", 0, [f"s{step}"])  # prepend storm
            else:
                n = e.seq_len("arr")
                e.seq_insert("arr", rng.randrange(n + 1), [f"s{step}"])
            if rng.random() < 0.3:
                src = rng.choice(engines)
                if src is not e:
                    e.apply_records(src.records_since(None), src.delete_set())
        recs, ds = union_of(engines)
        oracle = oracle_merge(engines)
        want = oracle.seq_order_table()[("root", "arr")]
        got = order_sequences(recs)[("root", "arr")]
        assert got == want, f"trial {trial}"


def test_pointer_double_cycle_terminates():
    # malformed (cyclic) input must terminate, not hang the device
    out = pointer_double(jnp.array([1, 2, 0], jnp.int32))
    assert out.shape == (3,)


def test_diff_mask_unknown_client():
    # a client beyond the peer vector's width has watermark 0
    m = statevec.diff_mask(
        jnp.array([5], jnp.int32),
        jnp.array([2], jnp.int64),
        jnp.array([True]),
        jnp.array([3, 1, 0, 7], jnp.int64),
    )
    assert bool(m[0])


def test_merge_wide_client_ids():
    # clients near pack_id's 23-bit bound must not corrupt the
    # collapsed id-ranked sibling key (regression: a 22-bit field
    # overflowed into the parent bits and dropped the winner)
    big = (1 << 22) + 1
    a, b = Engine(5), Engine(big)
    a.map_set("m", "k", "small")
    b.map_set("m", "k", "big")
    check_against_oracle([a, b])
