"""v1 update codec tests: round-trips, run coalescing, diff updates,
golden byte layouts, and malformed input."""

import random

import pytest

from crdt_tpu.codec import v1
from crdt_tpu.codec.lib0 import Decoder, Encoder
from crdt_tpu.core.engine import Engine
from crdt_tpu.core.ids import DeleteSet, StateVector
from crdt_tpu.core.store import K_ANY, K_GC, K_STRING, TYPE_ARRAY


def test_state_vector_roundtrip():
    sv = StateVector({1: 10, 7: 3, 42: 0})
    out = v1.decode_state_vector(v1.encode_state_vector(sv))
    assert out == sv
    assert v1.decode_state_vector(v1.encode_state_vector(StateVector())) == StateVector()


def test_state_vector_golden():
    # one client: n=1, client=1, clock=5
    assert v1.encode_state_vector(StateVector({1: 5})) == b"\x01\x01\x05"


def test_empty_update_roundtrip():
    blob = v1.encode_update([], None)
    assert blob == b"\x00\x00"  # zero struct groups, zero ds clients
    recs, ds = v1.decode_update(blob)
    assert recs == [] and ds == DeleteSet()


def roundtrip_engine(a: Engine) -> Engine:
    b = Engine(999)
    v1.apply_update(b, v1.encode_state_as_update(a))
    return b


def test_map_roundtrip():
    a = Engine(1)
    a.map_set("users", "alice", {"age": 30, "tags": ["x", "y"]})
    a.map_set("users", "bob", None)
    a.map_set("users", "alice", "v2")
    a.map_delete("users", "bob")
    b = roundtrip_engine(a)
    assert b.to_json() == a.to_json()
    assert b.state_vector() == a.state_vector()
    assert b.delete_set() == a.delete_set()


def test_array_roundtrip_with_runs():
    a = Engine(1)
    a.seq_insert("log", 0, list(range(50)))  # one run of 50 on the wire
    a.seq_insert("log", 10, ["mid"])
    a.seq_delete("log", 0, 5)
    blob = v1.encode_state_as_update(a)
    # run coalescing: 52 unit items must encode as few structs
    d = Decoder(blob)
    d.read_var_uint()  # num clients
    num_structs = d.read_var_uint()
    assert num_structs <= 4
    b = roundtrip_engine(a)
    assert b.seq_json("log") == a.seq_json("log")
    assert b.delete_set() == a.delete_set()


def test_nested_type_roundtrip():
    a = Engine(1)
    a.map_set_type("m", "list", TYPE_ARRAY)
    spec = a.map_entry_spec("m", "list")
    a.seq_insert("", 0, [1, [2, 3], {"k": "v"}], parent=spec)
    b = roundtrip_engine(a)
    assert b.to_json() == a.to_json() == {"m": {"list": [1, [2, 3], {"k": "v"}]}}


def test_diff_update():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "x", 1)
    v1.apply_update(b, v1.encode_state_as_update(a))
    a.map_set("m", "y", 2)
    a.seq_insert("s", 0, ["new"])
    # delta vs b's state vector: only the new items
    delta = v1.encode_state_as_update(a, b.state_vector())
    full = v1.encode_state_as_update(a)
    assert len(delta) < len(full)
    v1.apply_update(b, delta)
    assert b.to_json() == a.to_json()


def test_bidirectional_codec_sync():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "k", "a")
    b.map_set("m", "k", "b")
    b.seq_insert("s", 0, ["b0"])
    ua, ub = v1.encode_state_as_update(a), v1.encode_state_as_update(b)
    v1.apply_update(b, ua)
    v1.apply_update(a, ub)
    assert a.to_json() == b.to_json()
    assert a.map_get("m", "k") == "b"  # higher client wins same-origin


def test_reencode_stability():
    a = Engine(3)
    a.seq_insert("s", 0, ["a", "b", "c"])
    a.map_set("m", "k", 1)
    blob = v1.encode_state_as_update(a)
    recs, ds = v1.decode_update(blob)
    blob2 = v1.encode_update(recs, ds)
    assert blob == blob2  # decode∘encode is a fixpoint


def test_gc_and_skip_structs():
    # hand-build: client 5 with [GC len 3][Skip len 4][Any "x" at clock 7]
    e = Encoder()
    e.write_var_uint(1)  # one client group
    e.write_var_uint(3)  # three structs
    e.write_var_uint(5)  # client
    e.write_var_uint(0)  # start clock
    e.write_uint8(v1.REF_GC)
    e.write_var_uint(3)
    e.write_uint8(v1.REF_SKIP)
    e.write_var_uint(4)
    e.write_uint8(v1.REF_ANY | 0x20)  # parent + sub follow (no origins)
    e.write_var_uint(1)  # parent is root
    e.write_var_string("m")
    e.write_var_string("k")
    e.write_var_uint(1)  # one any value
    e.write_any("x")
    e.write_var_uint(0)  # empty delete set
    recs, ds = v1.decode_update(e.to_bytes())
    assert [r.kind for r in recs] == [K_GC, K_GC, K_GC, K_ANY]
    assert [r.clock for r in recs] == [0, 1, 2, 7]
    assert recs[3].key == "k" and recs[3].parent_root == "m"
    # re-encode preserves the gap with a Skip struct
    blob2 = v1.encode_update(recs, ds)
    recs2, _ = v1.decode_update(blob2)
    assert [(r.clock, r.kind) for r in recs2] == [(r.clock, r.kind) for r in recs]


def test_string_content_utf16():
    # ContentString run with an astral char (2 UTF-16 units -> 2 clocks)
    e = Encoder()
    e.write_var_uint(1)
    e.write_var_uint(1)
    e.write_var_uint(9)
    e.write_var_uint(0)
    e.write_uint8(v1.REF_STRING | 0x20)
    e.write_var_uint(1)
    e.write_var_string("t")
    e.write_var_string("sub")
    e.write_var_string("a\U0001F600b")
    e.write_var_uint(0)
    recs, _ = v1.decode_update(e.to_bytes())
    assert len(recs) == 4  # 'a', high surrogate, low surrogate, 'b'
    assert all(r.kind == K_STRING for r in recs)
    blob2 = v1.encode_update(recs, None)
    recs2, _ = v1.decode_update(blob2)
    from crdt_tpu.codec.v1 import _join_utf16

    assert _join_utf16([r.content for r in recs2]) == "a\U0001F600b"


def test_delete_set_roundtrip():
    ds = DeleteSet()
    ds.add(1, 0, 5)
    ds.add(1, 10, 1)
    ds.add(9, 3, 2)
    blob = v1.encode_update([], ds)
    _, out = v1.decode_update(blob)
    assert out == ds


def test_malformed_rejected():
    with pytest.raises(ValueError):
        v1.decode_update(b"\x01")  # truncated
    with pytest.raises(ValueError):
        v1.decode_update(b"\x00\x00\xff")  # trailing bytes
    # unknown ref id
    e = Encoder()
    e.write_var_uint(1)
    e.write_var_uint(1)
    e.write_var_uint(1)
    e.write_var_uint(0)
    e.write_uint8(31)  # ref 31 unused
    with pytest.raises(ValueError):
        v1.decode_update(e.to_bytes())


def test_fuzz_codec_convergence():
    from tests.test_engine import _random_op

    rng = random.Random(77)
    for _ in range(5):
        engines = [Engine(i + 1) for i in range(3)]
        for _ in range(80):
            _random_op(rng, rng.choice(engines), engines)
        # sync exclusively through wire blobs
        for _ in range(2):
            blobs = [v1.encode_state_as_update(e) for e in engines]
            for i, e in enumerate(engines):
                for j, blob in enumerate(blobs):
                    if i != j:
                        v1.apply_update(e, blob)
        jsons = [e.to_json() for e in engines]
        assert jsons[1] == jsons[0] and jsons[2] == jsons[0]
