"""Deterministic fault-injection fabric: the tier-1 chaos smoke.

One seeded schedule (drop + duplicate + delay/reorder + corrupt, plus
a partition leg) runs the sync protocol across all three merge modes
— scalar, device, resident — and must converge BYTE-IDENTICALLY to
the fault-free run: same per-replica snapshot bytes, same state
vectors. Recovery is driven entirely by the protocol's own machinery
(ready-probe retry/backoff, periodic anti-entropy), pinned by tracer
counters — no test-side resend plumbing. Heavier schedules live
behind ``-m slow``.

The fleet half (parallel/gossip.py hooks) pins the device-mesh
analogue: a round with withheld/partitioned replica batches, followed
by a heal round, lands on exactly the fault-free gossip output.
"""

import time

import numpy as np
import pytest

from crdt_tpu.net.faults import (
    FaultSchedule,
    FaultyEndpoint,
    Partition,
    install_faults,
    pump_until,
)
from crdt_tpu.net.replica import Replica
from crdt_tpu.net.udp_router import UdpRouter
from crdt_tpu.utils.trace import Tracer, set_tracer

SEED = 7
CHAOS = dict(drop=0.12, duplicate=0.1, delay=0.1, delay_polls=(1, 6),
             corrupt=0.05)


# ---------------------------------------------------------------------------
# schedule determinism (the replayability claim)
# ---------------------------------------------------------------------------


def test_schedule_is_deterministic_per_flow():
    a = FaultSchedule(SEED, **CHAOS)
    b = FaultSchedule(SEED, **CHAOS)
    flows = [(1000, 2000), (2000, 1000), (1000, 3000)]
    seq_a = [a.decide(s, d, n) for s, d in flows for n in range(200)]
    seq_b = [b.decide(s, d, n) for s, d in flows for n in range(200)]
    assert seq_a == seq_b
    # a different seed is a different schedule
    c = FaultSchedule(SEED + 1, **CHAOS)
    seq_c = [c.decide(s, d, n) for s, d in flows for n in range(200)]
    assert seq_c != seq_a
    # and the rates are in the ballpark they claim
    drops = sum(d["drop"] for d in seq_a)
    assert 0.04 * len(seq_a) < drops < 0.25 * len(seq_a)


def test_partition_blocks_cross_group_then_heals():
    p = Partition({1000}, {2000}, max_blocked=3)
    assert p.blocks(1000, 2000)
    assert p.blocks(2000, 1000)
    assert not p.blocks(1000, 3000)  # third parties unaffected
    assert p.blocks(1000, 2000)  # third blocked message -> auto-heal
    assert p.healed
    assert not p.blocks(1000, 2000)


# ---------------------------------------------------------------------------
# the chaos smoke: one seeded schedule x all three merge modes
# ---------------------------------------------------------------------------


def _chaos_run(merge_mode, schedule_seed=None, *, n_ops=12,
               partition=False, timeout_s=60.0):
    """Three replicas over real UDP routers; returns (snapshots, svs,
    cache, fault stats). ``schedule_seed=None`` = the fault-free
    reference run. Faults are injected AFTER membership settles and
    every write happens under them: the schedule attacks the sync /
    update traffic, whose loss only the protocol's own retry,
    anti-entropy, and partition-heal machinery may repair."""
    routers = [UdpRouter() for _ in range(3)]
    for i, r in enumerate(routers):
        for other in routers[:i]:
            r.add_peer(*other.addr)
    pump_until(
        routers,
        lambda: all(len(r.peers) == 2 for r in routers),
        timeout_s=timeout_s,
    )
    reps = [
        Replica(r, topic="room", client_id=i + 1, merge_mode=merge_mode,
                probe_retry_s=0.1, anti_entropy_s=0.15)
        for i, r in enumerate(routers)
    ]
    pump_until(
        routers,
        lambda: all(len(r.peers_on("room")) == 2 for r in routers),
        timeout_s=timeout_s,
    )
    eps = []
    part = None
    if schedule_seed is not None:
        ports = [r.endpoint.port for r in routers]
        if partition:
            # replica 2 partitioned away from 0 and 1 until the
            # partition has eaten a fixed number of messages (a
            # count, not a timer: the schedule replays)
            part = Partition(set(ports[:2]), {ports[2]}, max_blocked=25)
        for r in routers:
            sched = FaultSchedule(schedule_seed, partition=part, **CHAOS)
            eps.append(install_faults(r, sched))
    for i in range(n_ops):
        reps[i % 3].set("kv", f"k{i}", [i, "v"])
        reps[i % 3].push(f"log{i % 2}", f"e{i}")

    def converged():
        cs = [dict(r.c) for r in reps]
        return cs[0] == cs[1] == cs[2] and len(cs[0].get("kv", {})) == n_ops

    pump_until(routers, converged, timeout_s=timeout_s)
    # pump past convergence so the periodic anti-entropy cadence
    # provably fires at least once (its counters are asserted below;
    # post-convergence rounds find no deficit and change nothing)
    end = time.monotonic() + 0.4
    while time.monotonic() < end:
        for r in routers:
            r.poll()
        time.sleep(0.002)
    snaps = [r.doc.encode_state_as_update() for r in reps]
    svs = [r.doc.encode_state_vector() for r in reps]
    cache = dict(reps[0].c)
    stats = {}
    for ep in eps:
        for k, v in ep.stats.items():
            stats[k] = stats.get(k, 0) + v
    if part is not None:
        stats["partition_healed"] = part.healed
    for r in routers:
        r.close()
    return snaps, svs, cache, stats


@pytest.mark.parametrize("merge_mode", ["scalar", "device", "resident"])
def test_chaos_schedule_converges_byte_identical(merge_mode):
    tracer = set_tracer(Tracer(enabled=True))
    try:
        clean = _chaos_run(merge_mode, None)
        faulted = _chaos_run(merge_mode, SEED, partition=True)
    finally:
        set_tracer(Tracer(enabled=False))
    # the adversary actually showed up...
    stats = faulted[3]
    assert stats["dropped"] > 0, stats
    assert stats["corrupted"] + stats["duplicated"] + stats["delayed"] > 0
    assert stats["partitioned"] > 0 and stats["partition_healed"]
    # ...and the retry machinery (not test plumbing) recovered it,
    # visibly in the tracer
    counters = tracer.counters()
    assert (
        counters.get("replica.probe_retries", 0)
        + counters.get("replica.anti_entropy_rounds", 0)
    ) > 0, counters
    # byte-identical convergence: every replica, both runs
    clean_snaps, clean_svs, clean_cache, _ = clean
    f_snaps, f_svs, f_cache, _ = faulted
    assert clean_snaps[0] == clean_snaps[1] == clean_snaps[2]
    assert f_snaps[0] == f_snaps[1] == f_snaps[2]
    assert f_snaps == clean_snaps
    assert f_svs == clean_svs
    assert f_cache == clean_cache


@pytest.mark.slow
@pytest.mark.parametrize("merge_mode", ["scalar", "device", "resident"])
def test_heavy_chaos_schedule(merge_mode):
    clean = _chaos_run(merge_mode, None, n_ops=45, timeout_s=120.0)
    faulted = _chaos_run(
        merge_mode, SEED + 1, n_ops=45, partition=True, timeout_s=120.0
    )
    assert faulted[0] == clean[0]
    assert faulted[1] == clean[1]
    assert faulted[2] == clean[2]


# ---------------------------------------------------------------------------
# fault wrapper mechanics
# ---------------------------------------------------------------------------


def test_delayed_messages_count_as_pending_and_release():
    from crdt_tpu.net import transport as t

    a, b = t.UdpEndpoint(), t.UdpEndpoint()
    try:
        ep = FaultyEndpoint(a, FaultSchedule(0, delay=1.0, delay_polls=(3, 3)))
        ep.send("127.0.0.1", b.port, b"held")
        assert ep.stats["delayed"] == 1
        assert ep.pending >= 1  # held message visible to quiescence checks
        got = []
        for _ in range(200):
            ep.poll()
            b.poll()
            got.extend(b.recv_all())
            if got:
                break
        assert got and got[0][2] == b"held"
    finally:
        a.close()
        b.close()


def test_corrupted_envelope_is_rejected_not_fatal():
    """A corrupted encrypted envelope must fail authentication and be
    discarded — never poison peer state or kill the poll loop."""
    routers = [UdpRouter() for _ in range(2)]
    try:
        routers[1].add_peer(*routers[0].addr)
        pump_until(
            routers,
            lambda: all(len(r.peers) == 1 for r in routers),
            timeout_s=20.0,
        )
        # corrupt EVERY outbound message from router 1 for a while
        ep = install_faults(routers[0], FaultSchedule(0, corrupt=1.0))
        routers[0].alow("room", lambda m, pk: None)
        for _ in range(100):
            for r in routers:
                r.poll()
        assert ep.stats["corrupted"] > 0
        # fabric still alive; clearing the faults heals the topic
        routers[0].endpoint = ep._inner
        routers[1].alow("room", lambda m, pk: None)
        routers[0]._announce_topics()
        pump_until(
            routers,
            lambda: routers[1].peers_on("room") == [routers[0].public_key],
            timeout_s=20.0,
        )
    finally:
        for r in routers:
            r.close()


# ---------------------------------------------------------------------------
# fleet gossip fault hooks (parallel/gossip.py)
# ---------------------------------------------------------------------------


def test_fleet_gossip_drop_and_partition_heal_to_fault_free():
    jax = pytest.importorskip("jax")
    from crdt_tpu.parallel.gossip import (
        GossipFaultPlan,
        make_gossip_step,
        make_mesh,
        mask_packed,
        pack_cols,
        pack_dels,
        synth_columns,
    )

    del jax
    R, N = 8, 16
    cols, dels = synth_columns(R, N, num_maps=2, keys_per_map=8,
                               num_lists=2, seed=3)
    packed, dels_p = pack_cols(cols), pack_dels(dels)
    mesh = make_mesh(1)
    step = make_gossip_step(mesh, num_segments=R * N, num_clients=R + 1)
    reference = np.asarray(step(packed, dels_p))

    plan = GossipFaultPlan(seed=5, drop=0.4, partition_every=2, groups=2)
    keep = plan.delivered_mask(0, R)
    assert 0 < keep.sum() < R  # the plan actually dropped someone
    lossy = np.asarray(step(mask_packed(packed, keep), dels_p))
    assert not np.array_equal(lossy, reference)  # loss is observable

    masks = plan.partition_masks(2, R)
    assert masks is not None and sum(m.sum() for m in masks) == R
    for m in masks:
        np.asarray(step(mask_packed(packed, m), dels_p))  # group round

    # heal: the full columns re-presented -> exactly the fault-free
    # round (CRDT idempotence on device)
    healed = np.asarray(step(packed, dels_p))
    assert np.array_equal(healed, reference)

    # determinism: same plan, same decisions
    plan2 = GossipFaultPlan(seed=5, drop=0.4, partition_every=2, groups=2)
    assert np.array_equal(plan2.delivered_mask(0, R), keep)
    assert plan.partition_masks(1, R) is None  # off-cycle rounds clean
