"""Seeded fuzz for the round-23 SUBTREE split (see
``packed._subtree_split``).

Random branching trees — every op may anchor ANY prior op, so wide
stars, caterpillars, and bushy mixes all occur — plus right origins,
tombstone runs, deep origin-chained map key chains, and hostile
cyclic origins. Every trace must be BYTE-identical (cache and
snapshot) between the split-disabled oracle and the split at widths
{1, odd, default}, on the single-chip packed route and the 1/2/4-way
sharded route. The rounds reduction itself is pinned via the
``converge.wyllie_rounds`` / ``converge.map_rounds`` gauges and the
``converge.subtree_cuts`` / ``converge.map_chain_cuts`` cut counts.
"""

import jax
import numpy as np
import pytest

from crdt_tpu.codec import v1
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.models import replay as rp
from crdt_tpu.obs import Tracer, get_tracer, set_tracer
from crdt_tpu.ops import packed
from crdt_tpu.ops import shard


@pytest.fixture(autouse=True)
def _eight_devices():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"


@pytest.fixture(autouse=True)
def _no_ambient_sharding(monkeypatch):
    monkeypatch.delenv(shard.SHARD_ENV, raising=False)
    monkeypatch.delenv(shard.MIN_ROWS_ENV, raising=False)
    monkeypatch.delenv(packed._CHAIN_SPLIT_ENV, raising=False)


def conflict_trace(n_clients=5, n_ops=140, n_lists=2, map_chain=36,
                   rights=True, deletes=True, cycles=False, seed=0):
    """Per-replica blobs over shared lists: random-anchor branching
    inserts (the subtree-split shape), occasional right-origin
    mid-inserts, a deep origin-chained run of sets on one hot map
    key, optional tombstones and a hostile origin 2-cycle."""
    rng = np.random.default_rng(seed)
    blobs = []
    for c in range(n_clients):
        client = c + 1
        recs = []
        per_list = [[] for _ in range(n_lists)]
        clock = 0
        for k in range(n_ops):
            li = int(rng.integers(0, n_lists))
            anchors = per_list[li]
            r = float(rng.random())
            if rights and anchors and r < 0.12:
                j = int(rng.integers(0, len(anchors)))
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root=f"l{li}",
                    origin=anchors[j - 1] if j > 0 else None,
                    right=anchors[j], content=k,
                ))
                anchors.insert(j, (client, clock))
            elif anchors and r < 0.80:
                # branch: anchor a uniformly random prior own op
                j = int(rng.integers(0, len(anchors)))
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root=f"l{li}",
                    origin=anchors[j], content=k,
                ))
                anchors.append((client, clock))
            else:
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root=f"l{li}",
                    content=k,
                ))
                anchors.append((client, clock))
            clock += 1
        prev = None
        for k in range(map_chain):
            recs.append(ItemRecord(
                client=client, clock=clock, parent_root="m",
                key="hot" if k % 4 else f"k{k % 3}",
                origin=(client, prev) if prev is not None else None,
                content=k,
            ))
            prev = clock
            clock += 1
        if cycles and c == 0:
            recs.append(ItemRecord(
                client=client, clock=clock, parent_root="cyc",
                origin=(client, clock + 1), content=0))
            recs.append(ItemRecord(
                client=client, clock=clock + 1, parent_root="cyc",
                origin=(client, clock), content=1))
            clock += 2
            for k in range(40):
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root="cyc",
                    content=k))
                clock += 1
        ds = DeleteSet()
        if deletes:
            # a contiguous tombstone run plus scattered singles
            for k in range(5, 5 + n_ops // 8):
                ds.add(client, k)
            for k in rng.choice(n_ops, size=n_ops // 20,
                                replace=False):
                ds.add(client, int(k))
        blobs.append(v1.encode_update(recs, ds))
    return blobs


def stage_all(blobs):
    dec = rp.decode(blobs)
    cols, ds = rp.stage(dec)
    return dec, cols, ds


def run_single(dec, cols, ds):
    plan = packed.stage(cols)
    assert plan is not None
    res = packed.converge(plan)
    w, v, o = rp.gather(dec, ds, ("packed", res))
    return rp.materialize(dec, ds, w, v, o)


def run_sharded(dec, cols, ds, K):
    splan = shard.stage(cols, n_shards=K)
    assert splan is not None, f"sharded staging refused at K={K}"
    res = shard.converge(splan)
    w, v, o = rp.gather(dec, ds, ("packed", res))
    return rp.materialize(dec, ds, w, v, o)


def _set_width(monkeypatch, w):
    if w is None:  # the default width
        monkeypatch.delenv(packed._CHAIN_SPLIT_ENV, raising=False)
    else:
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, w)


WIDTHS = ("1", "13", None)  # degenerate, odd, default


class TestSubtreeFuzz:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_single_chip_differential(self, seed, monkeypatch):
        blobs = conflict_trace(seed=seed)
        dec, cols, ds = stage_all(blobs)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
        want = run_single(dec, cols, ds)
        for w in WIDTHS:
            _set_width(monkeypatch, w)
            assert run_single(dec, cols, ds) == want, f"width={w}"

    @pytest.mark.parametrize("seed", [3, 4])
    def test_sharded_differential(self, seed, monkeypatch):
        blobs = conflict_trace(seed=seed)
        dec, cols, ds = stage_all(blobs)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
        want = run_single(dec, cols, ds)
        for w in ("13", None):
            _set_width(monkeypatch, w)
            # K=1 is by design the single-chip packed route
            assert run_single(dec, cols, ds) == want, f"width={w} K=1"
            for K in (2, 4):
                got = run_sharded(dec, cols, ds, K)
                assert got == want, f"width={w} K={K}"

    def test_hostile_cycles_stay_exact(self, monkeypatch):
        blobs = conflict_trace(n_clients=3, n_ops=90, cycles=True,
                               seed=5)
        dec, cols, ds = stage_all(blobs)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
        want = run_single(dec, cols, ds)
        for w in WIDTHS:
            _set_width(monkeypatch, w)
            assert run_single(dec, cols, ds) == want, f"width={w}"

    def test_rights_and_tombstones_heavy(self, monkeypatch):
        """Right-heavy + delete-heavy: right origins pin only their
        own conflict-scan neighborhood now, not the whole segment."""
        blobs = conflict_trace(n_clients=4, n_ops=160, rights=True,
                               deletes=True, seed=6)
        dec, cols, ds = stage_all(blobs)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
        want = run_single(dec, cols, ds)
        for w in ("1", "7", None):
            _set_width(monkeypatch, w)
            assert run_single(dec, cols, ds) == want, f"width={w}"

    def test_gauges_drop_and_cuts_counted(self, monkeypatch):
        """The lever: on a branchy + deep-map trace the split lowers
        BOTH staged rounds bounds, and the new cut gauges fire."""
        blobs = conflict_trace(n_clients=6, n_ops=200, map_chain=48,
                               rights=False, deletes=False, seed=7)
        dec, cols, ds = stage_all(blobs)
        prev = get_tracer()
        tracer = set_tracer(Tracer(enabled=True))
        try:
            monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
            assert packed.stage(cols) is not None
            g0 = dict(tracer.report()["gauges"])
            monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "16")
            assert packed.stage(cols) is not None
            g1 = dict(tracer.report()["gauges"])
        finally:
            set_tracer(prev)
        assert g1["converge.wyllie_rounds"] < g0["converge.wyllie_rounds"]
        assert g1["converge.map_rounds"] < g0["converge.map_rounds"]
        assert g1["converge.subtree_cuts"] > 0
        assert g1["converge.map_chain_cuts"] > 0
        assert g0["converge.subtree_cuts"] == 0
        assert g0["converge.map_chain_cuts"] == 0

    def test_replay_route_cache_and_snapshot(self, monkeypatch):
        """The product seam: replay_trace with the split and the
        sharded route flipped on stays byte-identical end to end."""
        blobs = conflict_trace(n_clients=4, n_ops=120, seed=8)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "0")
        base = rp.replay_trace(blobs)
        monkeypatch.setenv(packed._CHAIN_SPLIT_ENV, "24")
        split = rp.replay_trace(blobs)
        assert split.cache == base.cache
        assert split.snapshot == base.snapshot
        monkeypatch.setenv(shard.SHARD_ENV, "4")
        monkeypatch.setenv(shard.MIN_ROWS_ENV, "1")
        sharded = rp.replay_trace(blobs)
        assert sharded.cache == base.cache
        assert sharded.snapshot == base.snapshot
