"""Document/API layer tests — the reference surface with D1-D7 fixed.

Shapes follow the reference README usage example
(/root/reference/README.md:29-76) and the op-layer behaviors at
/root/reference/crdt.js:325-657.
"""

import pytest

from crdt_tpu.api import Crdt, ReservedNameError, WrongKindError


def pair(a=1, b=2, **kw):
    """Two replicas wired directly update->apply (loopback without router)."""
    docs = {}
    da = Crdt(a, on_update=lambda u, m: docs["b"].apply_update(u), **kw)
    db = Crdt(b, on_update=lambda u, m: docs["a"].apply_update(u), **kw)
    docs["a"], docs["b"] = da, db
    return da, db


# ---------------------------------------------------------------------------
# map ops
# ---------------------------------------------------------------------------

class TestMap:
    def test_set_and_cache(self):
        d = Crdt(1)
        d.map("users")
        d.set("users", "u1", {"age": 30})
        assert d.c["users"] == {"u1": {"age": 30}}
        assert d["users"] == {"u1": {"age": 30}}
        # Proxy fallthrough (crdt.js:691)
        assert d.users == {"u1": {"age": 30}}

    def test_auto_create_on_set(self):
        d = Crdt(1)
        d.set("users", "u1", 5)  # no prior map() call (crdt.js:418-421)
        assert d.users == {"u1": 5}

    def test_get_method_exists(self):
        # D7: README documents get; the reference lacks it
        d = Crdt(1)
        d.set("users", "u1", {"x": 1})
        assert d.get("users", "u1") == {"x": 1}
        assert d.get("users", "missing") is None
        assert d.get("users") == {"u1": {"x": 1}}

    def test_delete(self):
        d = Crdt(1)
        d.set("users", "u1", 1)
        d.set("users", "u2", 2)
        d.delete("users", "u1")
        assert d.users == {"u2": 2}

    def test_lww_overwrite(self):
        d = Crdt(1)
        d.set("m", "k", "a")
        d.set("m", "k", "b")
        assert d.m == {"k": "b"}

    def test_reserved_names(self):
        d = Crdt(1)
        for name in ("ix", "doc"):
            with pytest.raises(ReservedNameError):
                d.map(name)
            with pytest.raises(ReservedNameError):
                d.set(name, "k", 1)

    def test_kind_mismatch(self):
        d = Crdt(1)
        d.map("m")
        with pytest.raises(WrongKindError):
            d.push("m", 1)
        d.array("a")
        with pytest.raises(WrongKindError):
            d.set("a", "k", 1)


# ---------------------------------------------------------------------------
# array ops
# ---------------------------------------------------------------------------

class TestArray:
    def test_push_insert_order(self):
        d = Crdt(1)
        d.array("log")
        d.push("log", "b")
        d.push("log", ["c", "d"])
        d.insert("log", 0, "a")  # README arg order: (name, index, value)
        assert d.log == ["a", "b", "c", "d"]

    def test_unshift_mutates(self):
        # D1: the reference's non-batch unshift is a silent no-op
        d = Crdt(1)
        d.push("log", "b")
        d.unshift("log", "a")
        assert d.log == ["a", "b"]

    def test_cut_mutates(self):
        # D1: the reference's non-batch cut is a silent no-op
        d = Crdt(1)
        d.push("log", ["a", "b", "c", "d"])
        d.cut("log", 1, 2)
        assert d.log == ["a", "d"]

    def test_insert_out_of_range(self):
        d = Crdt(1)
        d.array("log")
        with pytest.raises(IndexError):
            d.insert("log", 5, "x")


# ---------------------------------------------------------------------------
# nested array-in-map (crdt.js:422-432, D2 fixed)
# ---------------------------------------------------------------------------

class TestNested:
    def test_nested_push_and_cut(self):
        d = Crdt(1)
        d.set("m", "list", "x", array_method="push")
        d.set("m", "list", ["y", "z"], array_method="push")
        assert d.m == {"list": ["x", "y", "z"]}
        d.set("m", "list", None, array_method="cut", index=1, length=1)
        assert d.m == {"list": ["x", "z"]}

    def test_nested_insert_unshift(self):
        d = Crdt(1)
        d.set("m", "l", "c", array_method="push")
        d.set("m", "l", "a", array_method="unshift")
        d.set("m", "l", "b", array_method="insert", index=1)
        assert d.m == {"l": ["a", "b", "c"]}

    def test_nested_validation(self):
        d = Crdt(1)
        with pytest.raises(ValueError):
            d.set("m", "l", "x", array_method="bogus")
        with pytest.raises(ValueError):
            d.set("m", "l", "x", array_method="insert")  # no index

    def test_nested_converges(self):
        da, db = pair()
        da.set("m", "l", ["a", "b"], array_method="push")
        db.set("m", "l", "c", array_method="push")
        assert da.m == db.m
        assert da.m["l"][:2] == ["a", "b"]
        assert set(da.m["l"]) == {"a", "b", "c"}


# ---------------------------------------------------------------------------
# batch queue (crdt.js:325-355)
# ---------------------------------------------------------------------------

class TestBatch:
    def test_batch_queue_and_exec(self):
        updates = []
        d = Crdt(1, on_update=lambda u, m: updates.append((u, m)))
        d.set("m", "a", 1, batch=True)
        d.set("m", "b", 2, batch=True)
        d.push("log", "x", batch=True)
        assert d.pending_batch_size == 3
        assert updates == []  # nothing sent yet
        assert "m" not in d  # nothing applied yet
        out = d.exec_batch()
        assert d.pending_batch_size == 0
        assert d.m == {"a": 1, "b": 2}
        assert d.log == ["x"]
        # one update, one broadcast for the whole batch
        assert len(updates) == 1
        assert updates[0][1] == {"meta": "batch"}
        assert out == updates[0][0]

    def test_empty_exec_batch_returns(self):
        # D4: the reference hangs forever on an empty queue
        d = Crdt(1)
        assert d.exec_batch() is None

    def test_through_database_mode(self):
        updates = []
        d = Crdt(1, on_update=lambda u, m: updates.append(u))
        d.set("m", "a", 1, batch=True)
        out = d.exec_batch(propagate=False)  # throughDatabase (crdt.js:350)
        assert out is not None and updates == []
        other = Crdt(2)
        other.apply_update(out)
        assert other.m == {"a": 1}

    def test_batch_applies_atomically_to_peer(self):
        da, db = pair()
        da.set("m", "a", 1, batch=True)
        da.push("log", "x", batch=True)
        da.exec_batch()
        assert db.m == {"a": 1} and db.log == ["x"]


# ---------------------------------------------------------------------------
# replication through the update hook
# ---------------------------------------------------------------------------

class TestReplication:
    def test_two_replica_convergence_delta(self):
        da, db = pair()
        da.set("users", "u1", {"n": 1})
        db.set("users", "u2", {"n": 2})
        da.push("log", "a")
        db.push("log", "b")
        assert da.c == db.c
        assert da.users == {"u1": {"n": 1}, "u2": {"n": 2}}

    def test_two_replica_convergence_full_state(self):
        # Q2 compat mode: every update carries full state
        da, db = pair(full_state_updates=True)
        da.set("m", "a", 1)
        db.set("m", "b", 2)
        da.delete("m", "a")
        assert da.c == db.c == {"m": {"b": 2}}

    def test_concurrent_set_same_key(self):
        ua, ub = [], []
        da = Crdt(1, on_update=lambda u, m: ua.append(u))
        db = Crdt(2, on_update=lambda u, m: ub.append(u))
        da.set("m", "k", "from-a")
        db.set("m", "k", "from-b")
        for u in ua:
            db.apply_update(u)
        for u in ub:
            da.apply_update(u)
        assert da.m == db.m  # one deterministic winner
        assert da.m["k"] in ("from-a", "from-b")

    def test_remote_collection_appears_in_cache(self):
        # D3: the reference never adds remotely-created collections
        da, db = pair()
        da.set("newmap", "k", 1)
        da.push("newarr", "v")
        assert db.newmap == {"k": 1}
        assert db.newarr == ["v"]

    def test_idempotent_redelivery(self):
        ua = []
        da = Crdt(1, on_update=lambda u, m: ua.append(u))
        db = Crdt(2)
        da.set("m", "k", 1)
        da.push("l", "x")
        for u in ua * 3:  # deliver every update three times
            db.apply_update(u)
        assert db.c == da.c

    def test_out_of_order_delivery(self):
        ua = []
        da = Crdt(1, on_update=lambda u, m: ua.append(u))
        db = Crdt(2)
        da.push("l", "a")
        da.push("l", "b")
        da.push("l", "c")
        for u in reversed(ua):  # reversed: deps arrive late -> pending path
            db.apply_update(u)
        assert db.l == ["a", "b", "c"]


# ---------------------------------------------------------------------------
# observers (Q1 fixed: local fires too)
# ---------------------------------------------------------------------------

class TestObservers:
    def test_observer_function_local_and_remote(self):
        events = []
        da = Crdt(1, observer_function=events.append)
        da.set("m", "k", 1)
        assert events and events[-1]["origin"] == "local"
        db = Crdt(2)
        update = da.encode_state_as_update()
        events.clear()
        da.apply_update(db.encode_state_as_update())  # no-op update
        db.apply_update(update)
        assert all(e["origin"] == "remote" for e in events)

    def test_collection_observer_scoped(self):
        d = Crdt(1)
        seen = []
        d.observe("m", seen.append)
        d.set("m", "k", 1)
        assert seen and seen[-1]["value"] == {"k": 1}
        d.set("other", "k", 2)
        assert len(seen) == 1  # only fires for its collection

    def test_key_observer(self):
        d = Crdt(1)
        seen = []
        d.observe("m", seen.append, key="watched")
        d.set("m", "watched", 42)
        assert seen[-1]["value"] == 42

    def test_unobserve_detaches(self):
        d = Crdt(1)
        seen = []

        def cb(e):
            seen.append(e)

        d.observe("m", cb)
        d.set("m", "a", 1)
        assert d.unobserve(cb) is True
        d.set("m", "b", 2)
        assert len(seen) == 1


# ---------------------------------------------------------------------------
# state-vector sync primitives (used by the router layer)
# ---------------------------------------------------------------------------

class TestSyncPrimitives:
    def test_sv_diff_update(self):
        da, db_late = Crdt(1), Crdt(2)
        da.set("m", "a", 1)
        da.push("l", "x")
        # late joiner sends its SV; syncer encodes the diff (crdt.js:288)
        diff = da.encode_state_as_update(db_late.state_vector())
        db_late.apply_update(diff)
        assert db_late.c == da.c
        # now a delta on top
        da.set("m", "b", 2)
        diff2 = da.encode_state_as_update(db_late.state_vector())
        db_late.apply_update(diff2)
        assert db_late.c == da.c


# ---------------------------------------------------------------------------
# regression tests for review findings (exception safety, aliasing, guards)
# ---------------------------------------------------------------------------

class TestTxnSafety:
    def test_throwing_op_still_broadcasts_partial_txn(self):
        # an op that raises mid-txn must broadcast what it integrated,
        # or peers wedge forever on the client's clock gap
        ua = []
        da = Crdt(1, on_update=lambda u, m: ua.append(u))
        db = Crdt(2)
        da.push("a", "x", batch=True)
        da.insert("a", 99, "y", batch=True)  # raises IndexError
        with pytest.raises(IndexError):
            da.exec_batch()
        da.push("a", "z")  # later ops must still replicate
        for u in ua:
            db.apply_update(u)
        assert db.a == ["x", "z"]
        assert not db.engine.pending  # nothing stuck on a clock gap

    def test_throwing_nonbatch_op_keeps_replicas_consistent(self):
        ua = []
        da = Crdt(1, on_update=lambda u, m: ua.append(u))
        db = Crdt(2)
        with pytest.raises(IndexError):
            da.insert("arr", 5, "x")  # auto-created 'arr' must ship
        da.push("arr", "ok")
        for u in ua:
            db.apply_update(u)
        assert db.arr == ["ok"] and not db.engine.pending

    def test_cache_mutation_cannot_corrupt_state(self):
        da = Crdt(1)
        da.set("m", "k", [1])
        da.c["m"]["k"].append(2)  # mutating the cache view
        assert da.get("m", "k") == [1]  # engine state untouched
        db = Crdt(2)
        db.apply_update(da.encode_state_as_update())
        assert db.m == {"k": [1]}

    def test_nested_cut_length_zero_is_noop(self):
        d = Crdt(1)
        d.set("m", "k", [10, 20, 30], array_method="insert", index=0)
        d.set("m", "k", array_method="cut", index=0, length=0)
        assert d.m == {"k": [10, 20, 30]}

    def test_kind_guard_at_execution_time(self):
        d = Crdt(1)
        d.array("x", batch=True)
        d.set("x", "k", 1, batch=True)  # queued before kind known
        with pytest.raises(WrongKindError):
            d.exec_batch()
        assert d.x == []  # no hidden map entry under the array root

    def test_throwing_observer_does_not_block_broadcast(self):
        ua = []

        def bad_observer(e):
            raise RuntimeError("observer bug")

        da = Crdt(1, observer_function=bad_observer,
                  on_update=lambda u, m: ua.append(u))
        with pytest.raises(RuntimeError):
            da.set("m", "k", 1)
        assert len(ua) == 1  # update shipped before the observer blew up
        db = Crdt(2)
        db.apply_update(ua[0])
        assert db.m == {"k": 1}

    def test_observer_event_cache_is_snapshot(self):
        events = []
        da = Crdt(1, observer_function=events.append)
        da.set("m", "a", 1)
        da.set("m", "b", 2)
        assert events[0]["c"]["m"] == {"a": 1}  # not retroactively mutated
        assert events[1]["c"]["m"] == {"a": 1, "b": 2}

    def test_key_observer_ignores_other_keys(self):
        d = Crdt(1)
        seen = []
        d.observe("m", seen.append, key="watched")
        d.set("m", "other", 1)  # unrelated key: no event
        assert seen == []
        d.set("m", "watched", 42)
        assert len(seen) == 1 and seen[-1]["value"] == 42
        d.delete("m", "watched")
        assert len(seen) == 2 and seen[-1]["value"] is None
        d.set("m", "nested", "x", array_method="push")  # other key again
        assert len(seen) == 2

    def test_key_observer_fires_for_nested_edits_under_key(self):
        d = Crdt(1)
        seen = []
        d.observe("m", seen.append, key="list")
        d.set("m", "list", "a", array_method="push")
        d.set("m", "list", "b", array_method="push")
        assert [e["value"] for e in seen] == [["a"], ["a", "b"]]


def test_map_cache_refresh_is_per_key():
    """A 1-key txn on a big map must not re-materialize the whole map
    (r1 deep-copied every touched collection per txn)."""
    from crdt_tpu.api.doc import Crdt
    from crdt_tpu.core.engine import Engine

    doc = Crdt(1)
    for i in range(2000):
        doc.set("big", f"k{i}", {"v": i})
    calls = []
    orig = Engine.map_json

    def counting(self, name):
        calls.append(name)
        return orig(self, name)

    Engine.map_json = counting
    try:
        doc.set("big", "k7", "updated")
        doc.delete("big", "k9")
    finally:
        Engine.map_json = orig
    # per-key refresh: the 2000-key map is never re-materialized
    # ("ix" lookups via map_get are fine; map_json("big") is the smell)
    assert "big" not in calls, calls
    assert doc.c["big"]["k7"] == "updated"
    assert "k9" not in doc.c["big"]
    assert len(doc.c["big"]) == 1999


def test_cache_snapshots_stay_immutable_across_per_key_refresh():
    """Observer events hold the pre-txn snapshot; the per-key refresh
    must rebind, not mutate."""
    from crdt_tpu.api.doc import Crdt

    events = []
    doc = Crdt(1, observer_function=events.append)
    doc.set("m", "a", 1)
    snap_after_first = events[-1]["c"]
    doc.set("m", "b", 2)
    assert dict(snap_after_first["m"]) == {"a": 1}  # unchanged snapshot
    assert dict(doc.c["m"]) == {"a": 1, "b": 2}
