"""tools/metrics_diff.py: the BENCH_OUT regression gate, on two
synthetic reports (the round-5 verdict's durable-evidence
follow-through — committed artifacts must be diffable by one
command)."""

import copy
import json
import os
import sys

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools",
    ),
)
from metrics_diff import compare, format_table, main  # noqa: E402

OLD = {
    "metric": "e2e_trace_replay_lww_yata",
    "value": 100000,
    "unit": "ops/s",
    "vs_baseline": 2.0,
    "dispatch_floor_ms": 30.0,
    "phases_device_s": {"decode": 1.0, "converge": 2.0},
    "scale_run": {"vs_baseline": 3.0, "stream_vs_oneshot": 1.5},
    "xfer": {"h2d_bytes": 1_000_000, "d2h_bytes": 200_000,
             "h2d_bytes_saved": 1_000_000},
    "tracer": {
        "spans": {
            "decode": {"p50_s": 0.10, "p99_s": 0.20, "total_s": 1.0},
            "pack": {"p50_s": 0.05, "p99_s": 0.08, "total_s": 0.5},
        },
        "counters": {
            "xfer.h2d_bytes": 2_000_000,
            "xfer.d2h_bytes": 400_000,
            "xfer.staged_bytes": 2_000_000,
            "xfer.h2d_bytes_saved": 2_000_000,
            'xfer.col_width{bits="16",col="client"}': 4,
        },
        "gauges": {"xfer.narrowed_ratio": 0.5},
    },
}


def test_identical_reports_pass():
    rows, regressed = compare(OLD, copy.deepcopy(OLD))
    assert regressed == []
    assert all(r["verdict"] == "ok" for r in rows)
    assert format_table(rows)  # renders without crashing


def test_regressions_detected_in_both_directions():
    new = copy.deepcopy(OLD)
    new["value"] = 50000               # ops/s halved: worse (higher=better)
    new["dispatch_floor_ms"] = 60.0    # doubled: worse (lower=better)
    new["tracer"]["spans"]["decode"]["p99_s"] = 0.9  # tail blowup
    rows, regressed = compare(OLD, new, threshold=0.2)
    assert "value" in regressed
    assert "dispatch_floor_ms" in regressed
    assert "tracer.decode.p99_s" in regressed
    by_name = {r["metric"]: r for r in rows}
    assert by_name["value"]["verdict"] == "REGRESSION"
    assert by_name["value"]["delta_pct"] == -50.0


def test_improvements_never_fail():
    new = copy.deepcopy(OLD)
    new["value"] = 400000
    new["tracer"]["spans"]["decode"]["p99_s"] = 0.01
    rows, regressed = compare(OLD, new)
    assert regressed == []
    by_name = {r["metric"]: r for r in rows}
    assert by_name["value"]["verdict"] == "improved"


def test_threshold_is_respected():
    new = copy.deepcopy(OLD)
    new["vs_baseline"] = 1.8  # -10%
    _, regressed = compare(OLD, new, threshold=0.2)
    assert regressed == []
    _, regressed = compare(OLD, new, threshold=0.05)
    assert "vs_baseline" in regressed


def test_sub_noise_floor_timings_never_fail():
    old = {"tracer": {"spans": {
        "tiny": {"p50_s": 0.0005, "p99_s": 0.001, "total_s": 0.002},
    }}}
    new = {"tracer": {"spans": {
        "tiny": {"p50_s": 0.002, "p99_s": 0.004, "total_s": 0.004},
    }}}
    rows, regressed = compare(old, new)
    assert regressed == []  # 4x worse but under 5ms: scheduler noise
    assert any(r["verdict"] == "noise" for r in rows)


def test_ms_metrics_respect_noise_floor():
    # the floor is denominated in seconds; a 0.3ms wobble on a 1ms
    # metric is scheduler noise, a 30ms jump on a 30ms metric is not
    rows, regressed = compare(
        {"dispatch_floor_ms": 1.0}, {"dispatch_floor_ms": 1.3}
    )
    assert regressed == []
    assert any(r["verdict"] == "noise" for r in rows)
    _, regressed = compare(
        {"dispatch_floor_ms": 30.0}, {"dispatch_floor_ms": 60.0}
    )
    assert "dispatch_floor_ms" in regressed


def test_missing_sections_are_skipped():
    rows, regressed = compare({"value": 1, "unit": "ops/s"}, {})
    assert rows == [] and regressed == []


def test_xfer_bytes_lower_is_better():
    # the transfer diet undone (staged bytes doubled) must regress;
    # labelled per-column width counters are layout detail, never
    # compared
    new = copy.deepcopy(OLD)
    new["xfer"]["h2d_bytes"] = 3_000_000
    new["tracer"]["counters"]["xfer.h2d_bytes"] = 6_000_000
    new["tracer"]["counters"]["xfer.staged_bytes"] = 6_000_000
    rows, regressed = compare(OLD, new)
    assert "xfer.h2d_bytes" in regressed
    assert "tracer.xfer.h2d_bytes" in regressed
    # run-level ratio derived from the STAGED counters regresses too
    # (0.5 -> 0.75 staged/wide); the raw last-writer-wins gauge is
    # per-upload detail and must NOT be gated (it flaps with shard
    # staging order)
    assert "xfer.narrowed_ratio_run" in regressed
    assert not any(
        r["metric"] == "tracer.xfer.narrowed_ratio" for r in rows
    )
    assert not any("col_width" in r["metric"] for r in rows)


def test_xfer_ratio_ignores_non_staged_traffic_mix():
    # growing fleet/resident-delta uploads (xfer.h2d_bytes) without
    # touching the staged uploads must NOT move the narrowing ratio
    new = copy.deepcopy(OLD)
    new["tracer"]["counters"]["xfer.h2d_bytes"] = 20_000_000
    rows, regressed = compare(OLD, new)
    by_name = {r["metric"]: r for r in rows}
    assert by_name["xfer.narrowed_ratio_run"]["delta_pct"] == 0.0
    assert "xfer.narrowed_ratio_run" not in regressed


def test_xfer_bytes_saved_higher_is_better():
    # saving MORE bytes is an improvement, not a byte regression
    new = copy.deepcopy(OLD)
    new["xfer"]["h2d_bytes_saved"] = 4_000_000
    new["tracer"]["counters"]["xfer.h2d_bytes_saved"] = 8_000_000
    rows, regressed = compare(OLD, new)
    assert regressed == []
    by_name = {r["metric"]: r for r in rows}
    assert by_name["xfer.h2d_bytes_saved"]["verdict"] == "improved"
    # ...and saving fewer bytes regresses
    worse = copy.deepcopy(OLD)
    worse["tracer"]["counters"]["xfer.h2d_bytes_saved"] = 100
    _, regressed = compare(OLD, worse)
    assert "tracer.xfer.h2d_bytes_saved" in regressed


def test_xfer_byte_regressions_ignore_seconds_noise_floor():
    # bytes are not time: a small-but-real byte regression must not
    # be muted by the seconds noise floor
    old = {"xfer": {"h2d_bytes": 2048}}
    new = {"xfer": {"h2d_bytes": 4096}}
    _, regressed = compare(old, new)
    assert "xfer.h2d_bytes" in regressed


def test_cli_exit_codes(tmp_path, capsys):
    a, b = tmp_path / "old.json", tmp_path / "new.json"
    a.write_text(json.dumps(OLD))
    worse = copy.deepcopy(OLD)
    worse["value"] = 10000
    b.write_text(json.dumps(worse))
    assert main([str(a), str(a)]) == 0
    out = capsys.readouterr()
    assert "no regressions" in out.out
    assert main([str(a), str(b)]) == 1
    out = capsys.readouterr()
    assert "REGRESSION" in out.out
    # a loose threshold lets the same pair pass
    assert main([str(a), str(b), "--threshold", "0.95"]) == 0


def test_guard_counters_lower_is_better():
    """The robustness registry (guard.* / evictions / degraded /
    device fallbacks) gates direction-aware: MORE degradation on the
    same workload is a regression, less is an improvement."""
    old = copy.deepcopy(OLD)
    old["tracer"]["counters"].update({
        "guard.inbox_shed": 10,
        "engine.pending_evictions": 4,
        "persist.degraded_writes": 1,
        "device.fallback": 2,
        "persist.recovered_updates": 1,
        'device.fallback_by{route="host"}': 2,  # labeled: skipped
    })
    old["tracer"]["gauges"]["persist.degraded"] = 0
    new = copy.deepcopy(old)
    new["tracer"]["counters"]["guard.inbox_shed"] = 30
    new["tracer"]["counters"]["device.fallback"] = 1
    new["tracer"]["counters"]["persist.recovered_updates"] = 99
    rows, regressed = compare(old, new)
    assert "tracer.guard.inbox_shed" in regressed
    by_name = {r["metric"]: r for r in rows}
    assert by_name["tracer.device.fallback"]["verdict"] == "improved"
    # recovered_updates and labeled counters are not gated
    assert "tracer.persist.recovered_updates" not in by_name
    assert not any("route=" in r["metric"] for r in rows)


def test_overload_section_gated():
    old = copy.deepcopy(OLD)
    old["overload"] = {
        "peak_inbox_bytes": 300, "shed_count": 12, "shed_bytes": 900,
        "heal_s": 0.5,
    }
    new = copy.deepcopy(old)
    new["overload"]["peak_inbox_bytes"] = 500
    rows, regressed = compare(old, new)
    assert "overload.peak_inbox_bytes" in regressed


def test_kernel_sweep_and_ablation_gated():
    """Round 12: kernel_sweep_net_ms per size and the ablation legs
    are direction-aware gates — net ms lower-is-better (ms noise
    floor), sort_map_speedup higher-is-better and never muted."""
    old = copy.deepcopy(OLD)
    old["kernel_sweep_net_ms"] = {"25000": 8.0, "100000": 31.0}
    old["kernel_ablation"] = {
        "sort_ms": {"jnp": 6.0, "pallas": 2.0},
        "map_winners_ms": {"jnp": 8.0, "pallas": 3.0},
        "rank_ms": {"jnp": 9.0, "pallas": 7.0},
        "sort_map_speedup": 2.8,
    }
    new = copy.deepcopy(old)
    rows, regressed = compare(old, new)
    names = {r["metric"] for r in rows}
    assert "kernel_sweep_net_ms.100000_ms" in names
    assert "kernel_ablation.sort_ms.pallas_ms" in names
    assert "kernel_ablation.sort_map_speedup" in names
    assert regressed == []

    # net-compute regression past the threshold fails the gate
    new["kernel_sweep_net_ms"]["100000"] = 62.0
    new["kernel_ablation"]["sort_ms"]["pallas"] = 5.0
    rows, regressed = compare(old, new, threshold=0.2)
    assert "kernel_sweep_net_ms.100000_ms" in regressed
    assert "kernel_ablation.sort_ms.pallas_ms" in regressed

    # the >=2x speedup claim eroding fails as a regression (higher is
    # better), and a speedup IMPROVEMENT never does
    new2 = copy.deepcopy(old)
    new2["kernel_ablation"]["sort_map_speedup"] = 1.4
    _, regressed = compare(old, new2, threshold=0.2)
    assert "kernel_ablation.sort_map_speedup" in regressed
    new3 = copy.deepcopy(old)
    new3["kernel_ablation"]["sort_map_speedup"] = 9.0
    _, regressed = compare(old, new3, threshold=0.2)
    assert regressed == []

    # sub-noise-floor ms stay reported but never fail
    new4 = copy.deepcopy(old)
    old["kernel_ablation"]["rank_ms"]["pallas"] = 0.002
    new4["kernel_ablation"]["rank_ms"]["pallas"] = 0.004
    rows, regressed = compare(old, new4, threshold=0.2)
    assert "kernel_ablation.rank_ms.pallas_ms" not in regressed
    assert any(r["metric"] == "kernel_ablation.rank_ms.pallas_ms"
               and r["verdict"] == "noise" for r in rows)


def test_lint_findings_gated_lower_is_better():
    """crdtlint satellite: a PR that grows the lint baseline (or
    sprinkles inline disables) moves lint.findings and fails the
    gate — and because it's a count, the seconds noise floor must
    never mute it."""
    old = copy.deepcopy(OLD)
    old["lint"] = {"findings": 23, "open": 0, "baselined": 23,
                   "suppressed": 0}
    new = copy.deepcopy(old)
    new["lint"]["findings"] = 31
    new["lint"]["baselined"] = 31
    rows, regressed = compare(old, new)
    assert "lint.findings" in regressed
    assert "lint.baselined" in regressed
    # shrinking the baseline reads as an improvement, never a failure
    shrunk = copy.deepcopy(old)
    shrunk["lint"]["findings"] = 2
    shrunk["lint"]["baselined"] = 2
    rows, regressed = compare(old, shrunk)
    assert regressed == []
    by_name = {r["metric"]: r for r in rows}
    assert by_name["lint.findings"]["verdict"] == "improved"
    # tiny absolute counts still gate (no noise floor for counts):
    # 0 -> 1 open finding is an infinite relative regression
    zero = copy.deepcopy(old)
    zero["lint"]["findings"] = 0
    one = copy.deepcopy(old)
    one["lint"]["findings"] = 1
    rows, regressed = compare(zero, one)
    assert "lint.findings" in regressed


def test_lint_open_by_family_gated():
    """Round 16: per-family OPEN counts for the new analysis
    families (cl7 trace purity / cl8 lock discipline / cl9 async
    handles) gate lower-is-better with count semantics — the
    committed tree holds them at 0, so a single new open finding is
    an infinite relative regression the noise floor can never
    mute."""
    old = copy.deepcopy(OLD)
    old["lint"] = {
        "findings": 23, "open": 0, "baselined": 23, "suppressed": 0,
        "open_by_family": {"cl7": 0, "cl8": 0, "cl9": 0,
                           "cl10": 0, "cl11": 0},
    }
    for fam in ("cl7", "cl8", "cl9", "cl10", "cl11"):
        new = copy.deepcopy(old)
        new["lint"]["open_by_family"][fam] = 1
        rows, regressed = compare(old, new)
        assert f"lint.open_by_family.{fam}" in regressed, fam
    # all-zero stays clean, and fixing a finding is an improvement
    rows, regressed = compare(old, copy.deepcopy(old))
    assert regressed == []
    was_one = copy.deepcopy(old)
    was_one["lint"]["open_by_family"]["cl8"] = 1
    rows, regressed = compare(was_one, old)
    assert regressed == []


def test_lint_digest_embeds_family_counts_and_callgraph():
    """The bench digest (bench.lint_digest over the real tree) must
    carry the per-family counts the gate reads AND the call-graph
    size stats — the round-16 analysis layer's own evidence. The
    committed tree holds every new family at 0 open."""
    import bench

    digest = bench.lint_digest()
    assert digest, "lint_digest unexpectedly empty"
    fams = digest["open_by_family"]
    assert set(fams) == {"cl7", "cl8", "cl9", "cl10", "cl11"}
    assert fams == {"cl7": 0, "cl8": 0, "cl9": 0,
                    "cl10": 0, "cl11": 0}
    cgs = digest["callgraph"]
    for key in ("functions", "edges", "weak_edges", "collisions",
                "thread_roots", "thread_reachable"):
        assert isinstance(cgs[key], int), key
    # the graph really covers the tree: hundreds of defs, and the
    # streaming stager + decode pool give at least two thread roots
    assert cgs["functions"] > 300
    assert cgs["edges"] > cgs["functions"]
    assert cgs["thread_roots"] >= 2


def test_multichip_section_gated():
    """Round 13: the multichip leg's scaling efficiency is
    higher-is-better per device count; boundary bytes/fraction and
    the shard/wyllie tracer evidence are lower-is-better counts the
    seconds noise floor must never mute."""
    old = copy.deepcopy(OLD)
    old["multichip"] = {
        "scaling_efficiency": {"2": 1.6, "8": 2.4},
        "boundary_bytes": 400_000,
        "boundary_fraction": 0.05,
    }
    old["tracer"]["counters"]["shard.boundary_bytes"] = 400_000
    old["tracer"]["gauges"] = {"converge.wyllie_rounds": 14}
    new = copy.deepcopy(old)
    rows, regressed = compare(old, new)
    names = {r["metric"] for r in rows}
    assert "multichip.scaling_efficiency.2" in names
    assert "multichip.boundary_bytes" in names
    assert "tracer.shard.boundary_bytes" in names
    assert "tracer.converge.wyllie_rounds" in names
    assert regressed == []

    # scaling efficiency eroding fails (higher is better)...
    new["multichip"]["scaling_efficiency"]["2"] = 1.0
    _, regressed = compare(old, new, threshold=0.2)
    assert "multichip.scaling_efficiency.2" in regressed
    # ...improving never does
    new2 = copy.deepcopy(old)
    new2["multichip"]["scaling_efficiency"]["2"] = 3.0
    _, regressed = compare(old, new2, threshold=0.2)
    assert regressed == []

    # boundary bytes growing past the threshold fails — counts, so
    # the seconds noise floor cannot mute them
    new3 = copy.deepcopy(old)
    new3["multichip"]["boundary_bytes"] = 900_000
    new3["multichip"]["boundary_fraction"] = 0.12
    new3["tracer"]["counters"]["shard.boundary_bytes"] = 900_000
    rows, regressed = compare(old, new3, threshold=0.2)
    assert "multichip.boundary_bytes" in regressed
    assert "multichip.boundary_fraction" in regressed
    assert "tracer.shard.boundary_bytes" in regressed

    # a chain-split regression (rounds bound growing) fails too
    new4 = copy.deepcopy(old)
    new4["tracer"]["gauges"]["converge.wyllie_rounds"] = 18
    _, regressed = compare(old, new4, threshold=0.2)
    assert "tracer.converge.wyllie_rounds" in regressed


def test_multitenant_section_gated():
    """Round 14: the multitenant leg's docs/s and packing speedup are
    higher-is-better; per-doc p99 and dispatches-per-tick are
    lower-is-better, and none of them is muted by the seconds noise
    floor (they are section keys, not tracer timings). The
    tenant-scoped shed counters gate lower-is-better like every
    guard ladder."""
    old = copy.deepcopy(OLD)
    old["multitenant"] = {
        "docs_converged_per_s": 4000.0,
        "speedup": 12.0,
        "p99_per_doc_ms": 250.0,
        "dispatches_per_tick": 5,
    }
    old["tracer"]["counters"]["tenant.shed"] = 4
    old["tracer"]["counters"]["tenant.shed_bytes"] = 4096
    new = copy.deepcopy(old)
    rows, regressed = compare(old, new)
    names = {r["metric"] for r in rows}
    assert "multitenant.docs_converged_per_s" in names
    assert "multitenant.speedup" in names
    assert "multitenant.p99_per_doc_ms" in names
    assert "multitenant.dispatches_per_tick" in names
    assert "tracer.tenant.shed" in names
    assert regressed == []

    # throughput / speedup eroding fails (higher is better)...
    new["multitenant"]["docs_converged_per_s"] = 2500.0
    new["multitenant"]["speedup"] = 7.0
    _, regressed = compare(old, new, threshold=0.2)
    assert "multitenant.docs_converged_per_s" in regressed
    assert "multitenant.speedup" in regressed
    # ...improving never does
    new2 = copy.deepcopy(old)
    new2["multitenant"]["docs_converged_per_s"] = 9000.0
    _, regressed = compare(old, new2, threshold=0.2)
    assert regressed == []

    # tail latency and dispatch count growing fail — and p99 is a
    # section key, so the ms noise floor cannot mute it even at
    # sub-floor absolute values
    new3 = copy.deepcopy(old)
    new3["multitenant"]["p99_per_doc_ms"] = 400.0
    new3["multitenant"]["dispatches_per_tick"] = 9
    _, regressed = compare(old, new3, threshold=0.2)
    assert "multitenant.p99_per_doc_ms" in regressed
    assert "multitenant.dispatches_per_tick" in regressed
    old4 = copy.deepcopy(old)
    old4["multitenant"]["p99_per_doc_ms"] = 0.8  # below 5ms floor
    new4 = copy.deepcopy(old4)
    new4["multitenant"]["p99_per_doc_ms"] = 2.4
    _, regressed = compare(old4, new4, threshold=0.2)
    assert "multitenant.p99_per_doc_ms" in regressed

    # tenant shedding rising past threshold fails (guard semantics)
    new5 = copy.deepcopy(old)
    new5["tracer"]["counters"]["tenant.shed"] = 9
    _, regressed = compare(old, new5, threshold=0.2)
    assert "tracer.tenant.shed" in regressed


def test_multitenant_steady_section_gated():
    """Round 15 (delta ticks): the steady-state leg's docs/s and its
    speedup over the full-replay tick are higher-is-better; the
    eviction flood's committed resident peak is lower-is-better
    (bytes — a count, never muted by the seconds noise floor); the
    resident-eviction and delta-fallback counters gate
    lower-is-better like every guard ladder."""
    old = copy.deepcopy(OLD)
    old["multitenant"] = {
        "steady": {
            "docs_per_s": 5000.0,
            "speedup": 40.0,
            "eviction": {"peak_bytes": 1_000_000},
        },
    }
    old["tracer"]["counters"]["tenant.resident_evictions"] = 10
    old["tracer"]["counters"]["tenant.delta_fallbacks"] = 2
    new = copy.deepcopy(old)
    rows, regressed = compare(old, new)
    names = {r["metric"] for r in rows}
    assert "multitenant.steady.docs_per_s" in names
    assert "multitenant.steady.speedup" in names
    assert "multitenant.steady.eviction.peak_bytes" in names
    assert "tracer.tenant.resident_evictions" in names
    assert "tracer.tenant.delta_fallbacks" in names
    assert regressed == []

    # the >=10x steady bar eroding fails (higher is better)
    new["multitenant"]["steady"]["docs_per_s"] = 2000.0
    new["multitenant"]["steady"]["speedup"] = 12.0
    _, regressed = compare(old, new, threshold=0.2)
    assert "multitenant.steady.docs_per_s" in regressed
    assert "multitenant.steady.speedup" in regressed

    # resident peak growing past threshold fails; shrinking never
    new2 = copy.deepcopy(old)
    new2["multitenant"]["steady"]["eviction"]["peak_bytes"] = \
        2_000_000
    _, regressed = compare(old, new2, threshold=0.2)
    assert "multitenant.steady.eviction.peak_bytes" in regressed
    new3 = copy.deepcopy(old)
    new3["multitenant"]["steady"]["eviction"]["peak_bytes"] = 500_000
    _, regressed = compare(old, new3, threshold=0.2)
    assert regressed == []

    # eviction thrash / fallback churn gate like guard counters
    new4 = copy.deepcopy(old)
    new4["tracer"]["counters"]["tenant.resident_evictions"] = 30
    new4["tracer"]["counters"]["tenant.delta_fallbacks"] = 9
    _, regressed = compare(old, new4, threshold=0.2)
    assert "tracer.tenant.resident_evictions" in regressed
    assert "tracer.tenant.delta_fallbacks" in regressed


def test_pooled_dispatch_floor_gated():
    """Round 20 (pooled resident matrix): the steady dispatch floor
    ``multitenant.steady.device_dispatches_per_tick`` gates lower-
    is-better with COUNT semantics — the ms noise floor must never
    mute a pooled route rotting back to one-dispatch-per-doc — and
    the pool's peak allocation gates like the eviction flood's
    resident peak (bytes, lower). Both directions pinned."""
    old = copy.deepcopy(OLD)
    old["multitenant"] = {
        "steady": {
            "device_dispatches_per_tick": 1.0,
            "pool_peak_bytes": 2_097_152,
        },
    }
    new = copy.deepcopy(old)
    rows, regressed = compare(old, new)
    names = {r["metric"] for r in rows}
    assert "multitenant.steady.device_dispatches_per_tick" in names
    assert "multitenant.steady.pool_peak_bytes" in names
    assert regressed == []

    # the floor eroding back toward per-doc dispatches FAILS — and
    # not as "noise", however cheap each dispatch is (count, not ms)
    bad = copy.deepcopy(old)
    bad["multitenant"]["steady"]["device_dispatches_per_tick"] = 8.0
    rows, regressed = compare(old, bad, threshold=0.2)
    assert "multitenant.steady.device_dispatches_per_tick" \
        in regressed
    by_name = {r["metric"]: r["verdict"] for r in rows}
    assert by_name[
        "multitenant.steady.device_dispatches_per_tick"
    ] == "REGRESSION"

    # fewer dispatches (a better batch) never fails
    better = copy.deepcopy(old)
    old2 = copy.deepcopy(old)
    old2["multitenant"]["steady"]["device_dispatches_per_tick"] = 2.0
    _, regressed = compare(old2, better, threshold=0.2)
    assert regressed == []

    # pool peak growing past threshold fails; shrinking never
    bad2 = copy.deepcopy(old)
    bad2["multitenant"]["steady"]["pool_peak_bytes"] = 4_194_304
    _, regressed = compare(old, bad2, threshold=0.2)
    assert "multitenant.steady.pool_peak_bytes" in regressed
    good2 = copy.deepcopy(old)
    good2["multitenant"]["steady"]["pool_peak_bytes"] = 1_048_576
    _, regressed = compare(old, good2, threshold=0.2)
    assert regressed == []


def test_lint_open_by_family_gates_against_pre_round16_artifact():
    """Review round 2: an old artifact predating the round-16 digest
    has no open_by_family key — that means 0 open findings (the
    committed tree always lints clean), so the gate must treat the
    absent side as zero instead of silently skipping the row."""
    old = copy.deepcopy(OLD)
    old["lint"] = {"findings": 23, "open": 0, "baselined": 23,
                   "suppressed": 0}  # no open_by_family at all
    new = copy.deepcopy(old)
    new["lint"]["open_by_family"] = {"cl7": 0, "cl8": 1, "cl9": 0}
    rows, regressed = compare(old, new)
    assert "lint.open_by_family.cl8" in regressed
    clean = copy.deepcopy(old)
    clean["lint"]["open_by_family"] = {"cl7": 0, "cl8": 0, "cl9": 0}
    rows, regressed = compare(old, clean)
    assert regressed == []
    # same zero-default for the round-17 families: an artifact that
    # predates cl10/cl11 gates the moment the NEW side carries them
    wired = copy.deepcopy(old)
    wired["lint"]["open_by_family"] = {"cl10": 2, "cl11": 0}
    rows, regressed = compare(old, wired)
    assert "lint.open_by_family.cl10" in regressed
    assert "lint.open_by_family.cl11" not in regressed


def test_slo_and_timeline_gates_direction_aware():
    """Round 18: slo.breaches and timeline.stall_ms regress when they
    RISE; timeline.overlap_efficiency regresses when it FALLS (the
    double-buffer re-serialized). All direction-aware, none muted by
    the seconds noise floor except stall (ms-denominated)."""
    old = {"tracer": {
        "counters": {"slo.breaches": 10},
        "gauges": {"timeline.stall_ms": 100.0,
                   "timeline.overlap_efficiency": 0.8},
    }}
    good = copy.deepcopy(old)
    rows, regressed = compare(old, good)
    assert regressed == []
    names = {r["metric"] for r in rows}
    assert {"tracer.slo.breaches", "tracer.timeline.stall_ms",
            "tracer.timeline.overlap_efficiency"} <= names

    bad = copy.deepcopy(old)
    bad["tracer"]["counters"]["slo.breaches"] = 20        # +100%
    bad["tracer"]["gauges"]["timeline.stall_ms"] = 200.0  # +100%
    bad["tracer"]["gauges"]["timeline.overlap_efficiency"] = 0.4
    rows, regressed = compare(old, bad, threshold=0.2)
    assert "tracer.slo.breaches" in regressed
    assert "tracer.timeline.stall_ms" in regressed
    assert "tracer.timeline.overlap_efficiency" in regressed

    # the opposite directions are improvements, never failures
    better = copy.deepcopy(old)
    better["tracer"]["counters"]["slo.breaches"] = 0
    better["tracer"]["gauges"]["timeline.stall_ms"] = 10.0
    better["tracer"]["gauges"]["timeline.overlap_efficiency"] = 0.99
    rows, regressed = compare(old, better)
    assert regressed == []
    by_name = {r["metric"]: r for r in rows}
    assert by_name["tracer.timeline.overlap_efficiency"][
        "verdict"] == "improved"


def test_timeline_stall_respects_ms_noise_floor():
    # a 3x jump on a sub-millisecond stall is scheduler noise; the
    # same jump at tens of ms is a real pipeline regression
    old = {"tracer": {"gauges": {"timeline.stall_ms": 0.5}}}
    new = {"tracer": {"gauges": {"timeline.stall_ms": 1.5}}}
    rows, regressed = compare(old, new)
    assert regressed == []
    assert any(r["verdict"] == "noise" for r in rows)
    old = {"tracer": {"gauges": {"timeline.stall_ms": 50.0}}}
    new = {"tracer": {"gauges": {"timeline.stall_ms": 150.0}}}
    _, regressed = compare(old, new)
    assert "tracer.timeline.stall_ms" in regressed


def test_multitenant_obs_v2_section_keys_gated():
    """The run-stable artifact keys the --multitenant harness embeds:
    mean overlap (higher), total stall (lower, ms noise floor), and
    the chaos flooder's DETERMINISTIC breach count (lower) — not the
    default-objective legs' wall-clock totals, whose 0 baseline
    would make one slow-machine miss an infinite-delta failure."""
    old = {"multitenant": {
        "timeline": {"mean_overlap_efficiency": 0.6,
                     "stall_ms_total": 80.0},
        "flood": {"slo_flooder": {"breaches": 19}},
    }}
    bad = {"multitenant": {
        "timeline": {"mean_overlap_efficiency": 0.2,
                     "stall_ms_total": 200.0},
        "flood": {"slo_flooder": {"breaches": 40}},
    }}
    _, regressed = compare(old, bad, threshold=0.2)
    assert "multitenant.timeline.mean_overlap_efficiency" in regressed
    assert "multitenant.timeline.stall_ms_total_ms" in regressed
    assert "multitenant.flood.slo_flooder.breaches" in regressed
    _, regressed = compare(old, copy.deepcopy(old))
    assert regressed == []
    # stall_ms_total is wall-clock: a sub-floor wobble is noise
    tiny_old = {"multitenant": {"timeline": {"stall_ms_total": 0.8}}}
    tiny_bad = {"multitenant": {"timeline": {"stall_ms_total": 2.4}}}
    rows, regressed = compare(tiny_old, tiny_bad)
    assert regressed == []
    assert any(r["verdict"] == "noise" for r in rows)


def test_fleet_trace_section_keys_gated():
    """Round 19: the --fleet-trace artifact keys — procs and
    pair_rate regress when they FALL (fewer processes federated /
    paths no longer reconstructing), wire_overhead_ratio when it
    RISES (the tracing tax grew). Counts/ratios: the seconds noise
    floor never mutes them."""
    old = {"fleet_trace": {"procs": 3, "pair_rate": 1.0,
                           "wire_overhead_ratio": 0.02}}
    _, regressed = compare(old, copy.deepcopy(old))
    assert regressed == []
    bad = {"fleet_trace": {"procs": 2, "pair_rate": 0.6,
                           "wire_overhead_ratio": 0.06}}
    _, regressed = compare(old, bad, threshold=0.2)
    assert "fleet_trace.procs" in regressed
    assert "fleet_trace.pair_rate" in regressed
    assert "fleet_trace.wire_overhead_ratio" in regressed
    # the opposite directions never fail
    better = {"fleet_trace": {"procs": 5, "pair_rate": 1.0,
                              "wire_overhead_ratio": 0.001}}
    _, regressed = compare(old, better)
    assert regressed == []


def test_collector_and_propagation_gauges_gated():
    """Round 19 tracer rows: collector.procs / collector.pair_rate
    regress on a FALL (federation shrank / live reconstruction
    broke); propagation.wire_overhead_ratio and
    propagation.malformed_contexts regress on a RISE."""
    old = {"tracer": {
        "counters": {"propagation.malformed_contexts": 2},
        "gauges": {"collector.procs": 3, "collector.pair_rate": 1.0,
                   "propagation.wire_overhead_ratio": 0.02},
    }}
    _, regressed = compare(old, copy.deepcopy(old))
    assert regressed == []
    bad = {"tracer": {
        "counters": {"propagation.malformed_contexts": 50},
        "gauges": {"collector.procs": 1, "collector.pair_rate": 0.5,
                   "propagation.wire_overhead_ratio": 0.2},
    }}
    _, regressed = compare(old, bad, threshold=0.2)
    assert "tracer.collector.procs" in regressed
    assert "tracer.collector.pair_rate" in regressed
    assert "tracer.propagation.wire_overhead_ratio" in regressed
    assert "tracer.propagation.malformed_contexts" in regressed


def test_per_route_hop_lag_spans_gated():
    """The route-labeled replica.hop_lag histograms ride the span
    loop: p50/p99/total per route, lower-is-better, seconds noise
    floor applies (a sub-5ms wobble is scheduler noise)."""
    old = {"tracer": {"spans": {
        'replica.hop_lag{route="relayed"}': {
            "p50_s": 0.10, "p99_s": 0.30, "total_s": 2.0},
        'replica.hop_lag{route="direct"}': {
            "p50_s": 0.01, "p99_s": 0.02, "total_s": 0.2},
    }}}
    bad = copy.deepcopy(old)
    bad["tracer"]["spans"][
        'replica.hop_lag{route="relayed"}']["p99_s"] = 0.9
    _, regressed = compare(old, bad, threshold=0.2)
    assert 'tracer.replica.hop_lag{route="relayed"}.p99_s' in \
        regressed
    # sub-floor route lags never fail
    tiny_old = {"tracer": {"spans": {
        'replica.hop_lag{route="direct"}': {
            "p50_s": 0.0001, "p99_s": 0.0002, "total_s": 0.001}}}}
    tiny_bad = copy.deepcopy(tiny_old)
    tiny_bad["tracer"]["spans"][
        'replica.hop_lag{route="direct"}']["p99_s"] = 0.002
    _, regressed = compare(tiny_old, tiny_bad)
    assert regressed == []


def test_cold_start_section_keys_gated():
    """Round 21: the --coldstart artifact keys — join_ms /
    checkpoint_ms / restore_ms regress when they RISE (recovery got
    slower; SECTION keys, so the ms noise floor never mutes them),
    speedup when it FALLS (the snapshot join's edge over full WAL
    replay eroded — the >=5x acceptance bar lives in the artifact),
    and snap_fallbacks_counted when it RISES (the same run hit more
    damaged snapshots on the ladder)."""
    old = {"cold_start": {
        "join_ms": 40.0, "replay_ms": 800.0, "speedup": 20.0,
        "checkpoint_ms": 30.0, "restore_ms": 25.0,
        "snap_fallbacks_counted": 1,
    }}
    _, regressed = compare(old, copy.deepcopy(old))
    assert regressed == []
    bad = {"cold_start": {
        "join_ms": 400.0, "replay_ms": 800.0, "speedup": 2.0,
        "checkpoint_ms": 90.0, "restore_ms": 80.0,
        "snap_fallbacks_counted": 9,
    }}
    _, regressed = compare(old, bad, threshold=0.2)
    assert "cold_start.join_ms" in regressed
    assert "cold_start.speedup" in regressed
    assert "cold_start.checkpoint_ms" in regressed
    assert "cold_start.restore_ms" in regressed
    assert "cold_start.snap_fallbacks_counted" in regressed
    # replay_ms is a workload fact (the baseline), never gated
    assert not any("replay_ms" in r for r in regressed)
    # the opposite directions never fail: a faster join, a bigger
    # speedup, a cleaner ladder
    better = {"cold_start": {
        "join_ms": 4.0, "replay_ms": 800.0, "speedup": 200.0,
        "checkpoint_ms": 3.0, "restore_ms": 2.0,
        "snap_fallbacks_counted": 0,
    }}
    _, regressed = compare(old, better)
    assert regressed == []


def test_snapshot_guard_counters_lower_is_better():
    """Round 21 guard rows: an UNLABELED snap.fallbacks /
    snap.write_errors total regresses on a rise like any guard
    counter (the reason-labeled variants ride the artifact section
    above — the guard loop skips labeled names by design)."""
    old = {"tracer": {"counters": {
        "snap.fallbacks": 1, "snap.write_errors": 0,
        'snap.fallbacks{reason="crc"}': 1,
    }}}
    bad = {"tracer": {"counters": {
        "snap.fallbacks": 6, "snap.write_errors": 3,
        'snap.fallbacks{reason="crc"}': 6,
    }}}
    _, regressed = compare(old, bad, threshold=0.2)
    assert "tracer.snap.fallbacks" in regressed
    assert "tracer.snap.write_errors" in regressed
    # labeled variants stay out of the guard loop
    assert not any("{" in r for r in regressed)
    _, regressed = compare(old, copy.deepcopy(old))
    assert regressed == []


def test_autopilot_section_keys_gated():
    """Round 22: the --autopilot artifact keys — recovery_ticks
    regresses when it RISES (the flooder's burn takes longer to
    drain once the flood stops; a deterministic tick count, never
    noise-floored) and neighbor_p99_ms when it RISES (the squeeze
    stopped shielding the neighbors; a SECTION key, so a rotted
    squeeze rule fails the gate even under the ms noise floor).
    The OFF-leg twins are workload facts, never gated."""
    old = {"autopilot": {
        "recovery_ticks": 11, "recovery_ticks_off": 10,
        "neighbor_p99_ms": 4.0, "neighbor_p99_ms_off": 5.0,
    }}
    _, regressed = compare(old, copy.deepcopy(old))
    assert regressed == []
    bad = {"autopilot": {
        "recovery_ticks": 20, "recovery_ticks_off": 10,
        "neighbor_p99_ms": 40.0, "neighbor_p99_ms_off": 5.0,
    }}
    _, regressed = compare(old, bad, threshold=0.2)
    assert "autopilot.recovery_ticks" in regressed
    assert "autopilot.neighbor_p99_ms" in regressed
    assert not any("_off" in r for r in regressed)
    better = {"autopilot": {
        "recovery_ticks": 2, "recovery_ticks_off": 10,
        "neighbor_p99_ms": 1.0, "neighbor_p99_ms_off": 5.0,
    }}
    _, regressed = compare(old, better)
    assert regressed == []


def test_control_ledger_dropped_lower_is_better():
    """Round 22 guard row: control.ledger_dropped regresses on a
    rise (a control loop hot enough to churn its own audit ring is
    a finding); decisions/cooldown_skips are deliberately ungated —
    their healthy level is workload-dependent."""
    old = {"tracer": {"counters": {
        "control.ledger_dropped": 0, "control.decisions": 4,
        "control.cooldown_skips": 4,
    }}}
    bad = {"tracer": {"counters": {
        "control.ledger_dropped": 50, "control.decisions": 40,
        "control.cooldown_skips": 40,
    }}}
    _, regressed = compare(old, bad, threshold=0.2)
    assert "tracer.control.ledger_dropped" in regressed
    assert not any("decisions" in r or "cooldown" in r
                   for r in regressed)
    _, regressed = compare(old, copy.deepcopy(old))
    assert regressed == []
