"""Packed one-dispatch replay path (crdt_tpu.ops.packed).

The end-to-end exactness of this path is covered by the replay
differentials (tests/test_models.py, tests/test_grand_differential.py,
which route through it); these tests pin the staging contract and the
branches those suites do not reach: wide clocks, bound fallbacks, and
the stream layout.
"""

import numpy as np

from crdt_tpu.ops import packed


def _cols(n, *, clock_base=0, clients=None, seq=False):
    clients = clients if clients is not None else np.arange(1, n + 1)
    kid = np.full(n, -1 if seq else 0, np.int64)
    origin_c = np.full(n, -1, np.int64)
    origin_k = np.full(n, -1, np.int64)
    return {
        "client": np.asarray(clients, np.int64),
        "clock": np.arange(n, dtype=np.int64) + clock_base,
        "parent_is_root": np.ones(n, bool),
        "parent_a": np.zeros(n, np.int64),
        "parent_b": np.full(n, -1, np.int64),
        "key_id": kid,
        "origin_client": origin_c,
        "origin_clock": origin_k,
        "valid": np.ones(n, bool),
    }


class TestStage:
    def test_staged_matrix(self):
        # a tiny batch narrows to the int16 transfer-diet layout: one
        # flat array of the eight layout sections
        plan = packed.stage(_cols(8))
        assert plan is not None
        assert plan.mat.dtype == np.int16 and plan.mat.ndim == 1
        assert len(plan.encs) == len(packed.SECTION_NAMES)
        assert all(e in ("i16", "d16", "hilo") for e in plan.encs)
        assert plan.n == 8

    def test_forced_wide_matrix(self):
        plan = packed.stage(_cols(8), wide=True)
        assert plan is not None
        assert plan.mat.dtype == np.int32 and plan.mat.ndim == 1
        assert all(e == "i32" for e in plan.encs)

    def test_wide_clock_stays_packed(self):
        # clocks below the shared pack_id bound stay on the packed path
        plan = packed.stage(_cols(8, clock_base=1 << 33))
        assert plan is not None and plan.mat.dtype in (
            np.int16, np.int32
        )

    def test_clock_beyond_pack_bound_falls_back(self):
        assert packed.stage(_cols(8, clock_base=1 << 40)) is None

    def test_empty_returns_none(self):
        cols = _cols(4)
        cols["valid"][:] = False
        assert packed.stage(cols) is None
        assert packed.stage(_cols(0)) is None

    def test_key_bound_fallback(self):
        cols = _cols(4)
        cols["key_id"][:] = 1 << packed._KID_BITS
        assert packed.stage(cols) is None

    def test_seq_bucket_covers_seq_rows(self):
        cols = _cols(200, clients=np.ones(200), seq=True)
        plan = packed.stage(cols)
        assert plan.seq_bucket >= 200

    def test_map_bucket_tracks_map_rows_not_padded_n(self):
        # the round-12 satellite: the map chain runs at MAP-BUCKET
        # width (mirroring the seq compact block), so a seq-heavy
        # union must get a map bucket far below the padded kernel
        # width — the ~100-180ms lever ROOFLINE round 5 priced
        n = 600
        cols = _cols(n, clients=np.ones(n), seq=True)
        cols["key_id"][:8] = 0  # 8 map rows in a 600-row union
        plan = packed.stage(cols)
        assert plan.map_bucket <= 64  # bucket of 8, not of 600
        assert plan.seq_bucket >= n - 8
        assert len(plan.map_back) == plan.map_bucket

    def test_client_interning_order_preserving(self):
        cols = _cols(3, clients=np.array([900, 5, 37]))
        plan = packed.stage(cols)
        assert list(plan.clients) == [5, 37, 900]
        # rows stage id-sorted: the sort permutation maps each staged
        # row back to its caller row, and the grouped map block (one
        # root run here) keeps that id order in its translation table
        assert list(plan.order[:3]) == [1, 2, 0]
        assert list(plan.map_back[:3]) == [1, 2, 0]


class TestConverge:
    def test_map_winners_and_stream(self):
        # 3 clients set the same key; client 3 wins (no chains)
        cols = _cols(3, clients=np.array([1, 2, 3]))
        cols["clock"][:] = 0
        plan = packed.stage(cols)
        res = packed.converge(plan)
        wins = res.win_rows[res.win_rows >= 0]
        assert list(wins) == [2]
        assert not (res.stream_row >= 0).any()

    def test_sequence_stream_document_order(self):
        # one client appends a 5-chain: stream = rows in append order
        n = 5
        cols = _cols(n, clients=np.ones(n), seq=True)
        cols["origin_client"] = np.asarray([-1, 1, 1, 1, 1], np.int64)
        cols["origin_clock"] = np.asarray([-1, 0, 1, 2, 3], np.int64)
        plan = packed.stage(cols)
        res = packed.converge(plan)
        rows = res.stream_row[res.stream_row >= 0]
        assert list(rows) == [0, 1, 2, 3, 4]
        segs = res.stream_seg[res.stream_seg >= 0]
        assert len(set(segs.tolist())) == 1

    def test_duplicate_ids_dedup(self):
        # same (client, clock) delivered twice: one winner, first row kept
        cols = _cols(2, clients=np.array([7, 7]))
        cols["clock"][:] = 0
        plan = packed.stage(cols)
        res = packed.converge(plan)
        wins = res.win_rows[res.win_rows >= 0]
        assert len(wins) == 1

    def test_resident_fallback_matches_packed(self, monkeypatch):
        """The general resident path (taken when stage() refuses a
        batch) must produce the same replay result as the packed
        path — forced here by stubbing stage to refuse."""
        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord
        from crdt_tpu.models import replay_trace

        rng = np.random.default_rng(5)
        blobs = []
        for client in (1, 2, 3):
            recs, ds = [], DeleteSet()
            prev = None
            for k in range(30):
                if k % 3 == 0:
                    recs.append(ItemRecord(
                        client=client, clock=k, parent_root="m",
                        key=f"k{int(rng.integers(0, 4))}", content=k))
                else:
                    recs.append(ItemRecord(
                        client=client, clock=k, parent_root="L",
                        origin=(client, prev) if prev is not None else None,
                        content=k))
                    prev = k
            ds.add(client, 1)
            blobs.append(v1.encode_update(recs, ds))

        want = replay_trace(blobs)  # packed path
        monkeypatch.setattr(packed, "stage", lambda cols, **kw: None)
        got = replay_trace(blobs)   # resident fallback
        assert got.cache == want.cache
        assert got.snapshot == want.snapshot

    def test_wide_path_matches_narrow(self):
        n = 40
        rng = np.random.default_rng(0)
        base = _cols(n, clients=rng.integers(1, 6, n), seq=True)
        base["origin_client"][:] = -1
        base["origin_clock"][:] = -1
        narrow = packed.converge(packed.stage(base))
        wide_cols = {k: v.copy() for k, v in base.items()}
        wide_cols["clock"] = wide_cols["clock"] + (1 << 33)
        wide = packed.converge(packed.stage(wide_cols))
        n_rows = narrow.stream_row[narrow.stream_row >= 0]
        w_rows = wide.stream_row[wide.stream_row >= 0]
        assert list(n_rows) == list(w_rows)


class TestStagedRightOrdering:
    """The packed path orders attachment groups at staging
    (ops.packed._stage_rights): exact conflict-scan ranks ride the
    client column into the fused dispatch. These differentials target
    the shapes that killed the earlier closed-form attempt —
    prepend trees with client drift — plus hard shapes that must
    still take the scalar fallback."""

    @staticmethod
    def _replay_vs_engine(blobs):
        from crdt_tpu.codec import v1
        from crdt_tpu.core.engine import Engine
        from crdt_tpu.models import replay_trace

        out = replay_trace(blobs)
        eng = Engine(10**6)
        for b in blobs:
            v1.apply_update(eng, b)
        assert out.cache == eng_cache(eng), (out.cache, eng_cache(eng))
        return out

    def test_prepend_storm_with_client_drift(self):
        """Every writer keeps prepending at the head (origin None,
        right = current head) — the order depends on the full conflict
        scan, and writers' client ids interleave both ways."""
        import numpy as np

        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord

        for seed in range(6):
            rng = np.random.default_rng(seed)
            # client ids straddle each other so the scan's client
            # comparisons flip direction between groups
            clients = [int(c) for c in rng.permutation([3, 50, 7000, 2])]
            blobs = []
            heads: dict = {}
            for client in clients:
                recs = []
                head = None
                for k in range(12):
                    recs.append(ItemRecord(
                        client=client, clock=k, parent_root="L",
                        origin=None, right=head, content=f"{client}:{k}"))
                    head = (client, k)
                heads[client] = head
                blobs.append(v1.encode_update(recs, DeleteSet()))
            order = rng.permutation(len(blobs))
            self._replay_vs_engine([blobs[i] for i in order])

    def test_mixed_mid_inserts_vs_engine(self):
        """Random interleaved typing with 35% mid-inserts carrying
        both origins, shuffled delivery with duplicates."""
        import numpy as np

        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord

        for seed in range(5):
            rng = np.random.default_rng(100 + seed)
            blobs = []
            for r in range(4):
                client = [5, 80, 3, 900][r]
                recs, chain = [], []
                for k in range(25):
                    if chain and rng.random() < 0.35:
                        j = int(rng.integers(0, len(chain)))
                        recs.append(ItemRecord(
                            client=client, clock=k, parent_root="text",
                            origin=chain[j - 1] if j > 0 else None,
                            right=chain[j], content=k))
                        chain.insert(j, (client, k))
                    else:
                        recs.append(ItemRecord(
                            client=client, clock=k, parent_root="text",
                            origin=chain[-1] if chain else None,
                            content=k))
                        chain.append((client, k))
                blobs.append(v1.encode_update(recs, DeleteSet()))
            delivery = blobs + [blobs[int(rng.integers(0, 4))]]  # dup
            rng.shuffle(delivery)
            self._replay_vs_engine(delivery)

    def test_hard_shape_takes_fallback(self):
        """A right pointing INTO a member's subtree is inexpressible
        by sibling ranks: the plan must mark the segment hard and the
        result must still match the engine."""
        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord
        from crdt_tpu.models import replay as rp
        from crdt_tpu.ops import packed

        recs = [
            ItemRecord(client=1, clock=0, parent_root="L", content="a"),
            ItemRecord(client=1, clock=1, parent_root="L",
                       origin=(1, 0), content="b"),
            # c attaches under b (subtree of a's sibling group member)
            ItemRecord(client=1, clock=2, parent_root="L",
                       origin=(1, 1), content="c"),
            # hostile: same-origin sibling whose right dives into b's
            # subtree (points at c, a DESCENDANT of member b)
            ItemRecord(client=2, clock=0, parent_root="L",
                       origin=(1, 0), right=(1, 2), content="X"),
        ]
        blob = v1.encode_update(recs, DeleteSet())
        dec = rp.decode([blob])
        cols, _ = rp.stage(dec)
        plan = packed.stage(cols)
        assert plan is not None and len(plan.hard_rows) > 0
        self._replay_vs_engine([blob])

    def test_dangling_right_marks_hard(self):
        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord
        from crdt_tpu.models import replay as rp
        from crdt_tpu.ops import packed

        recs = [
            ItemRecord(client=1, clock=0, parent_root="L", content="a"),
            ItemRecord(client=2, clock=0, parent_root="L",
                       origin=(1, 0), right=(77, 5), content="X"),
        ]
        blob = v1.encode_update(recs, DeleteSet())
        dec = rp.decode([blob])
        cols, _ = rp.stage(dec)
        plan = packed.stage(cols)
        assert plan is not None and len(plan.hard_rows) > 0

    def test_clean_attachments_stage_without_fallback(self):
        """The bench's text shape (mid-inserts, all refs resolvable)
        must produce ZERO hard segments — the whole point of staged
        ordering."""
        import numpy as np

        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord
        from crdt_tpu.models import replay as rp
        from crdt_tpu.ops import packed

        rng = np.random.default_rng(7)
        blobs = []
        for r in range(3):
            client, recs, chain = r + 1, [], []
            for k in range(30):
                if chain and rng.random() < 0.3:
                    j = int(rng.integers(0, len(chain)))
                    recs.append(ItemRecord(
                        client=client, clock=k, parent_root="text",
                        origin=chain[j - 1] if j > 0 else None,
                        right=chain[j], content=k))
                    chain.insert(j, (client, k))
                else:
                    recs.append(ItemRecord(
                        client=client, clock=k, parent_root="text",
                        origin=chain[-1] if chain else None, content=k))
                    chain.append((client, k))
            blobs.append(v1.encode_update(recs, DeleteSet()))
        dec = rp.decode(blobs)
        cols, _ = rp.stage(dec)
        plan = packed.stage(cols)
        assert plan is not None and len(plan.hard_rows) == 0
        self._replay_vs_engine(blobs)


def eng_cache(eng):
    """Visible JSON of an engine — same shape replay_trace's cache has."""
    return eng.to_json()
