"""Device merge mode: the TPU kernels in the PRODUCT hot path.

``Crdt(device_merge=True)`` routes every
remote merge through converge_maps + tree_order_ranks instead of the
scalar integrate loop. These tests assert the two paths produce
IDENTICAL engine state — visible JSON, chain order, delete sets,
encoded full state — on every workload class, and that the acceptance
swarms converge with the device path enabled (VERDICT r1 item #1).
"""

import numpy as np
import pytest

from crdt_tpu.api.doc import Crdt


def _drain(docs):
    """Deliver every doc's outbox to every other doc until quiet."""
    progress = True
    while progress:
        progress = False
        for d in docs:
            out, d.outbox = d.outbox, []
            for upd in out:
                for other in docs:
                    if other is not d:
                        other.doc.apply_update(upd)
                        progress = True


class _Peer:
    """Tiny harness: a Crdt plus an outbox of emitted updates."""

    def __init__(self, client_id, device):
        self.outbox = []
        self.doc = Crdt(
            client_id,
            on_update=lambda u, m: self.outbox.append(u),
            device_merge=device,
        )


def _swarm(n, device):
    return [_Peer(i + 1, device) for i in range(n)]


def _assert_same_state(a: Crdt, b: Crdt):
    """Byte-level equivalence of two docs' CRDT state."""
    assert dict(a.c) == dict(b.c)
    assert a.engine.to_json() == b.engine.to_json()
    assert a.engine.delete_set() == b.engine.delete_set()
    assert a.engine.state_vector() == b.engine.state_vector()
    assert a.engine.map_winner_table() == b.engine.map_winner_table()
    assert a.engine.seq_order_table() == b.engine.seq_order_table()
    assert a.encode_state_as_update() == b.encode_state_as_update()


def _run_script(device, script):
    """Run an op script on a 3-peer swarm; return the converged docs."""
    peers = _swarm(3, device)
    script(peers)
    _drain(peers)
    first = dict(peers[0].doc.c)
    for p in peers[1:]:
        assert dict(p.doc.c) == first
    return peers


def _differential(script):
    """Same script under both modes -> identical converged state."""
    scalar = _run_script(False, script)
    device = _run_script(True, script)
    for s, d in zip(scalar, device):
        _assert_same_state(s.doc, d.doc)
    return device


class TestDifferentialModes:
    def test_concurrent_map_sets(self):
        def script(peers):
            for i, p in enumerate(peers):
                for k in range(20):
                    p.doc.set("m", f"k{k % 7}", f"v{i}.{k}")

        _differential(script)

    def test_map_set_delete_interleaved(self):
        def script(peers):
            a, b, c = peers
            for k in range(10):
                a.doc.set("m", f"k{k}", k)
            _drain(peers)
            b.doc.delete("m", "k3")
            c.doc.set("m", "k3", "resurrect")
            a.doc.delete("m", "k5")

        _differential(script)

    def test_concurrent_seq_ops(self):
        def script(peers):
            a, b, c = peers
            a.doc.push("l", ["a1", "a2"])
            b.doc.push("l", ["b1"])
            _drain(peers)
            a.doc.insert("l", 1, "mid")
            b.doc.unshift("l", "front")
            c.doc.cut("l", 0, 1)

        _differential(script)

    def test_nested_array_in_map(self):
        def script(peers):
            a, b, c = peers
            a.doc.set("cfg", "tags", None, array_method="push")
            _drain(peers)
            b.doc.set("cfg", "tags", ["x", "y"], array_method="push")
            c.doc.set("cfg", "tags", "z", array_method="unshift")
            a.doc.set("cfg", "mode", "dark")

        _differential(script)

    def test_batch_then_remote(self):
        def script(peers):
            a, b, _ = peers
            a.doc.set("m", "k1", 1, batch=True)
            a.doc.push("l", ["x"], batch=True)
            a.doc.set("m", "k2", 2, batch=True)
            a.doc.exec_batch()
            b.doc.set("m", "k1", "b-wins-or-loses")

        _differential(script)

    def test_fuzz_random_ops(self):
        def script(peers):
            # seeded inside the script: both mode runs must draw the
            # exact same op sequence
            rng = np.random.default_rng(7)
            for step in range(60):
                p = peers[rng.integers(len(peers))]
                op = rng.integers(5)
                if op == 0:
                    p.doc.set("m", f"k{rng.integers(6)}", int(step))
                elif op == 1:
                    p.doc.delete("m", f"k{rng.integers(6)}")
                elif op == 2:
                    p.doc.push("l", int(step))
                elif op == 3:
                    if len(p.doc.c.get("l", [])) > 1:
                        p.doc.cut("l", int(rng.integers(len(p.doc.c["l"]))))
                else:
                    if rng.integers(2):
                        _drain(peers)

        _differential(script)


class TestDeviceModePlumbing:
    def test_env_flag_does_not_touch_standalone_crdt(self, monkeypatch):
        """CRDT_TPU_DEVICE is the replica layer's knob (it selects
        merge_mode="resident" there); the standalone Crdt's engine
        device gate is strictly explicit."""
        monkeypatch.setenv("CRDT_TPU_DEVICE", "1")
        assert not Crdt(1).device_merge
        assert Crdt(1, device_merge=True).device_merge
        monkeypatch.delenv("CRDT_TPU_DEVICE")
        assert not Crdt(1).device_merge

    def test_apply_updates_batches_one_txn(self):
        """A backlog of K updates = one merge + one observer flush."""
        src = _Peer(1, False)
        for i in range(5):
            src.doc.set("m", f"k{i}", i)
        events = []
        dst = Crdt(2, observer_function=events.append, device_merge=True)
        dst.apply_updates(src.outbox)
        assert dict(dst.c)["m"] == dict(src.doc.c)["m"]
        assert len(events) == 1  # one flush for the whole backlog

    def test_pending_stash_device_mode(self):
        """Out-of-order delivery waits in pending, exactly like scalar."""
        src = _Peer(1, False)
        src.doc.set("m", "a", 1)
        src.doc.set("m", "b", 2)
        u1, u2 = src.outbox
        dst = Crdt(2, device_merge=True)
        dst.apply_update(u2)  # clock gap: must stash
        assert dst.engine.pending
        assert "m" not in dst.c or "b" not in dst.c.get("m", {})
        dst.apply_update(u1)  # gap filled: both integrate
        assert not dst.engine.pending
        assert dict(dst.c)["m"] == {"a": 1, "b": 2}

    def test_local_ops_after_device_rebuild(self):
        """Local mutations keep working on the rebuilt chain state."""
        a, b = _Peer(1, True), _Peer(2, True)
        a.doc.push("l", ["x", "y"])
        a.doc.set("m", "k", "v1")
        for u in a.outbox:
            b.doc.apply_update(u)
        # b mutates on top of device-rebuilt chains
        b.doc.insert("l", 1, "mid")
        b.doc.set("m", "k", "v2")
        b.doc.cut("l", 0)
        for u in b.outbox:
            a.doc.apply_update(u)
        assert dict(a.doc.c) == dict(b.doc.c)
        assert a.doc.c["l"] == ["mid", "y"]
        assert a.doc.c["m"] == {"k": "v2"}

    def test_large_random_client_ids(self):
        """Real replicas use random 31-bit client ids, which overflow
        the kernels' packed (client << 40) int64 ids; the rebuild's
        dense remap must keep outcomes identical to scalar."""
        ids = [2**31 - 7, 2**30 + 12345, 3]

        def script(peers):
            a, b, c = peers
            a.doc.set("m", "k", "a")
            b.doc.set("m", "k", "b")
            c.doc.set("m", "k", "c")
            a.doc.push("l", ["x"])
            b.doc.push("l", ["y"])

        def run(device):
            peers = [_Peer(cid, device) for cid in ids]
            script(peers)
            _drain(peers)
            return peers

        scalar, device = run(False), run(True)
        for s, d in zip(scalar, device):
            _assert_same_state(s.doc, d.doc)

    def test_duplicate_siblings_rebuild_on_device(self, monkeypatch):
        """rebuild_chains' own (client, ~clock) key (separate code from
        order_sequences) must order attachment-free same-client
        duplicate groups without the host scan."""
        import crdt_tpu.ops.yata as yata
        from crdt_tpu.codec import v1
        from crdt_tpu.core.records import ItemRecord

        recs = [
            ItemRecord(client=1, clock=0, parent_root="s", content="b0"),
            ItemRecord(client=1, clock=1, parent_root="s", origin=(1, 0),
                       content="b1"),
        ]
        for k in range(3):
            recs.append(ItemRecord(client=2, clock=k, parent_root="s",
                                   origin=(1, 0), content=f"dup{k}"))
        blob = v1.encode_update(recs, None)

        scalar = Crdt(999, device_merge=False)
        scalar.apply_update(blob)

        def boom(*a, **k):
            raise AssertionError("host scan ran in rebuild_chains")

        monkeypatch.setattr(yata, "_simulate_group", boom)
        device = Crdt(999, device_merge=True)
        device.apply_update(blob)
        _assert_same_state(scalar, device)
        assert device.c["s"] == ["b0", "b1", "dup2", "dup1", "dup0"]

    def test_hostile_rights_stay_identical_across_modes(self):
        """Crafted updates with rights pointing inside a sibling's
        subtree (or dangling) pass admission but defeat the sibling
        rank model; the hard-segment scalar fallback keeps device mode
        byte-identical to scalar mode."""
        from crdt_tpu.codec import v1
        from crdt_tpu.core.records import ItemRecord

        recs = [
            ItemRecord(client=1, clock=0, parent_root="s", content="a"),
            ItemRecord(client=1, clock=1, parent_root="s", origin=(1, 0),
                       content="b"),
            ItemRecord(client=1, clock=2, parent_root="s", origin=(1, 1),
                       content="c"),
        ]
        # right = grandchild of the sibling (1,1): splits its subtree
        hostile = ItemRecord(client=4, clock=0, parent_root="s",
                             origin=(1, 0), right=(1, 2), content="H")
        blob = v1.encode_update(recs + [hostile], None)
        scalar = Crdt(999, device_merge=False)
        device = Crdt(999, device_merge=True)
        scalar.apply_update(blob)
        device.apply_update(blob)
        _assert_same_state(scalar, device)
        assert scalar.c["s"] == ["a", "b", "H", "c"]

    def test_hostile_right_with_interleaved_clocks(self):
        """The hard-segment fallback must not re-apply admission gates:
        a client whose sequence clocks interleave with map clocks (gaps
        WITHIN the slice) keeps every live item."""
        from crdt_tpu.codec import v1
        from crdt_tpu.core.records import ItemRecord

        recs = [
            ItemRecord(client=1, clock=0, parent_root="s", content="a"),
            ItemRecord(client=1, clock=1, parent_root="m", key="k",
                       content="map-gap"),
            ItemRecord(client=1, clock=2, parent_root="s", origin=(1, 0),
                       content="b"),
            ItemRecord(client=1, clock=3, parent_root="s", origin=(1, 2),
                       content="c"),
            ItemRecord(client=4, clock=0, parent_root="s", origin=(1, 0),
                       right=(1, 3), content="H"),
        ]
        blob = v1.encode_update(recs, None)
        scalar = Crdt(999, device_merge=False)
        device = Crdt(999, device_merge=True)
        scalar.apply_update(blob)
        device.apply_update(blob)
        _assert_same_state(scalar, device)
        assert scalar.c["s"] == ["a", "b", "H", "c"]

    def test_hostile_right_deep_in_subtree(self):
        """Subtree depth exceeds group size: the hard-shape walk must
        bound by universe size, not sibling count."""
        from crdt_tpu.codec import v1
        from crdt_tpu.core.records import ItemRecord

        recs = [ItemRecord(client=1, clock=0, parent_root="s", content="root")]
        # a1: client 2, child of root; then an 8-deep chain under a1
        recs.append(ItemRecord(client=2, clock=0, parent_root="s",
                               origin=(1, 0), content="a1"))
        for k in range(1, 9):
            recs.append(ItemRecord(client=2, clock=k, parent_root="s",
                                   origin=(2, k - 1), content=f"d{k}"))
        hostile = ItemRecord(client=5, clock=0, parent_root="s",
                             origin=(1, 0), right=(2, 8), content="H")
        blob = v1.encode_update(recs + [hostile], None)
        scalar = Crdt(999, device_merge=False)
        device = Crdt(999, device_merge=True)
        scalar.apply_update(blob)
        device.apply_update(blob)
        _assert_same_state(scalar, device)

    def test_cross_parent_right_integrates_in_both_modes(self):
        """A right origin living in ANOTHER collection exists in the
        store, so the member must integrate (scan-to-end), not pend."""
        from crdt_tpu.codec import v1
        from crdt_tpu.core.records import ItemRecord

        recs = [
            ItemRecord(client=1, clock=0, parent_root="other", content="x"),
            ItemRecord(client=1, clock=1, parent_root="s", content="a"),
            ItemRecord(client=3, clock=0, parent_root="s", origin=(1, 1),
                       right=(1, 0), content="weird"),
        ]
        blob = v1.encode_update(recs, None)
        scalar = Crdt(999, device_merge=False)
        device = Crdt(999, device_merge=True)
        scalar.apply_update(blob)
        device.apply_update(blob)
        _assert_same_state(scalar, device)
        assert "weird" in scalar.c["s"]

    def test_hostile_rights_on_map_rows(self):
        """Crafted rights on MAP entries shift the chain tail; both
        modes must agree on the winner (the kernel path falls back to
        the exact scalar tail for those chains)."""
        from crdt_tpu.codec import v1
        from crdt_tpu.core.records import ItemRecord

        recs = [
            ItemRecord(client=1, clock=0, parent_root="m", key="k",
                       content="A"),
            # hostile: right = A makes the scan stop at the head, so B
            # lands BEFORE A and is tombstoned despite the larger client
            ItemRecord(client=2, clock=0, parent_root="m", key="k",
                       right=(1, 0), content="B"),
            ItemRecord(client=1, clock=1, parent_root="m", key="other",
                       content="clean"),
        ]
        blob = v1.encode_update(recs, None)
        scalar = Crdt(999, device_merge=False)
        device = Crdt(999, device_merge=True)
        scalar.apply_update(blob)
        device.apply_update(blob)
        _assert_same_state(scalar, device)
        assert scalar.c["m"]["k"] == "A"


class TestCompilationCacheHook:
    """The local-CPU escape hatch suppresses the persistent compile
    cache through jax's PRIVATE reset hook. If a jax upgrade removes
    it, suppression silently no-ops and the SIGILL hazard (XLA:CPU AOT
    artifacts persisted from an accelerator-backed process) returns —
    so the hook's presence is pinned here, and its absence must warn
    loudly instead of degrading in silence (ADVICE r5)."""

    def test_reset_hook_present(self):
        """Fails loudly when a jax upgrade removes the private hook
        crdt_tpu.ops.device._cache_singleton_reset depends on."""
        from jax._src import compilation_cache as cc

        assert callable(getattr(cc, "reset_cache", None)), (
            "jax._src.compilation_cache.reset_cache is gone: update "
            "crdt_tpu.ops.device's cache suppression for this jax "
            "version (silent no-op = SIGILL hazard)"
        )

    def test_missing_hook_warns_once_and_reports_failure(self, monkeypatch):
        """With the hook absent, _cache_singleton_reset must return
        False (callers then skip suppression) and emit its one-time
        RuntimeWarning instead of pretending the reset happened."""
        import warnings

        from jax._src import compilation_cache as cc

        from crdt_tpu.ops import device

        monkeypatch.delattr(cc, "reset_cache")
        monkeypatch.setattr(device, "_RESET_HOOK_WARNED", False)
        with pytest.warns(RuntimeWarning, match="reset_cache"):
            assert device._cache_singleton_reset(None) is False
        # second call: degraded mode already announced, no new warning
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert device._cache_singleton_reset(None) is False
