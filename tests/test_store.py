"""ItemStore structural tests."""

import numpy as np
import pytest

from crdt_tpu.core.ids import DeleteSet, StateVector
from crdt_tpu.core.store import K_ANY, K_DELETED, ItemStore


def test_interning():
    s = ItemStore()
    a = s.intern_root("users")
    b = s.intern_root("posts")
    assert a != b
    assert s.intern_root("users") == a
    k = s.intern_key("name")
    assert s.intern_key("name") == k
    assert s.root_names[a] == "users"
    assert s.keys[k] == "name"


def test_add_and_find():
    s = ItemStore(capacity=2)
    rid = s.intern_root("m")
    kid = s.intern_key("k")
    rows = []
    for i in range(100):  # force several growths
        rows.append(
            s.add_item(1, i, parent_root=rid, key_id=kid, kind=K_ANY, content=i)
        )
    assert len(s) == 100
    for i, row in enumerate(rows):
        assert s.find(1, i) == row
        assert s.content[row] == i
    assert s.find(1, 100) is None
    assert s.id_of(rows[5]) == (1, 5)


def test_duplicate_id_rejected():
    s = ItemStore()
    s.add_item(1, 0)
    with pytest.raises(ValueError):
        s.add_item(1, 0)


def test_state_vector():
    s = ItemStore()
    s.add_item(1, 0)
    s.add_item(1, 1)
    s.add_item(2, 5)  # gap: clocks 0-4 of client 2 never seen
    sv = s.state_vector()
    assert sv.get(1) == 2  # next clock
    assert sv.get(2) == 0  # non-contiguous clocks are not claimed
    assert sv.get(3) == 0
    # filling the gap makes the prefix visible
    for k in range(5):
        s.add_item(2, k)
    assert s.state_vector().get(2) == 6


def test_delete_set():
    s = ItemStore()
    s.add_item(1, 0)
    s.add_item(1, 1)
    s.add_item(1, 2, kind=K_DELETED)
    s.mark_deleted(s.find(1, 0))
    ds = s.delete_set()
    assert ds.contains(1, 0)
    assert not ds.contains(1, 1)
    assert ds.contains(1, 2)
    # ranges merged? mark 1 too -> one [0,3) range
    s.mark_deleted(s.find(1, 1))
    ds = s.delete_set()
    assert ds.ranges[1] == [(0, 3)]


def test_columns_dense():
    s = ItemStore()
    for i in range(10):
        s.add_item(3, i, content=None)
    cols = s.columns()
    assert all(len(v) == 10 for v in cols.values())
    assert np.array_equal(cols["clock"], np.arange(10))


def test_statevector_semantics():
    sv = StateVector()
    sv.observe(1, 0)
    assert sv.get(1) == 1
    assert sv.covers(1, 0)
    assert not sv.covers(1, 1)
    sv2 = StateVector({1: 5, 2: 3})
    merged = sv.merge(sv2)
    assert merged.get(1) == 5 and merged.get(2) == 3
    assert sv2.diff_dominates(sv)
    assert not sv.diff_dominates(sv2)
    assert StateVector({1: 0}) == StateVector({})


def test_deleteset_ops():
    ds = DeleteSet()
    ds.add(1, 5, 3)
    ds.add(1, 7, 2)  # overlaps -> [5,9)
    ds.add(1, 20)
    ds.normalize()
    assert ds.ranges[1] == [(5, 9), (20, 21)]
    assert ds.contains(1, 8)
    assert not ds.contains(1, 9)
    other = DeleteSet()
    other.add(1, 9)
    other.add(2, 0)
    merged = ds.merge(other)
    assert merged.ranges[1] == [(5, 10), (20, 21)]
    assert merged.contains(2, 0)
    assert list(other.iter_all()) == [(1, 9, 1), (2, 0, 1)]


def test_deleteset_lazy_normalize():
    ds = DeleteSet()
    ds.add(1, 3)
    ds.add(1, 1)
    ds.add(1, 9)
    ds.add(1, 5)
    # queries between add() and normalize() must still be correct
    assert ds.contains(1, 5)
    assert ds.contains(1, 1)
    assert not ds.contains(1, 2)


def test_bigint_out_of_range():
    from crdt_tpu.codec.lib0 import Encoder

    with pytest.raises(TypeError):
        Encoder().write_any(2**63)
    e = Encoder()
    e.write_any(2**62)  # in-range bigint fine
