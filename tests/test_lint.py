"""Tier-1 static-analysis gate: crdtlint over the package + ruff.

Three jobs:

1. **The gate itself** — ``python -m tools.crdtlint crdt_tpu/`` must
   exit 0 on the committed tree (baselined/suppressed findings
   allowed, open findings fail), and fast (<10 s: it runs on every
   tier-1 invocation forever).
2. **Anti-rot** — every registered checker code still FIRES on a
   violating snippet. Without this, a refactor that breaks a checker
   reads as "the tree got cleaner" and the contract silently dies.
3. **Pinned regressions for the drift crdtlint surfaced on its first
   run** — the registry names that were emitted-but-undocumented, the
   computed fault-event names, and the unlocked device-hook mutations
   each stay fixed.

Plus the ruff satellite behind a skip-if-unavailable guard (the
container may not ship ruff; when it does, `ruff check .` must be
clean — config in pyproject [tool.ruff]).
"""

import os
import re
import shutil
from collections import Counter
import subprocess
import sys
import textwrap
import threading
import time
import warnings

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.crdtlint.checkers import ALL_CHECKERS, ALL_CODES  # noqa: E402
from tools.crdtlint.core import LintConfig, run_lint  # noqa: E402
from tools.crdtlint.registry import Registry, load_registry  # noqa: E402


# ---------------------------------------------------------------------------
# 1. the gate


def test_package_lints_clean_via_cli():
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", "crdt_tpu/"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    dt = time.perf_counter() - t0
    assert proc.returncode == 0, (
        "crdtlint found unsuppressed violations:\n"
        + proc.stdout + proc.stderr
    )
    # no stale baseline entries either: a fixed finding must drop its
    # baseline row in the same PR, or the ledger rots into fiction
    assert "stale baseline" not in proc.stderr, proc.stderr
    assert dt < 10.0, f"crdtlint took {dt:.1f}s (must stay under ~10s)"


def test_checker_suite_is_complete():
    """≥11 checkers (round 17 added the CL10xx wire-taint and CL11xx
    decode-allocation families) and every advertised code belongs to
    exactly one, with an --explain text."""
    from tools.crdtlint.checkers import ALL_EXPLAIN

    assert len(ALL_CHECKERS) >= 11
    seen = {}
    for cls in ALL_CHECKERS:
        for code in cls.codes:
            assert code not in seen, f"{code} registered twice"
            seen[code] = cls.name
    assert len(seen) >= 26
    for code in seen:
        assert ALL_EXPLAIN.get(code), f"{code} has no --explain text"
    # the round-17 codes are present and --explain is substantive
    # (a rationale + fix recipe, not the one-line invariant)
    for code in ("CL1001", "CL1002", "CL1003", "CL1004",
                 "CL1101", "CL1102"):
        assert code in seen, code
        assert len(ALL_EXPLAIN[code]) > len(ALL_CODES[code]), (
            f"{code} --explain text is just the invariant line"
        )


def test_cli_runs_without_importing_jax():
    """The analysis layer is stdlib-only BY CONTRACT: the whole-tree
    pass (call graph included) must never import jax — that is what
    keeps it runnable in any environment and inside the <10 s
    budget."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, '.');"
         "from tools.crdtlint.__main__ import main;"
         "rc = main(['crdt_tpu/']);"
         "assert 'jax' not in sys.modules, 'crdtlint imported jax';"
         "sys.exit(rc)"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# 2. anti-rot: every code fires on its violating snippet


def _lint_snippet(path, src, registry=None):
    config = LintConfig(
        repo_root="/synthetic", readme_path="", smoke_test_path="",
        baseline_path="/synthetic/absent.json",
    )
    return run_lint(
        [(path, textwrap.dedent(src))], config=config, baseline={},
        shared={
            "metric_registry":
                registry if registry is not None else Registry()
        },
    )


def _reg(*names):
    r = Registry()
    for n in names:
        r.add(n, "metric", "README.md", 1)
    return r


_DONATE = '''
from functools import partial
import jax

@partial(jax.jit, donate_argnums=(0,))
def _converge_x(mat):
    return mat
'''

# code -> (path, violating snippet, registry or None)
STILL_FIRES = {
    "CL000": ("crdt_tpu/ops/x.py", "def broken(:\n", None),
    "CL101": ("crdt_tpu/ops/x.py", _DONATE + '''
def caller(mat):
    out = _converge_x(mat)
    return mat.sum()
''', None),
    "CL102": ("crdt_tpu/ops/x.py", _DONATE, None),
    "CL201": ("crdt_tpu/core/x.py", '''
def f(tracer):
    tracer.count("engine.not_in_registry", 1)
''', None),
    "CL202": ("crdt_tpu/core/x.py", '''
def f(tracer):
    tracer.count("engine.real", 1)
''', _reg("engine.real", "engine.dead_entry")),
    "CL203": ("crdt_tpu/core/x.py", '''
def f(tracer, name):
    tracer.count(name, 1)
''', None),
    "CL301": ("crdt_tpu/codec/x.py", '''
def decode_x(b):
    try:
        return b[0]
    except:
        return None
''', None),
    "CL302": ("crdt_tpu/codec/x.py", '''
def decode_x(b):
    raise KeyError("boom")
''', None),
    "CL303": ("crdt_tpu/guard/x.py", '''
def ladder(fn):
    try:
        return fn()
    except SimulatedCrash:
        return None
''', None),
    "CL401": ("crdt_tpu/models/x.py", '''
import jax

def upload(arr):
    return jax.device_put(arr)
''', None),
    "CL501": ("crdt_tpu/ops/x.py", '''
import time

def stamp():
    return time.time()
''', None),
    "CL502": ("crdt_tpu/parallel/x.py", '''
import random

def jitter():
    return random.random()
''', None),
    "CL503": ("crdt_tpu/parallel/x.py", None, None),  # two-file case
    "CL504": ("crdt_tpu/core/x.py", '''
def pack(items):
    return [k for k in set(items)]
''', None),
    "CL601": ("crdt_tpu/obs/tracer.py", '''
_state = dict()

def put(k, v):
    _state[k] = v
''', None),
    "CL701": ("crdt_tpu/ops/x.py", '''
import jax
from crdt_tpu.obs.tracer import get_tracer

@jax.jit
def step(x):
    get_tracer().count("engine.calls", 1)
    return x
''', None),
    "CL702": ("crdt_tpu/ops/x.py", '''
import os
import jax

@jax.jit
def step(x):
    if os.environ.get("CRDT_TPU_FLAG"):
        return x
    return x + 1
''', None),
    "CL703": ("crdt_tpu/ops/x.py", '''
import jax

@jax.jit
def step(x):
    jax.block_until_ready(x)
    return x
''', None),
    "CL704": ("crdt_tpu/ops/x.py", '''
import jax

_CACHE = {}

@jax.jit
def step(x):
    _CACHE["last"] = x
    return x
''', None),
    "CL801": ("crdt_tpu/ops/x.py", '''
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()

def ab():
    with LOCK_A:
        with LOCK_B:
            pass

def ba():
    with LOCK_B:
        with LOCK_A:
            pass
''', None),
    "CL802": ("crdt_tpu/ops/x.py", '''
import subprocess
import threading

_BUILD_LOCK = threading.Lock()

def build():
    with _BUILD_LOCK:
        subprocess.run(["make"])
''', None),
    "CL803": ("crdt_tpu/models/x.py", '''
import threading

class SharedState:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def locked_bump(self):
        with self._lock:
            self.n += 1

    def bare_reset(self):
        self.n = 0

def worker():
    SharedState().locked_bump()

def spawn():
    return threading.Thread(target=worker)
''', None),
    "CL901": ("crdt_tpu/models/x.py", '''
from crdt_tpu.ops import packed

def leak(plan):
    h = packed.converge_async(plan)
    return 0
''', None),
    "CL902": ("crdt_tpu/obs/x.py", '''
import jax

def capture(log_dir, work):
    jax.profiler.start_trace(log_dir)
    work()
    jax.profiler.stop_trace()
''', None),
    "CL1001": ("crdt_tpu/codec/x.py", '''
def decode_x(d):
    n = d.read_var_uint()
    return d.data[n]
''', None),
    "CL1002": ("crdt_tpu/codec/x.py", '''
def decode_x(d):
    n = d.read_var_uint()
    return bytearray(n)
''', None),
    "CL1003": ("crdt_tpu/codec/x.py", '''
def decode_x(d):
    n = d.read_var_uint()
    out = []
    for _ in range(n):
        out.append(1)
    return out
''', None),
    "CL1004": ("crdt_tpu/codec/x.py", '''
def decode_x(d, cols):
    n = d.read_var_uint()
    return stage(cols, rows=n)
''', None),
    "CL1101": ("crdt_tpu/codec/x.py", '''
def decode_x(d):
    n = d.read_var_uint()
    if n > (1 << 31):
        raise ValueError("too big")
    return bytearray(n)
''', None),
    "CL1102": ("crdt_tpu/codec/x.py", '''
def _helper(b):
    raise KeyError("boom")

def decode_x(b):
    return _helper(b)
''', None),
}


@pytest.mark.parametrize("code", sorted(ALL_CODES) + ["CL000"])
def test_checker_still_fires(code):
    assert code in STILL_FIRES, (
        f"checker code {code} has no still-fires snippet — add one "
        f"(tools/crdtlint/checkers/__init__.py documents the rule)"
    )
    path, src, registry = STILL_FIRES[code]
    if code == "CL503":
        config = LintConfig(
            repo_root="/synthetic", readme_path="",
            smoke_test_path="",
            baseline_path="/synthetic/absent.json",
        )
        result = run_lint(
            [
                ("crdt_tpu/net/faults.py", textwrap.dedent('''
                class FaultSchedule:
                    def __init__(self, seed: int = 0, *, drop=0.0):
                        self.seed = seed
                ''')),
                ("crdt_tpu/parallel/x.py", textwrap.dedent('''
                from crdt_tpu.net.faults import FaultSchedule

                def chaos():
                    return FaultSchedule(drop=0.5)
                ''')),
            ],
            config=config, baseline={},
            shared={"metric_registry": Registry()},
        )
    else:
        result = _lint_snippet(path, src, registry)
    assert any(f.code == code for f in result.findings), (
        f"{code} no longer fires on its violating snippet — the "
        f"checker rotted into a no-op"
    )


# ---------------------------------------------------------------------------
# round-17 satellites: SARIF export, per-checker timing, prune-stale


def test_cli_sarif_output(tmp_path):
    """--sarif writes a SARIF 2.1.0 log (one rule per registered
    code, --explain text as help, baselined findings carried as
    suppressions) WITHOUT changing exit-code semantics."""
    import json

    sarif_path = tmp_path / "out.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", "crdt_tpu/",
         "--sarif", str(sarif_path)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    log = json.loads(sarif_path.read_text())
    assert log["version"] == "2.1.0"
    run = log["runs"][0]
    assert run["tool"]["driver"]["name"] == "crdtlint"
    # informationUri must be a valid absolute URI or absent (SARIF
    # 2.1.0 `format: uri`); a repo-relative hint gets the whole log
    # rejected by upload-sarif, silently killing the annotation lane
    info = run["tool"]["driver"].get("informationUri")
    assert info is None or re.match(r"^[a-z][a-z0-9+.-]*://", info)
    rules = {r["id"]: r for r in run["tool"]["driver"]["rules"]}
    assert set(rules) == set(ALL_CODES)
    from tools.crdtlint.checkers import ALL_EXPLAIN

    for code in ("CL1001", "CL1102"):
        assert rules[code]["help"]["text"] == ALL_EXPLAIN[code]
    # the committed tree is clean: every result is a suppressed
    # (baselined) finding with its ledger justification attached
    results = run["results"]
    assert results, "expected the baselined findings as results"
    for r in results:
        assert r["level"] == "note"
        supp = r["suppressions"][0]
        assert supp["kind"] == "external"
        assert supp["justification"].strip()
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].startswith("crdt_tpu/")
        assert loc["region"]["startLine"] >= 1


def test_cli_sarif_open_findings_are_errors(tmp_path):
    """An open finding lands as an error-level SARIF result and the
    exit code still fails the run."""
    import json

    bad = tmp_path / "crdt_tpu" / "codec"
    bad.mkdir(parents=True)
    (bad / "x.py").write_text(
        "def decode_x(d):\n"
        "    n = d.read_var_uint()\n"
        "    return d.data[n]\n"
    )
    sarif_path = tmp_path / "out.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint",
         str(bad / "x.py"), "--sarif", str(sarif_path)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    log = json.loads(sarif_path.read_text())
    errs = [r for r in log["runs"][0]["results"]
            if r["level"] == "error"]
    assert any(r["ruleId"] == "CL1001" for r in errs)


def test_cli_statistics_reports_per_checker_time():
    """The round-17 --statistics surface itemizes the <10 s budget:
    one wall-time line per checker, the two new families included."""
    # a subtree is enough: every checker's wall time is recorded
    # whether or not its scope matched, and this keeps the tier-1
    # wall cost of the assertion small
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", "crdt_tpu/codec/",
         "--statistics"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    timed = {
        line.split()[1].rstrip(":")
        for line in proc.stdout.splitlines()
        if line.startswith("time ")
    }
    for name in ("wire-taint", "decode-alloc", "donate",
                 "trace-purity"):
        assert name in timed, (name, sorted(timed))


def test_cli_prune_stale_drops_dead_entries_only(tmp_path):
    """--prune-stale rewrites the ledger in place: entries with no
    live finding drop, surviving justifications stay verbatim, and a
    ledger with nothing stale is left byte-identical."""
    import json

    committed = os.path.join(REPO, "tools", "crdtlint",
                             "baseline.json")
    bl_path = tmp_path / "baseline.json"
    data = json.loads(open(committed).read())
    data["entries"].append({
        "code": "CL401",
        "fingerprint": "crdt_tpu/ops/removed.py|CL401|ghost",
        "justification": "row for a file deleted rounds ago",
        "path": "crdt_tpu/ops/removed.py",
    })
    bl_path.write_text(json.dumps(data))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", "crdt_tpu/",
         "--baseline", str(bl_path), "--prune-stale"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pruned 1 stale" in proc.stderr
    after = json.loads(bl_path.read_text())
    fps = {e["fingerprint"] for e in after["entries"]}
    assert "crdt_tpu/ops/removed.py|CL401|ghost" not in fps
    # every surviving entry kept its hand-written justification
    before_by_fp = {
        e["fingerprint"]: e["justification"] for e in data["entries"]
    }
    for e in after["entries"]:
        assert e["justification"] == before_by_fp[e["fingerprint"]]
    # idempotent: nothing stale now, ledger untouched
    unchanged = bl_path.read_text()
    proc2 = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", "crdt_tpu/",
         "--baseline", str(bl_path), "--prune-stale"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc2.returncode == 0
    assert "pruned" not in proc2.stderr
    assert bl_path.read_text() == unchanged


def test_committed_baseline_has_no_stale_entries_audit():
    """Round-17 baseline audit: the committed ledger carries ONLY
    live fingerprints (the CLI gate already asserts no stale
    warnings; this pins the audited count so silent ledger growth is
    a visible diff)."""
    import json

    committed = os.path.join(REPO, "tools", "crdtlint",
                             "baseline.json")
    data = json.loads(open(committed).read())
    by_code = Counter(e["code"] for e in data["entries"])
    # the audited composition: 9 donation twins, 14 seam waits, 2
    # singleton setters, 3 native-build locks, 2 round-17
    # environment-error raises
    assert by_code == Counter({
        "CL102": 9, "CL401": 14, "CL601": 2, "CL802": 3,
        "CL1102": 2,
    }), by_code
    for e in data["entries"]:
        assert e["justification"].strip()
        assert "TODO" not in e["justification"]


# ---------------------------------------------------------------------------
# 3. pinned regressions from crdtlint's first run over the tree


def _real_registry():
    return load_registry(
        os.path.join(REPO, "README.md"),
        os.path.join(REPO, "tests", "test_bench_smoke.py"),
    )


def test_registry_drift_fixed_fleet_and_engine_names():
    """First-run CL201 drift: these names were emitted by the code
    but missing from the README registry tables. They must stay
    documented."""
    reg = _real_registry()
    for name in (
        "fleet.step", "fleet.seg_step", "fleet.ops_converged",
        "engine.pending_delete_ranges",
        "persist.overflow_bytes", "persist.log_size_bytes",
        "replica.anti_entropy_bytes",
        "replica.propagation_lag_s", "replica.convergence_lag_s",
        "router.relay_send_bytes", "router.relay_bytes_forwarded",
        "router.relay_activations",
    ):
        assert name in reg.metrics, (
            f"{name} dropped out of the README registry tables "
            f"(round-8 drift fixed by crdtlint PR must stay fixed)"
        )


def test_registry_covers_converge_kernel_counters():
    """Round 12 (the sort diet) added the `converge.*` namespace for
    the Pallas kernel-dispatch evidence. Both directions must hold:
    the emitted names stay documented, and an UNdocumented converge
    name still fires CL201 — i.e. the namespace genuinely joined the
    registry-checked pool rather than an allowlist."""
    reg = _real_registry()
    for name in ("converge.pallas", "converge.pallas_fallback",
                 "converge.dispatch", "converge.fetch"):
        assert name in reg.metrics, (
            f"{name} dropped out of the README registry (round-12 "
            f"sort-diet contract)"
        )
    result = _lint_snippet("crdt_tpu/ops/x.py", '''
def f(tracer):
    tracer.count("converge.bogus_kernel", 1)
''', _reg("converge.pallas"))
    assert any(f.code == "CL201" for f in result.findings), (
        "an undocumented converge.* metric no longer fires CL201"
    )


def test_registry_covers_shard_counters():
    """Round 13 (multi-chip sharding) added the `shard.*` namespace
    and the chain-split staging gauges. Both directions must hold:
    the emitted names stay documented in the README registry, and an
    UNdocumented shard name still fires CL201."""
    reg = _real_registry()
    for name in ("shard.dispatches", "shard.boundary_bytes",
                 "shard.seam_rows", "shard.shards",
                 "converge.wyllie_rounds", "converge.chain_seams"):
        assert name in reg.metrics, (
            f"{name} dropped out of the README registry (round-13 "
            f"multi-chip contract)"
        )
    result = _lint_snippet("crdt_tpu/ops/x.py", '''
def f(tracer):
    tracer.count("shard.bogus_exchange", 1)
''', _reg("shard.dispatches"))
    assert any(f.code == "CL201" for f in result.findings), (
        "an undocumented shard.* metric no longer fires CL201"
    )


def test_registry_covers_tenant_counters():
    """Round 14 (tenant packing) added the `tenant.*` namespace, the
    `converge.docs_packed` staging counter, and the multi-doc
    sentinel rows. Both directions must hold: the emitted names stay
    documented in the README registry, and an UNdocumented tenant
    name still fires CL201 — the namespace genuinely joined the
    registry-checked pool."""
    reg = _real_registry()
    for name in ("converge.docs_packed", "tenant.submitted",
                 "tenant.docs_converged", "tenant.shed",
                 "tenant.shed_bytes", "tenant.fallback_docs",
                 "tenant.pending_bytes", "tenant.dispatch_docs",
                 "sentinel.doc_divergence", "sentinel.doc_lag"):
        assert name in reg.metrics, (
            f"{name} dropped out of the README registry (round-14 "
            f"tenant-packing contract)"
        )
    result = _lint_snippet("crdt_tpu/models/x.py", '''
def f(tracer):
    tracer.count("tenant.bogus_budget", 1)
''', _reg("tenant.submitted"))
    assert any(f.code == "CL201" for f in result.findings), (
        "an undocumented tenant.* metric no longer fires CL201"
    )


def test_registry_covers_delta_tick_counters():
    """Round 15 (delta ticks) added the resident-state ledger rows
    and the sentinel digest-cache row. Both directions must hold:
    the emitted names stay documented in the README registry, and an
    UNdocumented tenant/sentinel name still fires CL201 — the new
    rows genuinely joined the registry-checked pool."""
    reg = _real_registry()
    for name in ("tenant.delta_docs", "tenant.delta_rows",
                 "tenant.promotions", "tenant.delta_fallbacks",
                 "tenant.resident_evictions",
                 "tenant.resident_bytes", "tenant.resident_docs",
                 "sentinel.doc_digest_skips"):
        assert name in reg.metrics, (
            f"{name} dropped out of the README registry (round-15 "
            f"delta-tick contract)"
        )
    result = _lint_snippet("crdt_tpu/models/x.py", '''
def f(tracer):
    tracer.count("sentinel.bogus_digest_row", 1)
''', _reg("sentinel.doc_digest_skips"))
    assert any(f.code == "CL201" for f in result.findings), (
        "an undocumented sentinel.* metric no longer fires CL201"
    )


def test_registry_covers_pooled_resident_counters():
    """Round 20 (pooled resident matrix) added the pool's dispatch /
    compaction counters and allocation gauges. Both directions must
    hold: the emitted names stay documented in the README registry
    (never bare baseline entries), and an UNdocumented pool name
    still fires CL201 — the rows genuinely joined the
    registry-checked pool."""
    reg = _real_registry()
    for name in ("tenant.pool_dispatches", "tenant.pool_compactions",
                 "tenant.pool_bytes", "tenant.pool_docs"):
        assert name in reg.metrics, (
            f"{name} dropped out of the README registry (round-20 "
            f"pooled-resident contract)"
        )
    result = _lint_snippet("crdt_tpu/ops/x.py", '''
def f(tracer):
    tracer.count("tenant.pool_bogus_extent", 1)
''', _reg("tenant.pool_dispatches"))
    assert any(f.code == "CL201" for f in result.findings), (
        "an undocumented tenant.pool_* metric no longer fires CL201"
    )


def test_registry_covers_snapshot_counters():
    """Round 21 (crash-proof recovery) added the snapshot store's
    write/load/fallback plane. Both directions must hold: the emitted
    names stay documented in the README registry, and an undocumented
    ``snap.*`` name still fires CL201 — the new namespace genuinely
    joined the registry-checked pool."""
    reg = _real_registry()
    for name in ("snap.writes", "snap.loads", "snap.bytes",
                 "snap.fallbacks", "snap.write_errors",
                 "snap.evict_writes", "snap.write_ms", "snap.load_ms",
                 "tenant.checkpoint_docs"):
        assert name in reg.metrics, (
            f"{name} dropped out of the README registry (round-21 "
            f"snapshot contract)"
        )
    result = _lint_snippet("crdt_tpu/ops/x.py", '''
def f(tracer):
    tracer.count("snap.bogus_extent", 1)
''', _reg("snap.writes"))
    assert any(f.code == "CL201" for f in result.findings), (
        "an undocumented snap.* metric no longer fires CL201"
    )


def test_registry_covers_control_plane_counters():
    """Round 22 (the SLO-driven control plane) added the
    ``control.*`` decision/cooldown/ledger/setpoint registry plus the
    cadence-checkpoint counter. Both directions must hold: the
    emitted names stay documented in the README registry, and an
    undocumented ``control.*`` name still fires CL201 — the new
    namespace genuinely joined the registry-checked pool."""
    reg = _real_registry()
    for name in ("control.decisions", "control.cooldown_skips",
                 "control.ledger_dropped", "control.setpoint",
                 "snap.cadence_writes"):
        assert name in reg.metrics, (
            f"{name} dropped out of the README registry (round-22 "
            f"control-plane contract)"
        )
    result = _lint_snippet("crdt_tpu/obs/x.py", '''
def f(tracer):
    tracer.count("control.bogus_rule", 1)
''', _reg("control.decisions"))
    assert any(f.code == "CL201" for f in result.findings), (
        "an undocumented control.* metric no longer fires CL201"
    )


def test_registry_drift_fixed_event_kinds():
    """First-run CL201 drift on flight-recorder event kinds from the
    guard/storage/device adversaries."""
    reg = _real_registry()
    for name in ("guard.shed", "guard.evict", "device.fault",
                 "fault.disk", "persist.error"):
        assert name in reg.events | reg.metrics, (
            f"event kind {name} missing from the README event "
            f"registry"
        )


def test_fault_kind_events_declared_at_computed_site():
    """The one CL203 on first run: net/faults.py records
    f"fault.{kind}" — the closed name set must stay declared with an
    `emits=` directive so both registry directions keep seeing it."""
    with open(os.path.join(REPO, "crdt_tpu", "net", "faults.py")) as f:
        src = f.read()
    assert "crdtlint: emits=" in src
    for name in ("fault.drop", "fault.partition", "fault.corrupt",
                 "fault.delay", "fault.dup"):
        assert name in src


def test_device_hook_mutations_hold_lock():
    """First-run CL601s in ops/device.py: the fault-hook swap and the
    warn-once flag are reached from the streaming thread pool. Pin
    the behavior, not just the lint: concurrent swap-and-restore must
    never lose or duplicate a hook, and the degraded-cache warning
    must fire at most once under racing callers."""
    from crdt_tpu.ops import device as dev

    # swap storm: N threads each install a stream of unique tokens,
    # collecting what the swap hands back. An atomic exchange
    # conserves values under ANY interleaving: every installed token
    # (plus the initial hook) is returned by exactly one later swap
    # or is the final resident — a torn read-then-write would hand
    # the same predecessor to two threads and lose a token.
    initial = dev.device_fault_hook()
    n, rounds = 8, 200
    barrier = threading.Barrier(n)
    seen = [[] for _ in range(n)]

    def storm(tid):
        barrier.wait()
        for i in range(rounds):
            seen[tid].append(dev.set_device_fault_hook((tid, i)))

    threads = [
        threading.Thread(target=storm, args=(t,)) for t in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    final = dev.set_device_fault_hook(initial)  # restore + read last
    handed_out = Counter(v for lst in seen for v in lst)
    handed_out[final] += 1
    installed = Counter(
        (t, i) for t in range(n) for i in range(rounds)
    )
    installed[initial] += 1
    assert handed_out == installed, "hook swap lost/duplicated a value"
    assert dev.device_fault_hook() == initial

    # warn-once under racing callers: exactly one RuntimeWarning
    old_flag = dev._RESET_HOOK_WARNED
    dev._RESET_HOOK_WARNED = False
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            barrier2 = threading.Barrier(n)

            def warm():
                barrier2.wait()
                dev._warn_no_reset_hook()

            ts = [threading.Thread(target=warm) for _ in range(n)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        ours = [w for w in caught if "reset_cache" in str(w.message)]
        assert len(ours) == 1, (
            f"warn-once fired {len(ours)} times under racing threads"
        )
    finally:
        dev._RESET_HOOK_WARNED = old_flag


def test_device_memo_caches_locked_under_threads():
    """Review-pass CL601s (surfaced once the checker learned annotated
    globals): ``_pack_fns`` and ``_LOCAL_CPU_COMPILED`` are module
    memo caches reached from the streaming pool; their get-or-create
    now runs under ``_CACHE_LOCK``. Storm ``fetch_packed_i32`` across
    arities and pin byte-correct outputs with no lost cache entries."""
    import numpy as np

    jnp = pytest.importorskip("jax.numpy")
    from crdt_tpu.ops import device as dev

    with dev._CACHE_LOCK:
        dev._pack_fns.clear()
    n = 9
    errs = []
    barrier = threading.Barrier(n)

    def storm(tid):
        arity = 1 + (tid % 3)
        try:
            barrier.wait()
            arrays = [jnp.arange(4) + i for i in range(arity)]
            out = dev.fetch_packed_i32(*arrays)
            for i, a in enumerate(out):
                np.testing.assert_array_equal(
                    np.asarray(a), np.arange(4) + i
                )
        except Exception as e:  # noqa: BLE001 — collected for assert
            errs.append(e)

    threads = [
        threading.Thread(target=storm, args=(t,)) for t in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    # one jitted concat per distinct arity — racing threads must not
    # have lost entries (the pre-lock failure mode was a silent
    # overwrite: wasted recompile, never detected)
    assert sorted(dev._pack_fns) == [1, 2, 3]


def test_smoke_emit_skips_lint_pass(monkeypatch, tmp_path, capsys):
    """Review regression: ``emit_result(path=None)`` (the smoke mode
    every tier-1 run pays for) must not run the ~3s whole-tree lint
    pass for a digest nothing reads; the artifact path still embeds
    it."""
    import bench

    calls = []
    monkeypatch.setattr(
        bench, "lint_digest",
        lambda: calls.append(1) or {"findings": 0, "open": 0},
    )
    out = {"metric": "toy"}
    bench.emit_result(out, path=None)
    assert not calls and "lint" not in out

    out2 = {"metric": "toy"}
    bench.emit_result(out2, path=str(tmp_path / "B.json"))
    assert calls and out2["lint"] == {"findings": 0, "open": 0}
    capsys.readouterr()


# ---------------------------------------------------------------------------
# round-16 CL8xx audit: the lock-discipline checkers cleared the
# thread-shared surface (Tracer, FlightRecorder, the streaming
# _Phases accumulator, the serve() in-flight window) — these seeded
# storms pin the audited behavior so a refactor that drops a lock
# fails HERE, not just in the lint


def test_tracer_storm_conserves_counts():
    """CL803 audit pin: every Tracer mutation path (count/gauge/
    observe) under 8 racing threads loses nothing — the round-8 lock
    is load-bearing, not decorative."""
    from crdt_tpu.obs.tracer import Tracer

    tr = Tracer(enabled=True)
    n, rounds = 8, 400
    barrier = threading.Barrier(n)

    def storm(tid):
        barrier.wait()
        for i in range(rounds):
            tr.count("storm.hits")
            tr.count("storm.bytes", 3)
            tr.observe("storm.lat", 0.001 * ((tid + i) % 7 + 1))
            tr.gauge("storm.last", tid)

    threads = [
        threading.Thread(target=storm, args=(t,)) for t in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rep = tr.report()
    assert rep["counters"]["storm.hits"] == n * rounds
    assert rep["counters"]["storm.bytes"] == 3 * n * rounds
    span = rep["spans"]["storm.lat"]
    assert span["count"] == n * rounds
    assert sum(span["buckets"].values()) == n * rounds
    assert rep["gauges"]["storm.last"] in set(range(n))


def test_recorder_storm_conserves_events():
    """CL803 audit pin: FlightRecorder.record under racing producers
    never loses an increment, and the ring never exceeds capacity."""
    from crdt_tpu.obs.recorder import FlightRecorder

    rec = FlightRecorder(capacity=256, enabled=True)
    n, rounds = 8, 300
    barrier = threading.Barrier(n)

    def storm(tid):
        barrier.wait()
        for i in range(rounds):
            rec.record("update.sent", tid=tid, seq=i)

    threads = [
        threading.Thread(target=storm, args=(t,)) for t in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rec.recorded == n * rounds
    assert len(rec) == 256  # ring clamped at capacity, oldest evicted


def test_streaming_phase_accumulator_storm():
    """CL803 audit pin: the stager thread and the decode pool both
    charge busy seconds into one _Phases instance; racing adds must
    sum exactly (integer-valued floats — fp64 exact far beyond this
    count)."""
    from crdt_tpu.models.streaming import _Phases

    ph = _Phases()
    n, rounds = 9, 500
    barrier = threading.Barrier(n)

    def storm(tid):
        barrier.wait()
        for _ in range(rounds):
            ph.add("decode", 1.0)
            ph.add(f"lane{tid % 3}", 1.0)

    threads = [
        threading.Thread(target=storm, args=(t,)) for t in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ph.t["decode"] == float(n * rounds)
    assert sum(
        v for k, v in ph.t.items() if k.startswith("lane")
    ) == float(n * rounds)


def test_serve_inflight_window_ledger_exact():
    """CL803 audit pin for the serve() in-flight window: mid-tick
    arrivals (the live-ingest hook fires while a tick's dispatches
    are in flight) must never be marked converged without being
    converged, and the O(1) pending-byte ledger must land at exactly
    zero once the stream drains — the window accounting is
    single-thread-confined BY DESIGN (hook runs inside the tick),
    and this pins that the bookkeeping stays exact under it."""
    from crdt_tpu.codec import v1
    from crdt_tpu.core.records import ItemRecord
    from crdt_tpu.models import replay as rp
    from crdt_tpu.models.multidoc import MultiDocServer, cache_digest

    def blob(doc, b):
        return v1.encode_update([ItemRecord(
            client=1000 + doc, clock=b, parent_root=f"m{doc}",
            key=f"k{b}", content=b * 10 + doc,
        )])

    # 3 docs, 4 batches: each serve tick admits one batch, and the
    # ingest hook drains the next while dispatches are in flight
    batches = [
        [(d, blob(d, b)) for d in range(3)] for b in range(4)
    ]
    srv = MultiDocServer()
    rep = srv.serve(iter(batches), max_ticks=16)
    assert rep.submitted == 12
    assert srv.pending_bytes() == 0
    for d in range(3):
        st = srv._docs[d]
        assert not st.pending and not st.in_flight
        assert len(st.blobs) == 4  # every admitted blob converged
        # digest matches the cold oracle over the same history
        oracle = rp.replay_trace(st.blobs).cache
        assert cache_digest(srv.cache(d)) == cache_digest(oracle)


# ---------------------------------------------------------------------------
# round-16 CL702 regression: the Pallas dispatch decision is a
# host-computed static, never an ambient read inside a traced body


def test_pallas_mode_statics_thread_not_ambient(monkeypatch):
    """The traced-safe entries (apply_mask_static / missing_static /
    ds_mask_static / sv_deficit_static) must not read CRDT_TPU_PALLAS
    at all — poison the env readers and drive them with explicit
    modes. The first-run CL702 findings (env reads baked into
    converge_maps' trace via lax.cond) stay fixed."""
    import numpy as np

    jnp = pytest.importorskip("jax.numpy")
    from crdt_tpu.ops import deleteset, pallas_kernels as pk, statevec

    def boom(*a, **kw):
        raise AssertionError(
            "traced-safe path read CRDT_TPU_PALLAS (CL702 regression)"
        )

    monkeypatch.setattr(pk, "use_pallas", boom)
    monkeypatch.setattr(pk, "_interpret", boom)

    client = jnp.asarray(np.array([1, 1, 2], np.int32))
    clock = jnp.asarray(np.array([0, 5, 1], np.int64))
    valid = jnp.asarray(np.array([True, True, True]))
    dc = jnp.asarray(np.array([1], np.int32))
    dstart = jnp.asarray(np.array([0], np.int64))
    dend = jnp.asarray(np.array([1], np.int64))
    for mode in ("jnp", "interpret"):
        mask = deleteset.apply_mask_static(
            client, clock, valid, dc, dstart, dend, mode=mode
        )
        assert np.asarray(mask).tolist() == [True, False, False]
    svs = jnp.asarray(np.array([[3, 0], [1, 2]], np.int64))
    ref = np.asarray(statevec.missing_static(svs, "jnp"))
    got = np.asarray(statevec.missing_static(svs, "interpret"))
    np.testing.assert_array_equal(ref, got)


def test_mask_mode_reflects_env(monkeypatch):
    """The HOST-side mode helpers keep honoring runtime env flips —
    that is the contract the statics thread down."""
    from crdt_tpu.ops import deleteset, statevec

    monkeypatch.setenv("CRDT_TPU_PALLAS", "0")
    assert deleteset.mask_mode() == "jnp"
    assert statevec.deficit_mode() == "jnp"
    monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")
    assert deleteset.mask_mode() == "interpret"
    assert statevec.deficit_mode() == "interpret"


# ---------------------------------------------------------------------------
# ruff (satellite): targeted rule set, skip when unavailable


def test_ruff_clean_if_available():
    ruff = shutil.which("ruff")
    if ruff is None:
        pytest.skip("ruff not installed in this environment")
    proc = subprocess.run(
        [ruff, "check", "."], cwd=REPO, capture_output=True,
        text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_registry_covers_propagation_and_collector_counters():
    """Round 19 (distributed tracing) added the `propagation.*` and
    `collector.*` namespaces plus the per-hop replica rows and the
    bad-context event kind. Both directions must hold: the emitted
    names stay documented in the README registry, and an
    UNdocumented propagation/collector name still fires CL201 — the
    namespaces genuinely joined the registry-checked pool."""
    reg = _real_registry()
    for name in ("propagation.contexts_sent",
                 "propagation.contexts_received",
                 "propagation.malformed_contexts",
                 "propagation.hops_appended",
                 "propagation.hops_capped",
                 "propagation.context_bytes",
                 "propagation.traced_update_bytes",
                 "propagation.wire_overhead_ratio",
                 "replica.hop_lag",
                 "replica.birth_to_visibility",
                 "collector.procs", "collector.pair_rate",
                 "collector.scrapes", "collector.scrape_errors",
                 "collector.events_ingested",
                 "collector.divergences"):
        assert name in reg.metrics, (
            f"{name} dropped out of the README registry (round-19 "
            f"distributed-tracing contract)"
        )
    assert "update.bad_context" in reg.events | reg.metrics, (
        "update.bad_context event kind missing from the README "
        "event registry"
    )
    for path, snippet in (
        ("crdt_tpu/obs/x.py",
         'def f(tracer):\n    tracer.count("propagation.bogus", 1)\n'),
        ("crdt_tpu/obs/x.py",
         'def f(tracer):\n    tracer.gauge("collector.bogus", 1)\n'),
    ):
        result = _lint_snippet(path, snippet,
                               _reg("propagation.contexts_sent"))
        assert any(f.code == "CL201" for f in result.findings), (
            "an undocumented propagation/collector metric no longer "
            "fires CL201"
        )


def test_hop_lag_route_labels_declared_at_computed_site():
    """The route-labeled hop-lag observe is a computed name (one
    f-string over the closed route enum): the `emits=` directive
    must keep declaring it so both registry directions see it."""
    with open(os.path.join(REPO, "crdt_tpu", "obs",
                           "propagation.py")) as f:
        src = f.read()
    assert "crdtlint: emits=replica.hop_lag" in src


def test_wiretaint_scope_covers_trace_context_decode():
    """The round-19 decode path is inside the CL10xx/CL11xx scope:
    an unfenced wire read feeding an allocation in
    obs/propagation.py must fire, exactly like codec/."""
    from tools.crdtlint.checkers.decodealloc import DECODE_SCOPE
    from tools.crdtlint.checkers.wiretaint import SCOPE

    assert any("obs/propagation" in s for s in SCOPE)
    assert any("obs/propagation" in s for s in DECODE_SCOPE)
    result = _lint_snippet("crdt_tpu/obs/propagation.py", '''
def decode_thing(dec):
    n = dec.read_var_uint()
    return [0] * n
''')
    assert any(f.code == "CL1002" for f in result.findings), (
        "an unfenced allocation in obs/propagation.py no longer "
        "fires the wire-taint checker"
    )
