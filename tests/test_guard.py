"""Resource guards + failure policy (crdt_tpu/guard): tier-1 pins.

Four degradation ladders under seeded adversaries:

- device: the retry → split → host dispatch ladder, differential
  against the scalar oracle at every rung (a dead device yields a
  bit-identical answer, slower);
- engine: the pending-stash cap — provably bounded under a
  dependency-withholding adversary, evicted state recovered via the
  targeted SV re-probe;
- ingest: the inbox byte budget — provably bounded under a 10x flood,
  shed updates re-fetched through the probe/anti-entropy path;
- storage: retry/degrade/write-back, plus the ALICE-style crash-point
  matrix over ``store_updates``/``compact`` (simulated kill at every
  intermediate batch write; reopen loses no acked update).

The killer schedule composes all four (flood + withheld deps + disk
faults + device faults) in one seeded run per merge mode and asserts
byte-identical convergence with the fault-free oracle, every guard
counter pinned nonzero in the tracer.
"""

import math
import time

import pytest

from crdt_tpu.api.doc import Crdt
from crdt_tpu.core.engine import Engine
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.guard.device import dispatch_guarded
from crdt_tpu.guard.faults import (
    DeviceFaultPlan,
    DiskFaultSchedule,
    FaultyKv,
    SimulatedCrash,
)
from crdt_tpu.net.replica import Replica
from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
from crdt_tpu.storage.persistence import LogPersistence
from crdt_tpu.utils.trace import Tracer, set_tracer


@pytest.fixture
def tracer():
    from crdt_tpu.storage import persistence

    persistence._DEGRADED.clear()  # cross-test gauge isolation
    t = set_tracer(Tracer(enabled=True))
    yield t
    set_tracer(Tracer(enabled=False))


def _blobs(n=6, client=3, width=1):
    """n valid update blobs from a deterministic source doc."""
    src = Crdt(client)
    out = []
    src.on_update = lambda u, m: out.append(u)
    for i in range(n):
        src.set("m", f"k{i}", [i, "v" * width])
    return src, out


# ---------------------------------------------------------------------------
# the dispatch ladder (guard/device.py)
# ---------------------------------------------------------------------------


class TestDispatchLadder:
    def test_transient_fault_retries_once(self, tracer):
        with DeviceFaultPlan(fail_attempts=1):
            assert dispatch_guarded("t", lambda: 42) == 42
        c = tracer.counters()
        assert c["device.retries"] == 1
        assert "device.fallback" not in c

    def test_persistent_fault_falls_back_to_host(self, tracer):
        with DeviceFaultPlan(fail_attempts=2):
            out = dispatch_guarded("t", lambda: "dev", host=lambda: "host")
        assert out == "host"
        c = tracer.counters()
        assert c["device.fallback"] == 1
        assert c['device.fallback_by{route="host"}'] == 1

    def test_split_rung_re_guards_each_half(self, tracer):
        # main attempt + retry fail (2), first half's attempt + retry
        # fail (4) -> its host; second half succeeds on device
        with DeviceFaultPlan(fail_attempts=4):
            out = dispatch_guarded(
                "t",
                lambda: "whole",
                split=lambda: [
                    (lambda: "dev1", lambda: "host1"),
                    (lambda: "dev2", lambda: "host2"),
                ],
                host=lambda: "host-whole",
            )
        assert out == ["host1", "dev2"]
        c = tracer.counters()
        assert c['device.fallback_by{route="split"}'] == 1
        assert c['device.fallback_by{route="host"}'] == 1

    def test_without_rungs_the_error_reraises(self, tracer):
        with DeviceFaultPlan(fail_attempts=99):
            with pytest.raises(RuntimeError, match="injected"):
                dispatch_guarded("t", lambda: 1)

    def test_stage_filter_and_non_runtime_errors(self, tracer):
        with DeviceFaultPlan(fail_attempts=99, stages={"other"}):
            assert dispatch_guarded("t", lambda: 7) == 7

        def bad():
            raise ValueError("not a device fault")

        with pytest.raises(ValueError):
            dispatch_guarded("t", bad, host=lambda: 1)
        assert tracer.counters().get("device.retries", 0) == 0


class TestDeviceMergeLadder:
    """The ladder wired through the engine-backed device merge path:
    every rung lands on state bit-identical to the scalar oracle."""

    @pytest.mark.parametrize("fail_attempts", [1, 2, 4, 99])
    def test_faulted_device_merge_is_bit_identical(self, tracer,
                                                   fail_attempts):
        src = Crdt(3)
        blobs = []
        src.on_update = lambda u, m: blobs.append(u)
        for i in range(8):
            src.set("m", f"k{i}", i)
            src.push("l", [i])
            src.set("nest", "arr", i, array_method="push")
        oracle = Crdt(9)
        oracle.apply_updates(blobs)
        dev = Crdt(9, device_merge=True)
        with DeviceFaultPlan(fail_attempts=fail_attempts) as plan:
            dev.apply_updates(blobs)
        assert plan.fired > 0
        assert dev.engine.to_json() == oracle.engine.to_json()
        assert dev.engine.seq_order_table() == oracle.engine.seq_order_table()
        assert dev.engine.map_winner_table() == oracle.engine.map_winner_table()
        assert (
            dev.encode_state_as_update() == oracle.encode_state_as_update()
        )
        if fail_attempts >= 2:
            assert tracer.counters().get("device.fallback", 0) > 0


# ---------------------------------------------------------------------------
# pending-stash cap (engine + resident replay)
# ---------------------------------------------------------------------------


class TestPendingCap:
    def test_engine_pending_bounded_and_recoverable(self, tracer):
        e = Engine(5)
        e.pending_limit = 3
        dangling = [
            ItemRecord(client=9, clock=k, parent_root="s",
                       origin=(9, k - 1), content=k)
            for k in range(1, 12)
        ]
        e.apply_records(dangling)
        assert len(e.pending) <= 3
        # the kept records are the ones closest to the gap
        assert [r.clock for r in e.pending] == [1, 2, 3]
        ev = e.take_evicted_ranges()
        assert ev == {9: (4, 11)}
        assert e.take_evicted_ranges() == {}  # drained
        assert tracer.counters()["engine.pending_evictions"] == 8
        # recovery is the protocol's own math: our SV never advanced,
        # so a probe answer re-ships everything — replay the full set
        e.apply_records(
            [ItemRecord(client=9, clock=0, parent_root="s", content=0)]
            + dangling
        )
        assert not e.pending
        assert e.seq_json("s") == list(range(12))

    def test_eviction_ranks_per_client_not_by_absolute_clock(self, tracer):
        """A flooding FRESH client (low clocks) must not starve a
        long-lived client's nearly-ready records: eviction ranks by
        depth within each client's own queue."""
        e = Engine(5)
        e.pending_limit = 4
        old_client = [
            ItemRecord(client=7, clock=k, parent_root="s",
                       origin=(7, k - 1), content=k)
            for k in (1_000_001, 1_000_002)  # one gap from integrable
        ]
        flood = [
            ItemRecord(client=9, clock=k, parent_root="s",
                       origin=(9, k - 1), content=k)
            for k in range(1, 9)  # fresh client, low clocks, deep queue
        ]
        e._next_clock[7] = 1_000_000  # long-lived client's watermark
        e.apply_records(old_client + flood)
        kept = {(r.client, r.clock) for r in e.pending}
        # the old client's shallow (rank 0/1) records survive; the
        # flood's deep tail is what got evicted
        assert (7, 1_000_001) in kept and (7, 1_000_002) in kept
        assert len(e.pending) == 4
        assert 9 in e.take_evicted_ranges()

    def test_resident_pending_bounded_and_recoverable(self, tracer):
        from crdt_tpu.api.resident_doc import ResidentCrdt

        src = Crdt(9)
        blobs = []
        src.on_update = lambda u, m: blobs.append(u)
        for i in range(10):
            src.set("m", f"k{i}", i)
        doc = ResidentCrdt(5)
        doc.engine.pending_limit = 3
        doc.apply_updates(blobs[1:])  # withhold the first -> all stash
        assert len(doc.engine.pending) <= 3
        ev = doc.engine.take_evicted_ranges()
        assert 9 in ev
        assert tracer.counters()["engine.pending_evictions"] > 0
        doc.apply_updates(blobs)  # the re-fetched full set
        oracle = Crdt(5)
        oracle.apply_updates(blobs)
        assert dict(doc.c) == dict(oracle.c)


# ---------------------------------------------------------------------------
# inbox budget (flood) + withheld-deps re-probe, over loopback
# ---------------------------------------------------------------------------


def _pump_wall(net, reps, cond, timeout_s=20.0):
    """Pump a loopback fabric with WALL time: explicit replica ticks
    (the loopback run() only ticks during delivery rounds, so a quiet
    fabric needs the timer pump driven here, like a real router's
    poll loop) + queue drains + sleeps until ``cond()``."""
    deadline = time.monotonic() + timeout_s
    while not cond():
        if time.monotonic() > deadline:
            raise TimeoutError("loopback fabric did not converge")
        for r in reps:
            r.tick()
        net.run()
        time.sleep(0.005)


class TestInboxBudget:
    def test_flood_is_bounded_and_heals(self, tracer):
        net = LoopbackNetwork()
        a = Replica(
            LoopbackRouter(net, "a"), topic="t", client_id=1,
            batch_incoming=True, inbox_max_bytes=300,
            resync_retry_s=0.8,
        )
        b = Replica(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        # sustained 10x overload: each burst delivers well past the
        # budget in ONE round, five rounds in a row (the first resync
        # probe is deferred past the flood so no multi-op repair diff
        # — which the keep-the-newest rule admits whole — lands
        # mid-flood and muddies the peak assertion)
        for burst in range(5):
            for i in range(8):
                b.set("m", f"k{burst}_{i}", "x" * 40)
            net.run()
        assert a.inbox_peak_bytes <= 300, a.inbox_peak_bytes
        c = tracer.counters()
        assert c.get("guard.inbox_shed", 0) > 0
        assert c.get("guard.inbox_shed_bytes", 0) > 0
        # heal: the shed updates come back via the re-probe path
        _pump_wall(net, [a, b], lambda: dict(a.c) == dict(b.c)
                   and len(dict(a.c).get("m", {})) == 40)
        assert (
            a.doc.encode_state_as_update() == b.doc.encode_state_as_update()
        )

    def test_single_overbudget_update_still_lands(self, tracer):
        net = LoopbackNetwork()
        a = Replica(
            LoopbackRouter(net, "a"), topic="t", client_id=1,
            batch_incoming=True, inbox_max_bytes=64,
        )
        b = Replica(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        b.set("m", "big", "y" * 500)  # one update alone over budget
        net.run()
        assert dict(a.c)["m"]["big"] == "y" * 500


class TestWithheldDeps:
    def test_evictions_then_targeted_resync(self, tracer):
        net = LoopbackNetwork()
        a = Replica(
            LoopbackRouter(net, "a"), topic="t", client_id=1,
            batch_incoming=True, pending_max_records=2,
            resync_retry_s=0.01,
        )
        b = Replica(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        # the adversary: drop b's first two update broadcasts at the
        # fabric seam (app-level withholding — deterministic)
        dropped = []
        subs = net.topics["t"]
        for i, (r, h) in enumerate(subs):
            if r.public_key == "a":
                def wrapped(msg, frm, _h=h):
                    if (
                        frm == "b" and "update" in msg
                        and msg.get("meta") != "sync" and len(dropped) < 2
                    ):
                        dropped.append(msg)
                        return
                    _h(msg, frm)

                subs[i] = (r, wrapped)
        for i in range(6):
            b.set("m", f"k{i}", i)
        net.run()
        assert len(dropped) == 2
        assert len(a.doc.engine.pending) <= 2
        c = tracer.counters()
        assert c.get("engine.pending_evictions", 0) >= 2
        # the re-probe (bounded backoff, targeted at the blocking
        # peer) re-fetches both the withheld AND the evicted state
        _pump_wall(net, [a, b], lambda: dict(a.c) == dict(b.c)
                   and len(dict(a.c).get("m", {})) == 6)
        assert tracer.counters().get("guard.resync_probes", 0) > 0
        assert not a.doc.engine.pending
        assert (
            a.doc.encode_state_as_update() == b.doc.encode_state_as_update()
        )


class TestMalformedBisection:
    def test_isolation_cost_is_logarithmic(self, tracer):
        """One poisoned blob in an N-update flush costs O(log N) extra
        merge transactions (recursive bisection), not O(N) per-item
        retries — pinned by the split counter."""
        net = LoopbackNetwork()
        a = Replica(
            LoopbackRouter(net, "a"), topic="t", client_id=1,
            batch_incoming=True,
        )
        Replica(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        _, blobs = _blobs(16, client=7)
        for u in blobs:
            a._inbox.append((u, {"meta": None}, "b"))
        a._inbox.insert(8, (b"\xff\xfe\xfd", {"meta": None}, "evil"))
        a.flush_incoming()
        assert len(dict(a.c)["m"]) == 16
        c = tracer.counters()
        assert c["replica.malformed_updates"] == 1
        # bisection depth over 17 items, one split per poisoned level
        assert c["replica.isolation_splits"] <= math.ceil(math.log2(17)) + 1


# ---------------------------------------------------------------------------
# storage failure policy + crash points
# ---------------------------------------------------------------------------


def _faulty_lp(path, sched, **kw):
    kw.setdefault("retry_backoff_s", 0.001)
    return LogPersistence(
        str(path), kv_wrapper=lambda kv: FaultyKv(kv, sched), **kw
    )


class TestStoragePolicy:
    def test_transient_write_fault_retries(self, tmp_path, tracer):
        _, blobs = _blobs(2)
        lp = _faulty_lp(tmp_path / "s.kvlog",
                        DiskFaultSchedule(fail_writes={1}))
        lp.store_update("d", blobs[0])  # write 0 ok
        lp.store_update("d", blobs[1])  # write 1 fails -> retry ok
        assert tracer.counters()["persist.retries"] == 1
        assert lp.get_all_updates("d") == blobs
        assert "persist.degraded_writes" not in tracer.counters()
        lp.close()

    def test_degrade_then_write_back(self, tmp_path, tracer):
        _, blobs = _blobs(3)
        lp = _faulty_lp(tmp_path / "s.kvlog",
                        DiskFaultSchedule(fail_writes={1, 2, 3}),
                        retries=2)
        lp.store_update("d", blobs[0])        # write 0 ok
        lp.store_update("d", blobs[1])        # writes 1-3 fail: degrade
        rep = tracer.report()
        assert rep["gauges"]["persist.degraded"] == 1
        assert rep["counters"]["persist.degraded_writes"] == 1
        # reads see the buffered update during the outage
        assert lp.get_all_updates("d") == blobs[:2]
        lp.store_update("d", blobs[2])        # write 4 ok: drains + syncs
        rep = tracer.report()
        assert rep["gauges"]["persist.degraded"] == 0
        assert rep["counters"]["persist.recovered_updates"] == 1
        assert lp.get_all_updates("d") == blobs
        lp.close()
        # the write-back is durable
        lp2 = LogPersistence(str(tmp_path / "s.kvlog"))
        assert lp2.get_all_updates("d") == blobs
        lp2.close()

    def test_degraded_gauge_counts_stores_process_wide(self, tmp_path,
                                                       tracer):
        """One store's healthy writes must not mask another store's
        active degradation: the gauge counts currently-degraded
        (store, doc) windows, not the last writer's local state."""
        _, blobs = _blobs(3)
        bad = _faulty_lp(tmp_path / "bad.kvlog",
                         DiskFaultSchedule(fail_writes={1, 2, 3}),
                         retries=2)
        good = LogPersistence(str(tmp_path / "good.kvlog"))
        bad.store_update("d", blobs[0])   # write 0 ok
        bad.store_update("d", blobs[1])   # writes 1-3 fail: degraded
        assert tracer.report()["gauges"]["persist.degraded"] == 1
        good.store_update("d", blobs[0])  # healthy store writes fine...
        # ...and the gauge still reports bad's active degradation
        assert tracer.report()["gauges"]["persist.degraded"] == 1
        bad.store_update("d", blobs[2])   # write 4 ok: drains + clears
        assert tracer.report()["gauges"]["persist.degraded"] == 0
        bad.close()
        good.close()

    def test_overflow_bound_holds_across_docs(self, tmp_path, tracer):
        """``overflow_max_bytes`` is a GLOBAL budget: many degraded
        docs on one store trim against the shared total (oldest of the
        largest buffer first), never N x per-doc windows."""
        _, blobs = _blobs(4, width=60)
        sz = len(blobs[0])
        budget = 3 * sz  # far less than 4 docs x 4 updates
        lp = _faulty_lp(
            tmp_path / "s.kvlog",
            DiskFaultSchedule(fail_writes=set(range(4096))),
            retries=0, overflow_max_bytes=budget,
        )
        for doc in ("d0", "d1", "d2", "d3"):
            for u in blobs:
                lp.store_update(doc, u)
        assert lp._overflow_bytes <= budget
        assert tracer.counters()["persist.dropped_updates"] > 0
        # the window degrading last always keeps its newest update
        assert blobs[-1] in lp.get_all_updates("d3")
        lp.close()

    def test_raise_policy_propagates(self, tmp_path):
        _, blobs = _blobs(1)
        lp = _faulty_lp(tmp_path / "s.kvlog",
                        DiskFaultSchedule(fail_writes={0, 1, 2}),
                        retries=2, failure_policy="raise")
        with pytest.raises(OSError):
            lp.store_update("d", blobs[0])
        lp.close()

    def test_replica_survives_persistence_failure(self, tracer):
        """A backend with NO policy of its own raising mid-apply must
        not kill the apply path (the last-resort replica guard)."""
        class ExplodingPersistence:
            closed = False

            def store_update(self, *a, **kw):
                raise OSError("disk on fire")

            def get_all_updates(self, doc):
                return []

            def get_meta(self, doc):
                return None

            def close(self):
                self.closed = True

        net = LoopbackNetwork()
        a = Replica(
            LoopbackRouter(net, "a"), topic="t", client_id=1,
            persistence=ExplodingPersistence(),
        )
        b = Replica(LoopbackRouter(net, "b"), topic="t", client_id=2)
        net.run()
        b.set("m", "k", 1)
        net.run()  # a persists (explodes) but still applies
        assert dict(a.c)["m"] == {"k": 1}
        assert tracer.counters()["persist.errors"] > 0

    def test_failed_compact_rederives_next_seq(self, tmp_path, tracer):
        """Satellite fix: a failed compact invalidates the cached
        ``_next_seq`` so later appends re-derive from the log scan and
        never overwrite a live key (the stale-cache reopen hazard)."""
        _, blobs = _blobs(3)
        doc2 = Crdt(11)
        doc2.apply_updates(blobs[:2])
        snap = doc2.encode_state_as_update()
        lp = _faulty_lp(tmp_path / "s.kvlog",
                        DiskFaultSchedule(fail_writes={2, 3, 4}),
                        retries=2)
        lp.store_update("d", blobs[0])  # write 0
        lp.store_update("d", blobs[1])  # write 1
        lp.compact("d", snap)           # writes 2-4 fail -> degraded skip
        assert tracer.counters()["persist.compact_errors"] == 1
        lp.store_update("d", blobs[2])  # must append, not overwrite
        assert lp.get_all_updates("d") == blobs
        lp.close()
        lp2 = LogPersistence(str(tmp_path / "s.kvlog"))
        assert lp2.get_all_updates("d") == blobs
        lp2.close()


class TestCrashPointMatrix:
    """Simulated kill at EVERY intermediate op of every KV batch in an
    append/compact/append workload; reopening the store must lose no
    acked update (the torn-batch adversary models a store without the
    native log's atomic batches — compact's put-snapshot-before-delete
    ordering is what survives it)."""

    def _run_workload(self, lp, blobs, snap4):
        acked = []
        for u in blobs[:4]:
            lp.store_update("d", u)
            acked.append(u)
        lp.compact("d", snap4)
        for u in blobs[4:6]:
            lp.store_update("d", u)
            acked.append(u)
        return acked

    def test_matrix(self, tmp_path):
        _, blobs = _blobs(6)
        doc4 = Crdt(9)
        doc4.apply_updates(blobs[:4])
        snap4 = doc4.encode_state_as_update()

        # clean run records every batch's op count (the matrix axes)
        holder = []

        def wrapper(kv, sched=None):
            fk = FaultyKv(kv, sched or DiskFaultSchedule())
            holder.append(fk)
            return fk

        lp = LogPersistence(str(tmp_path / "clean.kvlog"),
                            kv_wrapper=wrapper)
        self._run_workload(lp, blobs, snap4)
        lp.close()
        shapes = holder[0].batches
        assert len(shapes) == 7  # 4 appends + compact + 2 appends

        for i, nops in enumerate(shapes):
            for j in range(nops + 1):
                path = str(tmp_path / f"c{i}_{j}.kvlog")
                sched = DiskFaultSchedule(crash_at=(i, j))
                lp = _faulty_lp(path, sched, retries=0)
                acked = []
                try:
                    acked = []
                    for u in blobs[:4]:
                        lp.store_update("d", u)
                        acked.append(u)
                    lp.compact("d", snap4)
                    for u in blobs[4:6]:
                        lp.store_update("d", u)
                        acked.append(u)
                except SimulatedCrash:
                    pass
                # hard kill: close the REAL file under the dead wrapper
                lp._kv._inner.close()
                reopened = LogPersistence(path)
                replayed = Crdt(11)
                replayed.apply_updates(reopened.get_all_updates("d"))
                before = replayed.encode_state_as_update()
                replayed.apply_updates(acked)  # must all be known
                assert replayed.encode_state_as_update() == before, (i, j)
                # _next_seq re-derives from the scan: appending after
                # reopen never overwrites surviving keys
                n0 = len(reopened.get_all_updates("d"))
                reopened.store_update("d", blobs[5])
                assert len(reopened.get_all_updates("d")) == n0 + 1
                reopened.close()


# ---------------------------------------------------------------------------
# the killer schedule: flood + withheld deps + disk faults + device
# faults, one seeded run per merge mode, byte-identical to the oracle
# ---------------------------------------------------------------------------


def _killer_run(merge_mode, tmp_path, *, faulted):
    net = LoopbackNetwork(seed=7)
    routers = [LoopbackRouter(net, f"r{i}") for i in range(3)]
    resident = merge_mode == "resident"
    lp = None
    if faulted:
        lp = _faulty_lp(
            tmp_path / f"{merge_mode}.kvlog",
            DiskFaultSchedule(fail_writes={1, 2, 3}), retries=2,
        )
    # anti-entropy stays OFF: the shed/evict repair must flow through
    # the targeted resync probe alone, so `guard.resync_probes > 0`
    # is deterministic instead of racing the AE cadence (the AE
    # repair path itself is covered by the net-layer chaos tests)
    guards = dict(
        inbox_max_bytes=260, pending_max_records=2,
        resync_retry_s=0.01,
    ) if faulted else {}
    a = Replica(
        routers[0], topic="room", client_id=1, merge_mode=merge_mode,
        batch_incoming=True, persistence=lp,
        device_min_rows=1 if resident else None, **guards,
    )
    b = Replica(
        routers[1], topic="room", client_id=2, merge_mode=merge_mode,
        batch_incoming=True,
        device_min_rows=1 if resident else None,
    )
    cr = Replica(
        routers[2], topic="room", client_id=3, merge_mode=merge_mode,
        batch_incoming=True,
        device_min_rows=1 if resident else None,
    )
    net.run()
    dropped = []
    if faulted:
        # withheld-deps adversary at the fabric seam: a loses b's
        # first two update broadcasts
        subs = net.topics["room"]
        for i, (r, h) in enumerate(subs):
            if r is routers[0]:
                def wrapped(msg, frm, _h=h):
                    if (
                        frm == "r1" and "update" in msg
                        and msg.get("meta") != "sync"
                        and len(dropped) < 2
                    ):
                        dropped.append(msg)
                        return
                    _h(msg, frm)

                subs[i] = (r, wrapped)
    plan = DeviceFaultPlan(fail_attempts=2) if (
        faulted and merge_mode != "scalar"
    ) else None
    if plan:
        plan.install()
    try:
        # every write happens BLIND (no delivery in between, like the
        # PR 2 chaos smoke): local record creation is then delivery-
        # independent, so the faulted and fault-free runs produce the
        # same op set and byte-identical convergence is assertable.
        # b's burst is the flood (4x the inbox budget in one round,
        # first two blobs withheld -> pending gaps + sheds together);
        # a's own writes drive the faulted WAL through its retry/
        # degrade/write-back ladder before any traffic arrives
        for i in range(4):
            a.set("kv", f"a{i}", i)
        for i in range(8):
            b.set("kv", f"b{i}", [i, "vvvv"])
        for i in range(4):
            cr.push("log", f"c{i}")
        net.run()
        reps = [a, b, cr]

        def converged():
            cs = [dict(r.c) for r in reps]
            return (
                cs[0] == cs[1] == cs[2]
                and len(cs[0].get("kv", {})) == 12
                and len(cs[0].get("log", [])) == 4
            )

        _pump_wall(net, reps, converged, timeout_s=30.0)
    finally:
        if plan:
            plan.uninstall()
    snaps = [r.doc.encode_state_as_update() for r in reps]
    svs = [r.doc.encode_state_vector() for r in reps]
    cache = dict(a.c)
    if lp is not None:
        lp.close()
    return snaps, svs, cache, len(dropped), (plan.fired if plan else 0)


@pytest.mark.parametrize("merge_mode", ["scalar", "device", "resident"])
def test_killer_schedule_converges_byte_identical(merge_mode, tmp_path):
    tracer = set_tracer(Tracer(enabled=True))
    try:
        clean = _killer_run(merge_mode, tmp_path, faulted=False)
        faulted = _killer_run(merge_mode, tmp_path, faulted=True)
    finally:
        set_tracer(Tracer(enabled=False))
    # every adversary actually showed up, every guard fired, visibly
    c = tracer.counters()
    rep = tracer.report()
    assert faulted[3] == 2  # withheld deps
    assert c.get("guard.inbox_shed", 0) > 0, c
    assert c.get("engine.pending_evictions", 0) > 0, c
    assert c.get("guard.resync_probes", 0) > 0, c
    assert c.get("persist.degraded_writes", 0) > 0, c
    assert c.get("persist.recovered_updates", 0) > 0, c
    assert rep["gauges"].get("persist.degraded") == 0  # recovered
    if merge_mode != "scalar":
        assert faulted[4] > 0  # injected device faults fired
        assert c.get("device.fallback", 0) > 0, c
        assert c.get("device.retries", 0) > 0, c
    # ...and convergence is byte-identical to the fault-free oracle:
    # same snapshots, same state vectors, every replica
    clean_snaps, clean_svs, clean_cache, _, _ = clean
    f_snaps, f_svs, f_cache, _, _ = faulted
    assert clean_snaps[0] == clean_snaps[1] == clean_snaps[2]
    assert f_snaps[0] == f_snaps[1] == f_snaps[2]
    assert f_snaps == clean_snaps
    assert f_svs == clean_svs
    assert f_cache == clean_cache
