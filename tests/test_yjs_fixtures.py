"""Foreign v1 bytes: hand-derived wire fixtures, not self-round-trips.

This environment has no Node.js and no Yjs installation (zero egress),
so blobs literally emitted by Yjs cannot be captured here. The next
best evidence of byte compatibility — and what these tests provide —
is INDEPENDENCE: every fixture below is a hex literal assembled by
hand, byte by byte, from the published v1 wire grammar (lib0 varints,
struct info bits, content refs, the `any` type codes), NOT produced by
this repo's encoder. A shared misunderstanding between our encoder and
decoder cannot forge a pass here: the decoder must accept the foreign
layout, the engine must integrate it, and the re-encode must reproduce
the exact original bytes (Yjs's own canonical choices: clients in
descending order, maximal runs, minimal varints).

Covered, per VERDICT r1 item #6: multi-client updates, string runs
with surrogate pairs, GC + Skip structs, Deleted runs, nested types,
items with left+right origins, delete sets, negative ints / null /
bool `any` payloads.
"""

from crdt_tpu.codec import v1
from crdt_tpu.core.engine import Engine
from crdt_tpu.core.store import (
    K_ANY,
    K_DELETED,
    K_GC,
    K_STRING,
    K_TYPE,
    TYPE_ARRAY,
)

# --- fixture A: README-shape map set --------------------------------------
# new Y.Doc({clientID: 176}); doc.getMap('users').set('user1',
#   {name: 'Alice', age: 30})
# one client group / one struct / ContentAny(object) / parentSub
FIX_MAP_SET = bytes.fromhex(
    "01"            # numClients = 1
    "01"            # numStructs = 1
    "b001"          # client 176 (two-byte varuint)
    "00"            # start clock 0
    "28"            # info: ref 8 (Any) | 0x20 (parentSub present)
    "01"            # parentInfo: 1 = parent is a root name
    "05" "7573657273"   # "users"
    "05" "7573657231"   # parentSub "user1"
    "01"            # ContentAny: 1 element
    "76"            # any: object (118)
    "02"            # 2 keys
    "04" "6e616d65" # "name"
    "77" "05" "416c696365"  # any string (119) "Alice"
    "03" "616765"   # "age"
    "7d" "1e"       # any varInt (125) = 30
    "00"            # empty delete set
)

# --- fixture B: text with surrogates, a Deleted run, GC, a delete set -----
# client 13 typed "héllo 😀" into root text "t" (8 UTF-16 units),
# then two deleted units and three GC'd clocks
FIX_TEXT_GC = bytes.fromhex(
    "01"            # numClients
    "03"            # numStructs
    "0d"            # client 13
    "00"            # start clock
    "04"            # info: ref 4 (String), parent follows
    "01" "01" "74"  # parent root "t"
    "0b" "68c3a96c6c6f20f09f9880"  # varstring "héllo 😀" (11 utf-8 bytes)
    "81"            # info: ref 1 (Deleted) | 0x80 (origin)
    "0d" "07"       # origin (13, 7)
    "02"            # deleted length 2  (clocks 8-9)
    "00"            # info: ref 0 (GC)
    "03"            # GC length 3      (clocks 10-12)
    "01"            # delete set: 1 client
    "0d"            # client 13
    "01"            # 1 range
    "08" "02"       # clock 8, len 2
)

# --- fixture C: nested type, mid-run parents, left+right origins ----------
# client 7: set root map "root" key "list" = new Y.Array(), push 1, "x";
# client 3 concurrently inserts `true` between (7,1) and (7,2)
FIX_NESTED = bytes.fromhex(
    "02"            # numClients (descending: 7 then 3)
    "02" "07" "00"  # client 7: 2 structs from clock 0
    "27"            # info: ref 7 (Type) | 0x20 (parentSub)
    "01" "04" "726f6f74"  # parent root "root"
    "04" "6c697374"       # parentSub "list"
    "00"            # typeRef 0 = YArray
    "08"            # info: ref 8 (Any), parent follows (no origins)
    "00" "07" "00"  # parentInfo 0 = parent is the item (7, 0)
    "02"            # ContentAny: 2 elements (clocks 1-2)
    "7d" "01"       # any varInt 1
    "77" "01" "78"  # any string "x"
    "01" "03" "00"  # client 3: 1 struct from clock 0
    "c8"            # info: ref 8 (Any) | 0x80 origin | 0x40 rightOrigin
    "07" "01"       # origin (7, 1)
    "07" "02"       # rightOrigin (7, 2)
    "01" "78"       # ContentAny: 1 element: any true (120)
    "00"            # empty delete set
)

# --- fixture D: any-array payload with null / false / negative int --------
# client 1: getMap('m').set('k', [null, false, -5])
FIX_ANY_EDGE = bytes.fromhex(
    "01" "01" "01" "00"
    "28"            # Any | parentSub
    "01" "01" "6d"  # parent root "m"
    "01" "6b"       # parentSub "k"
    "01"            # 1 element
    "75" "03"       # any array (117), 3 elements
    "7e"            # null (126)
    "79"            # false (121)
    "7d" "45"       # varInt -5 (sign bit 0x40 | 5)
    "00"
)

# --- fixture E: state vector ----------------------------------------------
# {200: 3, 1: 5}, clients descending
FIX_SV = bytes.fromhex("02" "c801" "03" "01" "05")


class TestForeignDecode:
    def test_map_set_fixture(self):
        recs, ds = v1.decode_update(FIX_MAP_SET)
        assert len(recs) == 1 and not ds.ranges
        r = recs[0]
        assert (r.client, r.clock) == (176, 0)
        assert r.parent_root == "users" and r.key == "user1"
        assert r.kind == K_ANY
        assert r.content == {"name": "Alice", "age": 30}

    def test_text_gc_fixture(self):
        recs, ds = v1.decode_update(FIX_TEXT_GC)
        kinds = [r.kind for r in recs]
        assert kinds == [K_STRING] * 8 + [K_DELETED] * 2 + [K_GC] * 3
        assert [r.clock for r in recs] == list(range(13))
        units = [r.content for r in recs[:8]]
        assert v1._join_utf16(units) == "héllo \U0001F600"
        assert recs[8].origin == (13, 7)
        assert ds.contains(13, 8) and ds.contains(13, 9)
        assert not ds.contains(13, 7)

    def test_nested_fixture(self):
        recs, _ = v1.decode_update(FIX_NESTED)
        by_id = {(r.client, r.clock): r for r in recs}
        t = by_id[(7, 0)]
        assert t.kind == K_TYPE and t.type_ref == TYPE_ARRAY
        assert t.parent_root == "root" and t.key == "list"
        assert by_id[(7, 1)].parent_item == (7, 0)
        assert by_id[(7, 1)].content == 1
        assert by_id[(7, 2)].origin == (7, 1)
        assert by_id[(7, 2)].content == "x"
        c3 = by_id[(3, 0)]
        assert c3.origin == (7, 1) and c3.right == (7, 2)
        assert c3.content is True

    def test_any_edge_fixture(self):
        recs, _ = v1.decode_update(FIX_ANY_EDGE)
        assert recs[0].content == [None, False, -5]

    def test_state_vector_fixture(self):
        sv = v1.decode_state_vector(FIX_SV)
        assert sv.clocks == {200: 3, 1: 5}


class TestForeignReencode:
    """decode -> re-encode must reproduce the foreign bytes exactly
    (clients descending, maximal runs, minimal varints — Yjs's own
    canonical layout)."""

    def test_byte_stable(self):
        for blob in (FIX_MAP_SET, FIX_TEXT_GC, FIX_NESTED, FIX_ANY_EDGE):
            recs, ds = v1.decode_update(blob)
            assert v1.encode_update(recs, ds) == blob

    def test_state_vector_byte_stable(self):
        sv = v1.decode_state_vector(FIX_SV)
        assert v1.encode_state_vector(sv) == FIX_SV


class TestForeignIntegration:
    """decode -> engine -> materialized state, both merge modes."""

    def test_map_set_integrates(self):
        e = Engine(999)
        v1.apply_update(e, FIX_MAP_SET)
        assert e.to_json() == {"users": {"user1": {"name": "Alice", "age": 30}}}

    def test_text_gc_integrates(self):
        e = Engine(999)
        v1.apply_update(e, FIX_TEXT_GC)
        # 6 visible units: the surrogate pair died with clocks 8-9?
        # no — the delete set covers clocks 8-9 (the Deleted run), so
        # all 8 string units stay visible
        vis = e.seq_json("t")
        assert v1._join_utf16(vis) == "héllo \U0001F600"
        assert e.delete_set().contains(13, 8)
        assert not e.pending

    def test_nested_integrates_and_orders(self):
        e = Engine(999)
        v1.apply_update(e, FIX_NESTED)
        # client 3's `true` landed between 1 and "x" (its origins)
        assert e.to_json() == {"root": {"list": [1, True, "x"]}}

    def test_device_mode_matches_scalar_on_foreign_bytes(self):
        from crdt_tpu.api.doc import Crdt

        for blob in (FIX_MAP_SET, FIX_TEXT_GC, FIX_NESTED, FIX_ANY_EDGE):
            s = Crdt(999, device_merge=False)
            d = Crdt(999, device_merge=True)
            s.apply_update(blob)
            d.apply_update(blob)
            assert dict(s.c) == dict(d.c)
            assert s.engine.to_json() == d.engine.to_json()
            assert s.engine.delete_set() == d.engine.delete_set()
            assert s.encode_state_as_update() == d.encode_state_as_update()


# --- fixtures F-K: the remaining content refs, each hand-assembled --------
# JSON(2) run with an `undefined` sentinel; Binary(3); Embed(5);
# Format(6); Doc(9) under a map key; Skip(10) splitting a client's
# clock range mid-update (the "weird interleaving" — clocks 2-6 are
# declared-but-absent, exactly how Yjs serializes a partial diff)

# client 9 appended two ContentJSON elements to root list "jl"
FIX_JSON_RUN = bytes.fromhex(
    "01" "01" "09" "00"
    "02"                    # info: ref 2 (JSON), parent follows
    "01" "02" "6a6c"        # parent root "jl"
    "02"                    # 2 json elements (clocks 0-1)
    "08" "7b2261223a20317d" # '{"a": 1}'
    "09" "756e646566696e6564"  # the literal 'undefined' sentinel
    "00"
)

# client 5 inserted ContentBinary deadbeef into root list "b"
FIX_BINARY = bytes.fromhex(
    "01" "01" "05" "00"
    "03"                    # info: ref 3 (Binary)
    "01" "0162"             # parent root "b"
    "04" "deadbeef"
    "00"
)

# client 6 embedded {"src": "img"} into root text "e" (Quill-style)
FIX_EMBED = bytes.fromhex(
    "01" "01" "06" "00"
    "05"                    # info: ref 5 (Embed)
    "01" "0165"             # parent root "e"
    "0e" "7b22737263223a2022696d67227d"  # '{"src": "img"}'
    "00"
)

# client 4 set a bold-start format marker in root text "tf"
FIX_FORMAT = bytes.fromhex(
    "01" "01" "04" "00"
    "06"                    # info: ref 6 (Format)
    "01" "027466"           # parent root "tf"
    "04" "626f6c64"         # key "bold"
    "04" "74727565"         # value 'true' (json)
    "00"
)

# client 8 stored a ContentDoc (subdocument guid "g1") at docs.sub
FIX_DOC = bytes.fromhex(
    "01" "01" "08" "00"
    "29"                    # info: ref 9 (Doc) | 0x20 parentSub
    "01" "04646f6373"       # parent root "docs"
    "03" "737562"           # parentSub "sub"
    "02" "6731"             # guid "g1"
    "76" "00"               # any: empty options object
    "00"
)

# client 11: "ab" at clocks 0-1, a Skip over clocks 2-6, then "z" at
# clock 7 whose origin is (11,1) — the tail of a diff whose middle is
# not included (Yjs emits exactly this shape for partial updates)
FIX_SKIP_MID = bytes.fromhex(
    "01" "03" "0b" "00"
    "04" "01" "027432"      # String, parent root "t2"
    "02" "6162"             # "ab"
    "0a" "05"               # Skip 5 (clocks 2-6)
    "84"                    # String | origin
    "0b" "01"               # origin (11, 1)
    "01" "7a"               # "z"
    "00"
)

_ALL_REF_FIXTURES = (
    FIX_MAP_SET, FIX_TEXT_GC, FIX_NESTED, FIX_ANY_EDGE, FIX_JSON_RUN,
    FIX_BINARY, FIX_EMBED, FIX_FORMAT, FIX_DOC, FIX_SKIP_MID,
)


class TestRemainingRefsDecode:
    def test_json_run(self):
        from crdt_tpu.codec.lib0 import UNDEFINED
        from crdt_tpu.core.store import K_JSON

        recs, _ = v1.decode_update(FIX_JSON_RUN)
        assert [r.kind for r in recs] == [K_JSON, K_JSON]
        assert recs[0].content == {"a": 1}
        assert recs[1].content is UNDEFINED
        assert recs[1].origin == (9, 0)  # unit chaining

    def test_binary(self):
        from crdt_tpu.core.store import K_BINARY

        recs, _ = v1.decode_update(FIX_BINARY)
        assert recs[0].kind == K_BINARY
        assert bytes(recs[0].content) == b"\xde\xad\xbe\xef"

    def test_embed(self):
        from crdt_tpu.core.store import K_EMBED

        recs, _ = v1.decode_update(FIX_EMBED)
        assert recs[0].kind == K_EMBED
        assert recs[0].content == {"src": "img"}

    def test_format(self):
        from crdt_tpu.core.store import K_FORMAT

        recs, _ = v1.decode_update(FIX_FORMAT)
        assert recs[0].kind == K_FORMAT
        assert recs[0].content == ("bold", True)

    def test_doc(self):
        from crdt_tpu.core.store import K_DOC

        recs, _ = v1.decode_update(FIX_DOC)
        assert recs[0].kind == K_DOC
        assert recs[0].key == "sub"
        assert recs[0].content == ("g1", {})

    def test_skip_interleaving(self):
        recs, _ = v1.decode_update(FIX_SKIP_MID)
        assert [r.clock for r in recs] == [0, 1, 7]  # 2-6 skipped
        assert recs[2].origin == (11, 1)

    def test_all_refs_byte_stable(self):
        """decode -> re-encode reproduces the foreign bytes exactly
        for every fixture — all 11 wire refs covered both directions
        (GC/Deleted/JSON/Binary/String/Embed/Format/Type/Any/Doc/Skip)."""
        for blob in _ALL_REF_FIXTURES:
            recs, ds = v1.decode_update(blob)
            assert v1.encode_update(recs, ds) == blob, blob.hex()

    def test_skip_gap_stashes_pending(self):
        """The post-Skip item sits above a clock gap: the engine must
        stash it (Yjs pending structs), not integrate or crash."""
        e = Engine(999)
        v1.apply_update(e, FIX_SKIP_MID)
        assert v1._join_utf16(e.seq_json("t2")) == "ab"
        assert e.pending  # "z" waits for clocks 2-6

    def test_native_codec_agrees_on_all_fixtures(self):
        """The C decoder accepts the same foreign bytes and re-encodes
        them identically (when the toolchain is available)."""
        import pytest

        from crdt_tpu.codec import native

        if not native.available():
            pytest.skip("native codec toolchain unavailable")
        for blob in _ALL_REF_FIXTURES:
            dec = native.decode_updates_columns([blob])
            assert native.encode_from_columns(dec) == blob, blob.hex()


class TestAdversarialRejectionMatrix:
    """VERDICT r3 item 7: with no channel for ground-truth Yjs bytes
    (no Node/Yjs in the image, zero egress), the decoders' REJECTION
    behavior is pinned instead. Every entry states a verdict —
    "reject" (ValueError, both codecs) or "accept" (parses, both
    codecs, same record/ds counts) — and the python and native
    decoders must AGREE case by case: a silent divergence would let a
    hostile blob split a mixed swarm. The hostile classes that
    motivated the matrix (all found live, round 4): GC/Deleted runs
    whose declared length bought unbounded per-clock expansion (both
    decoders hung), varuint 64-bit overflow silently WRAPPING in the C
    reader (a 2^69 length sailed under every sanity cap as 32), and
    delete ranges whose expansion was deferred to the apply path.
    Wire bounds now enforced at decode: clocks and run/range ends <
    2^40 (the kernels' pack_id clock width), GC/Deleted expansion
    budgeted per blob byte, varuint overflow rejects."""

    # (name, hex blob, verdict, note)
    MATRIX = [
        # --- truncated varints mid-struct --------------------------------
        ("trunc_numclients_only", "01", "reject",
         "numClients then EOF"),
        ("trunc_before_client", "0101", "reject",
         "numStructs then EOF before client id"),
        ("trunc_client_continuation", "0101b0", "reject",
         "client varuint ends with continuation bit set"),
        ("trunc_clock_continuation", "01010780", "reject",
         "clock varuint ends with continuation bit set"),
        ("trunc_mid_parent", "010107002801", "reject",
         "parentInfo=root then EOF before the name"),
        ("trunc_mid_origin", "010107008805", "reject",
         "origin client read, EOF before origin clock"),
        ("trunc_mid_parentsub", "01010700280101740561", "reject",
         "parentSub length 5 with 1 byte left"),
        # --- over-length declarations ------------------------------------
        ("string_overlength", "0101070004010174056868", "reject",
         "ContentString declares 5 bytes, 2 present"),
        ("any_count_huge", "0101070008010174808080808001", "reject",
         "ContentAny count 2^35 with no bodies: fail, not allocate"),
        ("numstructs_exceed_bytes", "01030700280101740161017d05",
         "reject", "3 structs declared, bytes for 1"),
        ("gc_len_huge", "0101070000808080808080800100", "reject",
         "GC run length 2^49: expansion budget, was a live hang"),
        ("deleted_len_huge",
         "01010700210101748080808080808001" + "00", "reject",
         "Deleted run length 2^49: budget, not an allocation"),
        ("skip_len_overflow", "010107000a8080808080808080804000",
         "reject", "skip length 2^69: varuint overflow must not wrap"),
        # the [2^63, 2^64) band fits a uint64 but wraps negative
        # through an int64 cast — the native codec must bound BEFORE
        # casting (found live: python rejected, native accepted with
        # clock = -2^63)
        ("client_in_wrap_band",
         "0101" "80808080808080808001" "0008010174017d0500", "reject",
         "client id 2^63 would wrap negative in a 64-bit codec"),
        ("clock_in_wrap_band",
         "010107" "80808080808080808001" "08010174017d0500", "reject",
         "start clock 2^63 would wrap negative in a 64-bit codec"),
        ("gc_len_in_wrap_band",
         "01010700" "00" "80808080808080808001" "00", "reject",
         "GC length 2^63: negative after a wrap would skip the "
         "expansion loop and accept silently"),
        ("any_int_in_wrap_band",
         "010107000801017401" "7d" "80808080808080808002" "00",
         "reject", "ContentAny varint magnitude 2^63: python would "
         "keep the bigint, a 64-bit codec would wrap it negative — "
         "same blob, different document (found live)"),
        ("origin_client_sentinel_wrap",
         "01010700" "88" "ffffffffffffffffff01" "00" "017d0500",
         "reject", "origin client 2^64-1 would wrap to the -1 "
         "'absent' sentinel — an origin-bearing row would decode as "
         "origin-free"),
        # --- hostile but well-formed: pinned accepts ---------------------
        ("numstructs_zero", "0100070000", "accept",
         "empty client group is vacuous, not an error"),
        ("skip_only_group", "010107000a0300", "accept",
         "skip-only group advances the clock, no records"),
        ("skip_len_zero", "010107000a0000", "accept",
         "zero-length skip is vacuous"),
        ("gc_len_zero", "010107000000" + "00", "accept",
         "zero-length GC run is vacuous"),
        ("dup_client_group",
         "0201070008010174017d05" + "01070008010174017d0600", "accept",
         "same client twice with colliding clocks decodes to both "
         "rows; duplicate-id arbitration is admission's job (the "
         "first admitted id wins, redeliveries drop)"),
        # --- delete-set hostiles -----------------------------------------
        ("ds_numclients_huge", "00808080808001", "reject",
         "ds numClients 2^35 with no bodies"),
        ("ds_truncated_mid_range", "000107020005", "reject",
         "2 ranges declared, EOF mid first"),
        ("ds_overlapping_ranges", "0001070200050203", "accept",
         "overlapping ranges coalesce (merge semantics)"),
        ("ds_len_overflow", "000107010580808080808080808040",
         "reject", "range length 2^69: overflow rejects in BOTH "
         "codecs (the C reader used to wrap it to 32)"),
        ("ds_len_past_clock_bound",
         "000107010580808080808080800100", "reject",
         "range end 2^49 exceeds the 2^40 wire clock bound"),
        # --- parent-field hostiles (pinned accepts) ----------------------
        ("parentinfo_2", "0101070008020174017d0500", "accept",
         "parentInfo=2 reads as the item-id arm like Yjs's boolean "
         "decode of nonzero"),
        ("parentsub_with_origin", "01010700a80500017d0500", "accept",
         "origin present: parent/parentSub fields are not read, the "
         "0x20 bit is inert (Yjs layout)"),
    ]

    def _py(self, blob):
        try:
            recs, ds = v1.decode_update(blob)
            return ("accept", len(recs), len(ds.ranges))
        except ValueError:
            return ("reject",)

    def test_matrix(self):
        from crdt_tpu.codec import native

        for name, hx, verdict, _note in self.MATRIX:
            blob = bytes.fromhex(hx)
            py = self._py(blob)
            assert py[0] == verdict, f"{name}: python={py[0]}, " \
                f"matrix says {verdict}"
            if not native.available():
                continue
            try:
                dec = native.decode_updates_columns([blob])
                nat = ("accept", len(dec["client"]))
            except ValueError:
                nat = ("reject",)
            assert nat[0] == verdict, f"{name}: native={nat[0]}, " \
                f"matrix says {verdict}"
            if verdict == "accept":
                # both accepted: unit-record counts must agree (GC
                # runs expand identically on both sides)
                assert nat[1] == py[1], f"{name}: native decoded " \
                    f"{nat[1]} rows, python {py[1]} records"

    def test_verdicts_are_exhaustive_over_outcomes(self):
        """Every entry names one of the two pinned outcomes — the
        matrix is a contract, not a survey."""
        for name, _hx, verdict, note in self.MATRIX:
            assert verdict in ("reject", "accept"), name
            assert note, name


class TestMalformedRejected:
    """Corrupt or hostile bytes must raise ValueError — never crash,
    hang, or silently misparse (the receive path isolates the blob,
    net/replica.py)."""

    def test_truncations_every_fixture(self):
        import pytest

        for blob in _ALL_REF_FIXTURES:
            for cut in (1, len(blob) // 2, len(blob) - 1):
                try:
                    v1.decode_update(blob[:cut])
                except ValueError:
                    continue
                except Exception as exc:  # noqa: BLE001
                    pytest.fail(f"wrong error {exc!r} at cut {cut}")
                # some prefixes are themselves valid updates (e.g. a
                # cut landing exactly before the delete set) — fine

    def test_unknown_struct_ref(self):
        import pytest

        bad = bytes.fromhex("01" "01" "01" "00" "1f")
        with pytest.raises(ValueError):
            v1.decode_update(bad)

    def test_huge_declared_counts(self):
        import pytest

        # numClients = 2^35 with no bodies: must fail, not allocate
        bad = bytes.fromhex("8080808080" "01")
        with pytest.raises(ValueError):
            v1.decode_update(bad)

    def test_bad_utf8_string(self):
        import pytest

        # String struct whose var_string bytes are an orphan
        # continuation byte
        bad = bytes.fromhex("01" "01" "01" "00" "04" "01" "0174" "01" "c3")
        with pytest.raises(ValueError):
            v1.decode_update(bad)

    def test_garbage_any_type_code(self):
        import pytest

        # Any content advertising type code 0x50 (not a lib0 any tag)
        bad = bytes.fromhex("01" "01" "01" "00" "08" "01" "0174" "01" "50")
        with pytest.raises(ValueError):
            v1.decode_update(bad)
