"""Incremental device replay vs the cold replay and the engine.

After EVERY round the incremental cache must equal the cold
``replay_trace`` of all blobs so far (which is itself differential-
tested against the scalar engine), across map overwrites, concurrent
appends, shared-anchor conflicts, right-bearing mid-inserts,
tombstones, redelivery, and nested collections.
"""

import numpy as np

from crdt_tpu.codec import v1
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.models import replay_trace
from crdt_tpu.models.incremental import IncrementalReplay


def _blob(recs, ds=None):
    return v1.encode_update(recs, ds or DeleteSet())


class TestIncrementalRounds:
    def test_map_rounds(self):
        inc = IncrementalReplay()
        blobs = []
        for rnd in range(4):
            recs = [
                ItemRecord(client=c, clock=rnd * 4 + j, parent_root="m",
                           key=f"k{j % 3}", content=(c, rnd, j))
                for c in (1, 2) for j in range(4)
            ]
            blobs.append(_blob(recs))
            inc.apply(blobs[-1])
            cache = inc.cache
            assert cache == replay_trace(blobs).cache, f"round {rnd}"

    def test_sequence_append_rounds(self):
        inc = IncrementalReplay()
        blobs, prev = [], {}
        for rnd in range(4):
            recs = []
            for c in (1, 2, 3):
                for j in range(5):
                    k = rnd * 5 + j
                    recs.append(ItemRecord(
                        client=c, clock=k, parent_root="lst",
                        origin=(c, prev[c]) if c in prev else None,
                        content=(c, k)))
                    prev[c] = k
            blobs.append(_blob(recs))
            inc.apply(blobs[-1])
            cache = inc.cache
            assert cache == replay_trace(blobs).cache, f"round {rnd}"

    def test_mixed_with_deletes_and_redelivery(self):
        rng = np.random.default_rng(3)
        inc = IncrementalReplay()
        blobs, clk, prev = [], {}, {}
        for rnd in range(6):
            recs, ds = [], DeleteSet()
            for c in (1, 2, 3, 4):
                for _ in range(6):
                    k = clk[c] = clk.get(c, -1) + 1
                    if rng.random() < 0.5:
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root="m",
                            key=f"x{rng.integers(0, 5)}", content=k))
                    else:
                        key = (c, rng.integers(0, 2))
                        lst = f"l{key[1]}"
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root=lst,
                            origin=(c, prev[key]) if key in prev else None,
                            content=k))
                        prev[key] = k
            if rnd >= 2:
                ds.add(1, int(rng.integers(0, clk[1])))
            blobs.append(_blob(recs, ds))
            inc.apply(blobs[-1])
            if rnd >= 1:  # redeliver an old blob: must be a no-op
                inc.apply(blobs[int(rng.integers(0, len(blobs)))])
            assert inc.cache == replay_trace(blobs).cache, f"round {rnd}"

    def test_shared_anchor_conflict_rounds(self):
        inc = IncrementalReplay()
        blobs = []
        # round 1: client 1 heads the list with anchors
        anchors = [ItemRecord(client=1, clock=j, parent_root="L",
                              content=("a", j)) for j in range(3)]
        blobs.append(_blob(anchors))
        inc.apply(blobs[-1])
        # later rounds: everyone piles onto the anchors
        for rnd, c in enumerate((2, 3, 4)):
            recs = [ItemRecord(client=c, clock=j, parent_root="L",
                               origin=(1, j % 3), content=(c, j))
                    for j in range(4)]
            blobs.append(_blob(recs))
            inc.apply(blobs[-1])
            cache = inc.cache
            assert cache == replay_trace(blobs).cache, f"round {rnd}"

    def test_right_bearing_rounds(self):
        inc = IncrementalReplay()
        blobs = []
        chain = [ItemRecord(client=1, clock=j, parent_root="t",
                            origin=(1, j - 1) if j else None, content=j)
                 for j in range(5)]
        blobs.append(_blob(chain))
        inc.apply(blobs[-1])
        for rnd, c in enumerate((2, 3)):
            # concurrent mid-inserts with right origins
            recs = [ItemRecord(client=c, clock=0, parent_root="t",
                               origin=(1, 1), right=(1, 2), content=(c, 0)),
                    ItemRecord(client=c, clock=1, parent_root="t",
                               origin=(c, 0), right=(1, 2), content=(c, 1))]
            blobs.append(_blob(recs))
            inc.apply(blobs[-1])
            cache = inc.cache
            assert cache == replay_trace(blobs).cache, f"round {rnd}"

    def test_nested_collections(self):
        from crdt_tpu.core.store import K_TYPE, TYPE_ARRAY

        inc = IncrementalReplay()
        blobs = []
        # round 1: a nested array under a map key
        recs = [
            ItemRecord(client=1, clock=0, parent_root="root", key="list",
                       kind=K_TYPE, type_ref=TYPE_ARRAY),
            ItemRecord(client=1, clock=1, parent_item=(1, 0), content="a"),
        ]
        blobs.append(_blob(recs))
        inc.apply(blobs[-1])
        assert inc.cache == replay_trace(blobs).cache
        # round 2: another client extends the nested array
        recs = [ItemRecord(client=2, clock=0, parent_item=(1, 0),
                           origin=(1, 1), content="b")]
        blobs.append(_blob(recs))
        inc.apply(blobs[-1])
        cache = inc.cache
        assert cache == replay_trace(blobs).cache
        assert cache["root"]["list"] == ["a", "b"]

    def test_child_arrives_before_parent_type(self):
        """A nested collection's rows delivered BEFORE the type item
        that parents them must surface once the parent arrives."""
        from crdt_tpu.core.store import K_TYPE, TYPE_MAP

        inc = IncrementalReplay()
        blobs = [
            # batch 1: an entry of a nested map whose parent type is
            # still unknown
            _blob([ItemRecord(client=2, clock=0, parent_item=(1, 0),
                              key="a", content=5)]),
            # batch 2: the parent type item under root "r"
            _blob([ItemRecord(client=1, clock=0, parent_root="r",
                              key="sub", kind=K_TYPE, type_ref=TYPE_MAP)]),
        ]
        inc.apply(blobs[0])
        inc.apply(blobs[1])
        cache = inc.cache
        assert cache == replay_trace(blobs).cache
        assert cache["r"]["sub"] == {"a": 5}

    def test_growth_across_capacity(self):
        inc = IncrementalReplay(capacity=64)
        blobs, prev = [], {}
        for rnd in range(4):
            recs = []
            for c in (1, 2):
                for j in range(40):
                    k = rnd * 40 + j
                    recs.append(ItemRecord(
                        client=c, clock=k, parent_root="big",
                        origin=(c, prev[c]) if c in prev else None,
                        content=k))
                    prev[c] = k
            blobs.append(_blob(recs))
            inc.apply(blobs[-1])
            cache = inc.cache
            assert cache == replay_trace(blobs).cache, f"round {rnd}"

    def test_late_small_client_relabel(self):
        inc = IncrementalReplay()
        blobs = []
        recs = [ItemRecord(client=50, clock=0, parent_root="m", key="k",
                           content="big")]
        blobs.append(_blob(recs))
        inc.apply(blobs[-1])
        # a smaller client id arrives later: dense ranks shift and the
        # resident matrix must relabel
        recs = [ItemRecord(client=7, clock=0, parent_root="m", key="k",
                           content="small")]
        blobs.append(_blob(recs))
        inc.apply(blobs[-1])
        cache = inc.cache
        assert cache == replay_trace(blobs).cache
        assert cache["m"]["k"] == "big"  # client 50 still wins

    def test_hostile_parent_cycle_terminates(self):
        """Two type items naming each other as parent must not hang
        apply() (the cold replay drops them as unrootable too)."""
        from crdt_tpu.core.store import K_TYPE, TYPE_MAP

        inc = IncrementalReplay()
        blob = _blob([
            ItemRecord(client=1, clock=0, parent_item=(2, 0), key="a",
                       kind=K_TYPE, type_ref=TYPE_MAP),
            ItemRecord(client=2, clock=0, parent_item=(1, 0), key="b",
                       kind=K_TYPE, type_ref=TYPE_MAP),
        ])
        inc.apply(blob)
        cache = inc.cache
        assert cache == replay_trace([blob]).cache

    def test_redelivered_deletes_do_not_grow(self):
        inc = IncrementalReplay()
        ds = DeleteSet()
        for k in range(10):
            ds.add(1, k)
        recs = [ItemRecord(client=1, clock=k, parent_root="m", key=f"k{k}",
                           content=k) for k in range(12)]
        blob = _blob(recs, ds)
        inc.apply(blob)
        size = len(inc._ds_ranges()[0])
        assert size == 1  # ten unit deletes coalesce to one range
        for _ in range(3):
            inc.apply(blob)  # redelivery must not grow the range set
        assert len(inc._ds_ranges()[0]) == size
        assert inc.cache == replay_trace([blob]).cache

    def test_bulk_delete_range(self):
        inc = IncrementalReplay()
        recs = [ItemRecord(client=1, clock=k, parent_root="m",
                           key=f"k{k % 7}", content=k) for k in range(50)]
        b1 = _blob(recs)
        inc.apply(b1)
        ds = DeleteSet()
        ds.add(1, 0, 45)  # one compacted range -> vectorized scan path
        b2 = _blob([], ds)
        inc.apply(b2)
        cache = inc.cache
        assert cache == replay_trace([b1, b2]).cache

    def test_out_of_order_delivery_pends_like_engine(self):
        """Batches arriving out of causal order: rows whose clock run
        has a gap (or whose origin is missing) must stay invisible
        until the gap fills — matching Engine.apply_records applied in
        the same arrival order, round by round."""
        from crdt_tpu.core.engine import Engine

        inc = IncrementalReplay()
        eng = Engine(0)
        # client 1 writes a 9-op chain + 3 map sets, split into three
        # blobs delivered newest-first
        recs = []
        prev = None
        for kk in range(9):
            recs.append(ItemRecord(client=1, clock=kk, parent_root="s",
                                   origin=prev, content=kk))
            prev = (1, kk)
        for j, kk in enumerate(range(9, 12)):
            recs.append(ItemRecord(client=1, clock=kk, parent_root="m",
                                   key=f"k{j}", content=kk))
        chunks = [recs[8:], recs[4:8], recs[:4]]  # reversed delivery
        for i, chunk in enumerate(chunks):
            blob = _blob(chunk)
            inc.apply(blob)
            rr, _ = v1.decode_update(blob)
            eng.apply_records(rr)
            assert inc.cache == eng.to_json(), f"chunk {i}"
        assert inc.cache["s"] == list(range(9))
        assert len(inc._pending) == 0

    def test_cross_client_dependency_ordering(self):
        """Client 2's insert referencing client 1's item arrives first;
        it must pend until client 1's chain shows up."""
        from crdt_tpu.core.engine import Engine

        inc = IncrementalReplay()
        eng = Engine(0)
        b2 = _blob([ItemRecord(client=2, clock=0, parent_root="s",
                               origin=(1, 1), content="late")])
        b1 = _blob([
            ItemRecord(client=1, clock=0, parent_root="s", content="a"),
            ItemRecord(client=1, clock=1, parent_root="s", origin=(1, 0),
                       content="b"),
        ])
        for i, blob in enumerate((b2, b1)):
            inc.apply(blob)
            rr, _ = v1.decode_update(blob)
            eng.apply_records(rr)
            assert inc.cache == eng.to_json(), f"blob {i}"
        assert inc.cache["s"] == ["a", "b", "late"]

    def test_random_shuffled_delivery(self):
        from crdt_tpu.core.engine import Engine

        rng = np.random.default_rng(23)
        blobs, clk, chains = [], {}, {}
        for rnd in range(10):
            recs = []
            for c in (1, 2, 3):
                for _ in range(5):
                    k = clk[c] = clk.get(c, -1) + 1
                    if rng.random() < 0.4:
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root="m",
                            key=f"q{rng.integers(0, 5)}", content=k))
                    else:
                        prev = chains.get(c)
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root="s",
                            origin=prev, content=k))
                        chains[c] = (c, k)
            blobs.append(_blob(recs))
        order = rng.permutation(len(blobs))
        inc = IncrementalReplay()
        eng = Engine(0)
        for i in order:
            inc.apply(blobs[i])
            rr, _ = v1.decode_update(blobs[i])
            eng.apply_records(rr)
            assert inc.cache == eng.to_json(), f"after blob {i}"
        assert inc.cache == replay_trace(blobs).cache
        assert len(inc._pending) == 0

    def test_random_grand_rounds(self):
        rng = np.random.default_rng(11)
        inc = IncrementalReplay()
        blobs, clk = [], {}
        own: dict = {}
        for rnd in range(8):
            recs, ds = [], DeleteSet()
            for c in (1, 2, 3):
                for _ in range(8):
                    k = clk[c] = clk.get(c, -1) + 1
                    p = rng.random()
                    if p < 0.35:
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root="m",
                            key=f"q{rng.integers(0, 6)}", content=k))
                    elif p < 0.85 or not own.get(c):
                        chain = own.setdefault(c, [])
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root="s",
                            origin=chain[-1] if chain else None,
                            content=k))
                        chain.append((c, k))
                    else:
                        chain = own[c]
                        j = int(rng.integers(0, len(chain)))
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root="s",
                            origin=chain[j - 1] if j else None,
                            right=chain[j], content=k))
                        chain.insert(j, (c, k))
            if rnd >= 3 and rng.random() < 0.6:
                ds.add(int(rng.integers(1, 4)), int(rng.integers(0, 10)))
            blobs.append(_blob(recs, ds))
            inc.apply(blobs[-1])
            assert inc.cache == replay_trace(blobs).cache, f"round {rnd}"


    def test_forced_host_device_alternation_with_rights(self):
        """The round-4 interleave: linked-chain integrate rounds (host)
        alternating with whole-segment device reconvergence, plus
        redeliveries — every transition between the incremental links
        and the wholesale orders must land on the cold replay's exact
        state (links drop on _set_order, rebuild on the next
        incremental round, stale lists materialize on read)."""
        rng = np.random.default_rng(23)
        inc = IncrementalReplay()
        blobs, clk = [], {}
        own: dict = {}
        for rnd in range(10):
            recs, ds = [], DeleteSet()
            for c in (1, 2, 3, 4):
                for _ in range(6):
                    k = clk[c] = clk.get(c, -1) + 1
                    p = rng.random()
                    chain = own.setdefault(c, [])
                    if p < 0.25:
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root="m",
                            key=f"q{rng.integers(0, 4)}", content=k))
                    elif p < 0.6 or not chain:
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root="s",
                            origin=chain[-1] if chain else None,
                            content=k))
                        chain.append((c, k))
                    else:
                        j = int(rng.integers(0, len(chain)))
                        recs.append(ItemRecord(
                            client=c, clock=k, parent_root="s",
                            origin=chain[j - 1] if j else None,
                            right=chain[j], content=k))
                        chain.insert(j, (c, k))
            if rnd >= 2 and rng.random() < 0.5:
                ds.add(int(rng.integers(1, 5)), int(rng.integers(0, 12)))
            blobs.append(_blob(recs, ds))
            # force the path per round: even rounds host (incremental
            # links), odd rounds device (wholesale reconvergence)
            inc.device_min_rows = (1 << 62) if rnd % 2 == 0 else 0
            inc.apply(blobs[-1])
            if rng.random() < 0.4:
                inc.apply(blobs[int(rng.integers(0, len(blobs)))])
            assert inc.cache == replay_trace(blobs).cache, f"round {rnd}"


def test_host_and_device_modes_converge_identically():
    """The same delta stream through forced-host rounds (pure-Python
    segment ordering, zero device work) and forced-device rounds must
    land on the identical cache — the crossover rule may pick either
    side at any time."""
    import bench as _bench  # the canonical workload generators

    base = _bench.build_trace(40, 40, seed=3)
    deltas = [
        _bench.build_trace(4, 40, seed=60 + i, client_base=900 + 4 * i,
                           map_frac=0.5)
        for i in range(3)
    ]
    from crdt_tpu.models.incremental import IncrementalReplay

    host = IncrementalReplay(capacity=1 << 13, device_min_rows=1 << 62)
    dev = IncrementalReplay(capacity=1 << 13, device_min_rows=0)
    host.apply(base)
    dev.apply(base)
    for d in deltas:
        host.apply(d)
        dev.apply(d)
    assert host.cache == dev.cache
    # and a mode FLIP mid-stream converges too (lazy tail flushes)
    flip = IncrementalReplay(capacity=1 << 13, device_min_rows=1 << 62)
    flip.apply(base)
    flip.apply(deltas[0])
    flip.device_min_rows = 0
    flip.apply(deltas[1])
    flip.device_min_rows = 1 << 62
    flip.apply(deltas[2])
    assert flip.cache == dev.cache


class TestAutoCalibration:
    """The host/device crossover default is measured per session, not
    shipped (VERDICT r3 item 2)."""

    def test_explicit_and_env_override_auto(self, monkeypatch):
        from crdt_tpu.models.incremental import IncrementalReplay

        monkeypatch.delenv("CRDT_TPU_DEVICE_MIN", raising=False)
        assert IncrementalReplay().device_min_rows is None  # AUTO
        assert IncrementalReplay(device_min_rows=7).device_min_rows == 7
        monkeypatch.setenv("CRDT_TPU_DEVICE_MIN", "123")
        assert IncrementalReplay().device_min_rows == 123

    def test_probe_yields_floored_threshold(self):
        from crdt_tpu.models.incremental import IncrementalReplay

        info = IncrementalReplay.calibration_info()
        assert info["threshold"] >= 4096  # keystroke rounds never probe
        assert info["t_interact_ms"] is not None
        # both per-row constants are MEASURED per session, recorded
        # for reproducibility (VERDICT r4 item 6)
        assert info["host_us_per_row"] is not None
        assert info["host_us_per_row"] > 0
        # a fast local backend's measured per-row transfer cost can be
        # arbitrarily small — recorded, non-negative, never required
        # to clear an arbitrary floor
        assert info["dev_us_per_row"] is not None
        assert info["dev_us_per_row"] >= 0
        # cached: the probe runs once per process
        assert IncrementalReplay.calibration_info() == info


class TestLazyCache:
    """Rounds mark segments dirty; only reads materialize the JSON
    view (the firehose steady state depends on this)."""

    def test_apply_defers_materialization(self):
        from crdt_tpu.models.incremental import IncrementalReplay

        inc = IncrementalReplay(device_min_rows=1 << 62)
        recs = [ItemRecord(client=1, clock=k, parent_root="m",
                           key=f"k{k}", content=k) for k in range(8)]
        inc.apply(_blob(recs, DeleteSet()))
        assert inc._dirty  # nothing read yet: segments pend
        assert inc.cache["m"]["k3"] == 3  # read flushes...
        assert not inc._dirty  # ...and clears the pending set

    def test_bookkeeping_without_read(self):
        """Observer bookkeeping (touched roots/keys) is computed per
        round even when nothing reads the cache."""
        from crdt_tpu.models.incremental import IncrementalReplay

        inc = IncrementalReplay(device_min_rows=1 << 62)
        recs = [ItemRecord(client=1, clock=0, parent_root="m",
                           key="a", content=1)]
        inc.apply(_blob(recs, DeleteSet()))
        assert inc.last_touched_roots == ["m"]
        assert inc.last_touched_keys == {"m": {"a"}}
        assert inc._dirty  # still unmaterialized


class TestResidentAccounting:
    """Round 15: the resident-bytes accessors the multi-doc budget
    (and a fleet capacity planner) sum per store."""

    def test_resident_bytes_tracks_growth_and_estimate_bounds(self):
        inc = IncrementalReplay()
        base = inc.resident_bytes()
        assert base > 0  # host columns exist from construction
        recs = [ItemRecord(client=1, clock=k, parent_root="m",
                           key=f"k{k % 4}", content=k)
                for k in range(3000)]
        inc.apply(_blob(recs))
        grown = inc.resident_bytes()
        assert grown > base  # host column capacity doubled past 1024
        # the pre-promotion estimate is a true upper bound of the
        # post-build footprint (the budget gate refuses BEFORE
        # building, so an under-estimate would breach the ledger)
        assert IncrementalReplay.estimate_resident_bytes(3000) >= grown

    def test_resident_columns_device_bytes(self):
        from crdt_tpu.ops.resident import COLUMNS, ResidentColumns

        rc = ResidentColumns(capacity=1 << 10)
        want = sum(
            rc.capacity * np.dtype(dt).itemsize for _, dt in COLUMNS
        )
        assert rc.device_bytes() == want
