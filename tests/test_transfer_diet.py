"""Transfer diet (round 9): narrow-column staging, donated device
buffers, and end-to-end byte accounting.

Three contracts under test:

1. **Byte-identical narrowing.** The int16 narrow-encoded staging
   matrix, decoded by the fused widening prelude, must reproduce the
   wide int32 layout EXACTLY — the converge outputs, the materialized
   cache, and the snapshot bytes are compared narrow-vs-forced-wide at
   values straddling every width boundary (int16 edges per encoding,
   clocks at 2^15-1 / 2^31-1, forced-wide fallbacks), across all three
   merge routes (one-shot, stream, fleet), including delete-only and
   empty chunks.
2. **Donation safety.** The converge dispatches donate their staged
   buffers and the fleet/gossip steps donate their packed column
   blocks; a reused executor fed a second trace must stage FRESH
   buffers and never read freed ones (back-to-back double runs,
   byte-identical).
3. **Byte accounting.** ``xfer.h2d_bytes`` growth across steady-state
   resident rounds must be DELTA-sized (the donated resident matrix
   never re-uploads), and the narrow path must ship half the wide
   path's staged bytes (``xfer.narrowed_ratio``).
"""

import numpy as np
import pytest

from crdt_tpu.codec import v1
from crdt_tpu.core.engine import Engine
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.models import replay_trace, stream_replay
from crdt_tpu.obs import Tracer, get_tracer, set_tracer
from crdt_tpu.ops import packed


@pytest.fixture
def tracer():
    old = get_tracer()
    tr = set_tracer(Tracer(enabled=True))
    try:
        yield tr
    finally:
        set_tracer(old)


# ---------------------------------------------------------------------------
# encoder/decoder unit round trips at the width boundaries
# ---------------------------------------------------------------------------


class TestNarrowEncodings:
    """Each host encoder and its device decoder must be exact
    inverses; infeasible ranges must refuse (None), never wrap."""

    @staticmethod
    def _widen(fn, arr):
        import jax.numpy as jnp

        return np.asarray(fn(jnp.asarray(arr)))

    def test_ident_boundary(self):
        # the identity sections (seq_seg/seg_off/map_key/map_root_end):
        # values in [-1, 32767] ship as-is, anything past refuses
        ok = np.asarray([-1, 0, 1, (1 << 15) - 1], np.int64)
        enc = packed._narrow_ident(ok)
        assert enc is not None and enc.dtype == np.int16
        assert (enc.astype(np.int64) == ok).all()  # identity widen
        assert packed._narrow_ident(
            np.asarray([1 << 15], np.int64)) is None
        assert packed._narrow_ident(
            np.asarray([-2], np.int64)) is None  # only -1 is a sentinel

    def test_delta_ref_boundaries(self):
        n = 10
        vals = np.full(n, -1, np.int32)
        vals[3] = 1    # delta +2
        vals[2] = 9    # delta -7 (forward reference)
        enc = packed._narrow_delta_ref(vals)
        assert enc is not None
        assert (self._widen(packed._widen_delta_ref, enc) == vals).all()
        # a self-reference collides with the no-ref sentinel: refuse
        self_ref = np.full(4, -1, np.int32)
        self_ref[2] = 2
        assert packed._narrow_delta_ref(self_ref) is None
        # a delta beyond int16: refuse
        far = np.full((1 << 15) + 8, -1, np.int64)
        far[-1] = 0  # delta = len-1 > 32767
        assert packed._narrow_delta_ref(far) is None

    def test_section_encoders_cover_every_section(self):
        # every staged section has a registered preferred encoder, and
        # the flat layout's static sizes align with the section table
        assert set(packed._SECTION_NARROW) == set(packed.SECTION_NAMES)
        sizes = packed._section_sizes(4, 8, 16)
        assert len(sizes) == len(packed.SECTION_NAMES)
        assert sizes == (8, 4, 8, 8, 12, 16, 16, 4)


# ---------------------------------------------------------------------------
# staged-plan differentials: narrow vs forced wide, boundary values
# ---------------------------------------------------------------------------


def boundary_blobs(clock_base=0, R=6, K=20, seed=4):
    """Chained map sets + list appends + right-bearing mid-inserts,
    with clocks offset to straddle a chosen width boundary."""
    rng = np.random.default_rng(seed)
    blobs = []
    for r in range(R):
        client = r + 1
        recs, chain, last = [], [], {}
        for k in range(K):
            clock = clock_base + k
            kind = int(rng.integers(0, 3))
            if kind == 0:
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root="m",
                    key=f"k{int(rng.integers(0, 5))}", content=k))
            elif kind == 1 and chain:
                j = int(rng.integers(0, len(chain)))
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root="text",
                    origin=chain[j - 1] if j > 0 else None,
                    right=chain[j], content=k))
                chain.insert(j, (client, clock))
            else:
                prev = last.get(0)
                recs.append(ItemRecord(
                    client=client, clock=clock, parent_root="l",
                    origin=(client, prev) if prev is not None else None,
                    content=k))
                last[0] = clock
                chain.append((client, clock))
        ds = DeleteSet()
        ds.add(client, clock_base + int(rng.integers(0, K)))
        blobs.append(v1.encode_update(recs, ds))
    return blobs


def _routes_identical(blobs, monkeypatch):
    """one-shot narrow == one-shot wide == stream (both) == fleet,
    cache and snapshot byte-identical."""
    monkeypatch.delenv("CRDT_TPU_WIDE_STAGING", raising=False)
    narrow = replay_trace(blobs, route="device")
    monkeypatch.setenv("CRDT_TPU_WIDE_STAGING", "1")
    wide = replay_trace(blobs, route="device")
    assert narrow.cache == wide.cache
    assert narrow.snapshot == wide.snapshot
    st_wide = stream_replay(
        blobs, chunk_blobs=2, max_shards=3, min_shard_rows=1
    )
    monkeypatch.delenv("CRDT_TPU_WIDE_STAGING", raising=False)
    st = stream_replay(
        blobs, chunk_blobs=2, max_shards=3, min_shard_rows=1
    )
    assert st.cache == narrow.cache and st.snapshot == narrow.snapshot
    assert st_wide.cache == narrow.cache
    assert st_wide.snapshot == narrow.snapshot
    from crdt_tpu.models.fleet import fleet_replay
    from crdt_tpu.parallel.gossip import make_mesh

    fl = fleet_replay(blobs, mesh=make_mesh(1))
    assert fl.cache == narrow.cache
    assert fl.snapshot == narrow.snapshot
    return narrow


class TestBoundaryDifferentials:
    def test_small_clocks_all_routes(self, monkeypatch):
        res = _routes_identical(boundary_blobs(0), monkeypatch)
        # pin against the scalar oracle too
        eng = Engine(10 ** 6)
        for b in boundary_blobs(0):
            v1.apply_update(eng, b)
        assert res.cache == eng.to_json()

    def test_clocks_straddle_int16_boundary(self, monkeypatch):
        _routes_identical(boundary_blobs((1 << 15) - 8), monkeypatch)

    def test_clocks_straddle_int31_boundary(self, monkeypatch):
        # 2^31-1 clocks: far beyond any narrow field but well under
        # the 2^40 pack_id bound — the staged path must keep them
        _routes_identical(boundary_blobs((1 << 31) - 8), monkeypatch)

    def test_delete_only_and_empty_updates(self, monkeypatch):
        ds = DeleteSet()
        ds.add(1, 3, 4)
        blobs = boundary_blobs(0, R=4, K=12) + [
            v1.encode_update([], ds),   # delete-only
            v1.encode_update([], DeleteSet()),  # empty
        ]
        _routes_identical(blobs, monkeypatch)

    def test_self_referential_origin_takes_hi_lo_section(self):
        """A row claiming itself as origin makes its chain-end slot
        point at its own position — delta 0 collides with the d16
        no-reference sentinel, so that SECTION must fall back to the
        exact hi/lo stretch pair (never decode wrong) and still
        converge like the wide path."""
        n = 6
        cols = {
            "client": np.full(n, 1, np.int64),
            "clock": np.arange(n, dtype=np.int64),
            "parent_is_root": np.ones(n, bool),
            "parent_a": np.zeros(n, np.int64),
            "parent_b": np.full(n, -1, np.int64),
            "key_id": np.zeros(n, np.int64),
            "origin_client": np.full(n, -1, np.int64),
            "origin_clock": np.full(n, -1, np.int64),
            "valid": np.ones(n, bool),
        }
        cols["origin_client"][3] = 1
        cols["origin_clock"][3] = 3  # row 3's origin is row 3
        plan = packed.stage(cols)
        assert plan is not None and plan.mat.dtype == np.int16
        by_name = dict(zip(packed.SECTION_NAMES, plan.encs))
        assert by_name["map_chain_end"] == "hilo"
        # the other map sections stay narrow
        assert by_name["map_key"] == "i16"
        assert by_name["map_root_end"] == "i16"
        res = packed.converge(plan)
        wide = packed.converge(packed.stage(cols, wide=True))
        assert list(res.win_rows) == list(wide.win_rows)

    def test_hi_lo_split_round_trips_any_int32(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        v = rng.integers(-(2 ** 31), 2 ** 31, 4096).astype(np.int32)
        v[:4] = (-1, 0, 2 ** 31 - 1, -(2 ** 31))
        hi, lo = packed._split_hi_lo(v)
        assert hi.dtype == np.int16 and lo.dtype == np.int16
        back = np.asarray(
            packed._join_hi_lo(jnp.asarray(hi), jnp.asarray(lo))
        )
        assert (back == v).all()

    def test_many_segments_keep_int16_matrix(self):
        """Past 32k segments the seg column cannot delta-narrow; the
        matrix must ship hi/lo rows for it, NOT collapse to int32 —
        this is the scale run's stream-shard shape."""
        n = 40_000
        cols = {
            "client": np.ones(n, np.int64),
            "clock": np.arange(n, dtype=np.int64),
            "parent_is_root": np.ones(n, bool),
            "parent_a": np.zeros(n, np.int64),
            "parent_b": np.full(n, -1, np.int64),
            "key_id": np.arange(n, dtype=np.int64),  # n distinct segs
            "origin_client": np.full(n, -1, np.int64),
            "origin_clock": np.full(n, -1, np.int64),
            "valid": np.ones(n, bool),
        }
        plan = packed.stage(cols)
        assert plan.mat.dtype == np.int16
        by_name = dict(zip(packed.SECTION_NAMES, plan.encs))
        # the grouped end positions overflow one int16 stretch past
        # 32k map rows; everything else stays narrow
        assert by_name["map_root_end"] == "hilo"
        assert by_name["map_key"] == "i16"
        assert "i32" not in plan.encs
        res = packed.converge(plan)
        wide = packed.converge(packed.stage(cols, wide=True))
        assert list(res.win_rows[res.win_rows >= 0]) == \
            list(wide.win_rows[wide.win_rows >= 0])

    def test_forced_wide_env_knob(self, monkeypatch):
        monkeypatch.setenv("CRDT_TPU_WIDE_STAGING", "1")
        plan = packed.stage({
            "client": np.ones(8, np.int64),
            "clock": np.arange(8, dtype=np.int64),
            "parent_is_root": np.ones(8, bool),
            "parent_a": np.zeros(8, np.int64),
            "parent_b": np.full(8, -1, np.int64),
            "key_id": np.full(8, -1, np.int64),
            "origin_client": np.full(8, -1, np.int64),
            "origin_clock": np.full(8, -1, np.int64),
            "valid": np.ones(8, bool),
        })
        assert plan is not None
        assert plan.mat.dtype == np.int32
        assert all(e == "i32" for e in plan.encs)

    def test_eager_path_narrow_matches_matrix(self):
        """stage(put=...) ships per-array narrow encodings; results
        must match the matrix-staged plan exactly."""
        from crdt_tpu.ops.device import xfer_put

        blobs = boundary_blobs(0, R=4, K=16)
        from crdt_tpu.models import replay as rp

        dec = rp.decode(blobs)
        cols, _ = rp.stage(dec)
        mat_res = packed.converge(packed.stage(cols))
        eager_plan = packed.stage(cols, put=xfer_put)
        assert eager_plan.mat is None and len(eager_plan.dev) == 3
        assert any(e in ("i16", "d16") for e in eager_plan.encs)
        eager_res = packed.converge(eager_plan)
        assert list(mat_res.win_rows) == list(eager_res.win_rows)
        assert list(mat_res.stream_row) == list(eager_res.stream_row)
        assert list(mat_res.stream_seg) == list(eager_res.stream_seg)


# ---------------------------------------------------------------------------
# donation safety
# ---------------------------------------------------------------------------


class TestDonationSafety:
    def test_stream_double_run_after_donation(self):
        """Back-to-back run_stream: every shard of the second run must
        stage fresh buffers — byte-identical results, no
        'Invalid buffer' from reading donated memory."""
        blobs = boundary_blobs(0, R=8, K=16, seed=9)
        r1 = stream_replay(
            blobs, chunk_blobs=2, max_shards=3, min_shard_rows=1
        )
        r2 = stream_replay(
            blobs, chunk_blobs=2, max_shards=3, min_shard_rows=1
        )
        assert r1.cache == r2.cache
        assert r1.snapshot == r2.snapshot

    def test_fleet_second_trace_after_donation(self):
        """A reused ReplicaFleet fed a second trace must not read the
        first round's donated column block."""
        from crdt_tpu.models.fleet import (
            fleet_for_trace,
            gather_fleet,
            load_trace,
        )
        from crdt_tpu.parallel.gossip import make_mesh

        mesh = make_mesh(1)
        tr = load_trace(boundary_blobs(0, R=4, K=12, seed=2),
                        replicas_multiple=1)
        fleet = fleet_for_trace(tr, mesh=mesh)
        out1 = fleet.step(tr.cols, tr.dels)
        out2 = fleet.step(tr.cols, tr.dels)  # same trace, fresh upload
        assert (out1.global_sv == out2.global_sv).all()
        w1 = gather_fleet(tr, out1)
        w2 = gather_fleet(tr, out2)
        assert w1[0] == w2[0] and w1[2] == w2[2]

    def test_repeat_dispatch_helper_is_undonated(self):
        """The bench sweep's probe re-dispatches one device matrix."""
        cols = {
            "client": np.ones(8, np.int64),
            "clock": np.arange(8, dtype=np.int64),
            "parent_is_root": np.ones(8, bool),
            "parent_a": np.zeros(8, np.int64),
            "parent_b": np.full(8, -1, np.int64),
            "key_id": np.full(8, -1, np.int64),
            "origin_client": np.full(8, -1, np.int64),
            "origin_clock": np.full(8, -1, np.int64),
            "valid": np.ones(8, bool),
        }
        dev, fn = packed.make_repeat_dispatch(packed.stage(cols))
        a = np.asarray(fn(dev))
        b = np.asarray(fn(dev))
        assert (a == b).all()


# ---------------------------------------------------------------------------
# byte accounting
# ---------------------------------------------------------------------------


class TestByteAccounting:
    def test_narrow_ships_half_the_wide_bytes(self, tracer):
        blobs = boundary_blobs(0, R=6, K=18, seed=5)
        from crdt_tpu.models import replay as rp

        dec = rp.decode(blobs)
        cols, _ = rp.stage(dec)

        def staged_bytes(wide):
            before = tracer.counters("xfer.").get("xfer.h2d_bytes", 0)
            packed.converge(packed.stage(cols, wide=wide))
            return tracer.counters("xfer.")["xfer.h2d_bytes"] - before

        wide_b = staged_bytes(True)
        before_staged = tracer.counters("xfer.")["xfer.staged_bytes"]
        before_saved = tracer.counters("xfer.")["xfer.h2d_bytes_saved"]
        narrow_b = staged_bytes(False)
        assert narrow_b * 2 == wide_b
        # the gauge reports shipped / PRE-diet (round-8) staging of the
        # same union — the section re-cut counts as savings too, so the
        # value is workload-shaped; pin it against the recorded
        # counters instead of a constant
        shipped = tracer.counters("xfer.")["xfer.staged_bytes"] \
            - before_staged
        saved = tracer.counters("xfer.")["xfer.h2d_bytes_saved"] \
            - before_saved
        assert shipped == narrow_b and saved > 0
        ratio = tracer.report()["gauges"]["xfer.narrowed_ratio"]
        assert ratio == round(shipped / (shipped + saved), 4)

    def test_resident_rounds_ship_delta_bytes_only(self, tracer):
        """Steady-state device rounds against the donated resident
        matrix: per-round xfer.h2d_bytes growth must be delta-sized,
        never the full matrix (the no-per-round-full-device_put
        pin)."""
        from crdt_tpu.models.incremental import IncrementalReplay

        inc = IncrementalReplay(capacity=1 << 13)
        inc.device_min_rows = 0  # force the device path every round
        base = boundary_blobs(0, R=8, K=30, seed=6)
        inc.apply(base)
        full_mat_bytes = 7 * inc._mat.shape[1] * 8

        def one_round(i):
            recs = [
                ItemRecord(client=99, clock=i * 40 + k, parent_root="m",
                           key=f"k{k % 5}", content=k)
                for k in range(40)
            ]
            blob = v1.encode_update(recs, DeleteSet())
            before = tracer.counters("xfer.").get("xfer.h2d_bytes", 0)
            inc.apply([blob])
            return tracer.counters("xfer.")["xfer.h2d_bytes"] - before

        growths = [one_round(i) for i in range(3)]
        for g in growths:
            assert 0 < g < full_mat_bytes // 2, (growths, full_mat_bytes)
        # rounds of equal delta size ship equal bytes: no creeping
        # re-upload of resident state
        assert len(set(growths)) == 1, growths

    def test_d2h_accounting_and_histograms(self, tracer):
        blobs = boundary_blobs(0, R=4, K=12, seed=7)
        replay_trace(blobs, route="device")
        rep = tracer.report()
        assert rep["counters"]["xfer.d2h_bytes"] > 0
        assert rep["counters"]["xfer.h2d_bytes"] > 0
        assert rep["spans"]["xfer.h2d"]["count"] > 0
        assert rep["spans"]["xfer.d2h"]["count"] > 0
        widths = [k for k in rep["counters"]
                  if k.startswith("xfer.col_width{")]
        assert widths, "per-column width histogram missing"
