"""Native codec vs the pure-Python reference codec.

The Python codec is the semantic reference (pinned by the hand-derived
wire fixtures); the C extension must agree with it field-for-field on
decode and byte-for-byte on encode, across every content kind.
"""

import random

import numpy as np
import pytest

from crdt_tpu.codec import native, v1
from crdt_tpu.codec.lib0 import UNDEFINED, Encoder
from crdt_tpu.core.engine import Engine
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.ops.merge import resolve_parents

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native codec toolchain unavailable"
)


def assert_matches_python(blobs):
    """C decode == Python decode(+resolve); C encode == original bytes."""
    dec = native.decode_updates_columns(blobs)
    c_records, c_ds = native.decoded_to_records(dec)

    py_records = []
    py_ds = DeleteSet()
    for blob in blobs:
        recs, d = v1.decode_update(blob)
        py_records.extend(recs)
        for c, k, length in d.iter_all():
            py_ds.add(c, k, length)
    py_records = resolve_parents(py_records)

    assert len(c_records) == len(py_records)
    for cr, pr in zip(c_records, py_records):
        assert (cr.client, cr.clock) == (pr.client, pr.clock)
        assert cr.parent_root == pr.parent_root, (cr, pr)
        assert cr.parent_item == pr.parent_item
        assert cr.key == pr.key
        assert cr.origin == pr.origin
        assert cr.right == pr.right
        assert cr.kind == pr.kind
        assert cr.content == pr.content or (
            cr.content is UNDEFINED and pr.content is UNDEFINED
        )
        if cr.kind == 6:  # K_TYPE
            assert cr.type_ref == pr.type_ref
    assert c_ds == py_ds

    # single-blob inputs: C re-encode reproduces the original bytes
    if len(blobs) == 1:
        assert native.encode_from_columns(dec) == blobs[0]
    return dec


def engine_blob(build):
    e = Engine(1)
    build(e)
    return v1.encode_state_as_update(e)


class TestDifferentialDecodeEncode:
    def test_map_doc(self):
        def build(e):
            e.map_set("users", "alice", {"age": 30, "tags": ["x", 1.5]})
            e.map_set("users", "bob", None)
            e.map_set("users", "alice", "v2")
            e.map_delete("users", "bob")

        assert_matches_python([engine_blob(build)])

    def test_seq_runs_and_deletes(self):
        def build(e):
            e.seq_insert("log", 0, list(range(40)))
            e.seq_insert("log", 10, ["mid1", "mid2"])
            e.seq_delete("log", 0, 5)

        assert_matches_python([engine_blob(build)])

    def test_nested_types(self):
        def build(e):
            from crdt_tpu.core.store import TYPE_ARRAY

            e.map_set_type("m", "list", TYPE_ARRAY)
            spec = e.map_entry_spec("m", "list")
            e.seq_insert("", 0, [1, [2, 3], {"k": "v"}], parent=spec)

        assert_matches_python([engine_blob(build)])

    def test_multi_blob_union_with_ds_merge(self):
        a, b = Engine(1), Engine(2)
        a.map_set("m", "k", "a")
        a.seq_insert("s", 0, ["x", "y"])
        a.seq_delete("s", 0, 1)
        b.map_set("m", "k", "b")
        b.map_delete("m", "k")
        blobs = [v1.encode_state_as_update(a), v1.encode_state_as_update(b)]
        dec = assert_matches_python(blobs)
        assert len(dec["client"]) == 4

    def test_any_payload_coverage(self):
        vals = [
            UNDEFINED, None, True, False, 0, -1, 63, -64, 2**40,
            -(2**40), 2**53 + 10, -(2**53) - 10, 1.5, 0.1, float(2**40),
            "", "plain", "héllo \U0001F600", b"\x00\xff\x10",
            {"a": 1, "b": [None, {"c": "d"}]}, [1, [2, [3]]],
        ]
        recs = [
            ItemRecord(client=7, clock=i, parent_root="m", key=f"k{i}",
                       content=v)
            for i, v in enumerate(vals)
        ]
        blob = v1.encode_update(recs, None)
        assert_matches_python([blob])

    def test_string_runs_with_surrogates(self):
        e = Encoder()
        e.write_var_uint(1)
        e.write_var_uint(1)
        e.write_var_uint(9)
        e.write_var_uint(0)
        e.write_uint8(v1.REF_STRING)
        e.write_var_uint(1)
        e.write_var_string("t")
        e.write_var_string("a\U0001F600bé")
        e.write_var_uint(0)
        assert_matches_python([e.to_bytes()])

    def test_gc_skip_structs(self):
        e = Encoder()
        e.write_var_uint(1)
        e.write_var_uint(3)
        e.write_var_uint(5)
        e.write_var_uint(0)
        e.write_uint8(v1.REF_GC)
        e.write_var_uint(3)
        e.write_uint8(v1.REF_SKIP)
        e.write_var_uint(4)
        e.write_uint8(v1.REF_ANY | 0x20)
        e.write_var_uint(1)
        e.write_var_string("m")
        e.write_var_string("k")
        e.write_var_uint(1)
        e.write_any("x")
        e.write_var_uint(0)
        assert_matches_python([e.to_bytes()])

    def test_format_embed_doc_type(self):
        e = Encoder()
        e.write_var_uint(1)
        e.write_var_uint(4)
        e.write_var_uint(3)
        e.write_var_uint(0)
        # ContentType (YMap ref 1) under root
        e.write_uint8(v1.REF_TYPE | 0x20)
        e.write_var_uint(1)
        e.write_var_string("root")
        e.write_var_string("sub")
        e.write_var_uint(1)
        # ContentFormat chained
        e.write_uint8(v1.REF_FORMAT | 0x80)
        e.write_var_uint(3)
        e.write_var_uint(0)
        e.write_var_string("bold")
        e.write_var_string("true")
        # ContentEmbed chained
        e.write_uint8(v1.REF_EMBED | 0x80)
        e.write_var_uint(3)
        e.write_var_uint(1)
        e.write_var_string('{"img": "x.png"}')
        # ContentDoc chained
        e.write_uint8(v1.REF_DOC | 0x80)
        e.write_var_uint(3)
        e.write_var_uint(2)
        e.write_var_string("guid-1")
        e.write_any({"autoLoad": True})
        e.write_var_uint(0)
        assert_matches_python([e.to_bytes()])

    def test_foreign_fixtures(self):
        from tests.test_yjs_fixtures import (
            FIX_ANY_EDGE,
            FIX_MAP_SET,
            FIX_NESTED,
            FIX_TEXT_GC,
        )

        for blob in (FIX_MAP_SET, FIX_TEXT_GC, FIX_NESTED, FIX_ANY_EDGE):
            assert_matches_python([blob])

    def test_fuzz_engine_docs(self):
        from tests.test_engine import _random_op

        rng = random.Random(99)
        for _ in range(5):
            engines = [Engine(i + 1) for i in range(3)]
            for _ in range(60):
                _random_op(rng, rng.choice(engines), engines)
            for e in engines:
                for o in engines:
                    if o is not e:
                        v1.apply_update(e, v1.encode_state_as_update(o))
            blob = v1.encode_state_as_update(engines[0])
            assert_matches_python([blob])


class TestSeededDifferentialFuzz:
    """Random hostile byte streams against BOTH decoders: identical
    accept/reject decisions and identical decoded records on accept —
    the swarm-split property (a wire blob must never divide a mixed
    py/native swarm into two groups holding different documents). The
    corpus is fully seeded, so a divergence reproduces from its seed
    (VERDICT r4 item 7: the fuzz extension of the hand-built
    adversarial rejection matrix)."""

    @staticmethod
    def _both_decode(blob: bytes, ctx: str):
        try:
            recs_p, ds_p = v1.decode_update(blob)
            ok_p = True
        except Exception:
            ok_p, recs_p, ds_p = False, None, None
        try:
            dec = native.decode_updates_columns([blob])
            ok_c = True
        except Exception:
            ok_c, dec = False, None
        assert ok_p == ok_c, (
            f"{ctx}: decoders disagree on acceptance "
            f"(python={'accept' if ok_p else 'reject'}, "
            f"native={'accept' if ok_c else 'reject'}) "
            f"blob={blob.hex()}"
        )
        if not ok_p:
            return
        c_records, c_ds = native.decoded_to_records(dec)
        py_records = resolve_parents(recs_p)
        assert len(c_records) == len(py_records), ctx
        for cr, pr in zip(c_records, py_records):
            assert (cr.client, cr.clock) == (pr.client, pr.clock), ctx
            assert cr.parent_root == pr.parent_root, ctx
            assert cr.parent_item == pr.parent_item, ctx
            assert cr.key == pr.key, ctx
            assert cr.origin == pr.origin, ctx
            assert cr.right == pr.right, ctx
            assert cr.kind == pr.kind, ctx
            assert cr.content == pr.content or (
                cr.content is UNDEFINED and pr.content is UNDEFINED
            ), ctx
        assert c_ds == ds_p, ctx

    @staticmethod
    def _valid_blob(seed: int) -> bytes:
        from tests.test_engine import _random_op

        rng = random.Random(seed)
        engines = [Engine(i + 1) for i in range(3)]
        for _ in range(40):
            _random_op(rng, rng.choice(engines), engines)
        for e in engines:
            for o in engines:
                if o is not e:
                    v1.apply_update(e, v1.encode_state_as_update(o))
        return v1.encode_state_as_update(engines[0])

    def test_random_bytes(self):
        rng = random.Random(1234)
        for i in range(400):
            blob = rng.randbytes(rng.randint(1, 200))
            self._both_decode(blob, f"random[{i}]")

    def test_bit_flip_mutants(self):
        base = self._valid_blob(7)
        rng = random.Random(4321)
        for i in range(400):
            mut = bytearray(base)
            for _ in range(rng.randint(1, 3)):
                pos = rng.randrange(len(mut))
                mut[pos] ^= 1 << rng.randrange(8)
            self._both_decode(bytes(mut), f"flip[{i}]")

    def test_truncations(self):
        base = self._valid_blob(11)
        step = max(1, len(base) // 120)
        for cut in range(0, len(base), step):
            self._both_decode(base[:cut], f"trunc[{cut}]")

    def test_spliced_headers(self):
        """Structurally plausible hostility: valid prefixes spliced
        with random varuint-shaped tails (big counts, giant clocks,
        shifted info bytes)."""
        base = self._valid_blob(13)
        rng = random.Random(999)
        for i in range(200):
            cut = rng.randrange(1, len(base))
            tail = bytearray()
            for _ in range(rng.randint(1, 12)):
                v = rng.choice([
                    rng.randrange(0, 128),
                    rng.randrange(0, 1 << 20),
                    (1 << 40) - 1, (1 << 40), (1 << 62), (1 << 63) - 1,
                ])
                while True:  # varuint
                    b = v & 0x7F
                    v >>= 7
                    tail.append(b | (0x80 if v else 0))
                    if not v:
                        break
            self._both_decode(base[:cut] + bytes(tail), f"splice[{i}]")


class TestMalformed:
    def test_truncated(self):
        with pytest.raises(ValueError):
            native.decode_updates_columns([b"\x01"])

    def test_trailing_bytes(self):
        with pytest.raises(ValueError):
            native.decode_updates_columns([b"\x00\x00\xff"])

    def test_unknown_ref(self):
        e = Encoder()
        e.write_var_uint(1)
        e.write_var_uint(1)
        e.write_var_uint(1)
        e.write_var_uint(0)
        e.write_uint8(31)
        with pytest.raises(ValueError):
            native.decode_updates_columns([e.to_bytes()])

    def test_empty_update(self):
        dec = native.decode_updates_columns([b"\x00\x00"])
        assert len(dec["client"]) == 0
        assert native.encode_from_columns(dec) == b"\x00\x00"


class TestKernelColumns:
    def test_matches_records_to_columns(self):
        from crdt_tpu.ops.merge import Interner, records_to_columns

        def build(e):
            e.map_set("m", "k1", 1)
            e.map_set("m", "k2", 2)
            e.seq_insert("l", 0, ["a", "b"])

        blob = engine_blob(build)
        dec = native.decode_updates_columns([blob])
        cols = native.kernel_columns(dec)

        recs = resolve_parents(v1.decode_update(blob)[0])
        interner = Interner()
        ref = records_to_columns(recs, interner, pad=len(recs))
        # same interning order (first-appearance) -> identical columns
        np.testing.assert_array_equal(cols["client"], ref["client"])
        np.testing.assert_array_equal(cols["clock"], ref["clock"])
        np.testing.assert_array_equal(
            cols["parent_is_root"], ref["parent_is_root"]
        )
        np.testing.assert_array_equal(cols["parent_a"], ref["parent_a"])
        np.testing.assert_array_equal(cols["key_id"], ref["key_id"])
        np.testing.assert_array_equal(
            cols["origin_client"], ref["origin_client"]
        )

    def test_bytearray_and_memoryview_inputs(self):
        e = Engine(1)
        e.map_set("m", "k", 1)
        blob = v1.encode_state_as_update(e)
        for wrap in (bytearray, memoryview):
            dec = native.decode_updates_columns([wrap(blob)])
            assert len(dec["client"]) == 1

    def test_float_out_of_f32_range(self):
        """1e300 is a legal f64 payload; both codecs must encode it
        (the Python oracle's f32 probe used to OverflowError)."""
        recs = [ItemRecord(client=1, clock=0, parent_root="m", key="k",
                           content=[1e300, -1e300, 1.5])]
        blob = v1.encode_update(recs, None)
        assert_matches_python([blob])

    def test_unresolvable_parent_keeps_merge_sentinels(self):
        """Rows whose origin lies outside the batch have NO parent;
        kernel_columns must emit the same -2 sentinels as
        records_to_columns or segment keys diverge."""
        from crdt_tpu.codec.lib0 import Encoder as E0
        from crdt_tpu.ops.merge import Interner, records_to_columns

        e = E0()
        e.write_var_uint(1)
        e.write_var_uint(1)
        e.write_var_uint(9)
        e.write_var_uint(5)
        e.write_uint8(v1.REF_ANY | 0x80)  # origin present, outside batch
        e.write_var_uint(3)               # origin (3, 7) — unknown
        e.write_var_uint(7)
        e.write_var_uint(1)
        e.write_any("orphan")
        e.write_var_uint(0)
        blob = e.to_bytes()
        dec = native.decode_updates_columns([blob])
        cols = native.kernel_columns(dec)
        recs = resolve_parents(v1.decode_update(blob)[0])
        ref = records_to_columns(recs, Interner(), pad=len(recs))
        np.testing.assert_array_equal(cols["parent_a"], ref["parent_a"])
        np.testing.assert_array_equal(cols["parent_b"], ref["parent_b"])
        assert cols["parent_a"][0] == -2 and cols["parent_b"][0] == -2


def test_engine_columns_snapshot_byte_identical():
    """Full-state encodes route through the engine's SoA columns and
    the native encoder (v1.encode_state_as_update, sv=None); the bytes
    must equal the Python record-walk encode exactly — compaction
    snapshots are interchangeable between the two paths."""
    rng = random.Random(7)
    eng = Engine(1)
    peers = [Engine(c) for c in (2, 3)]
    for e in [eng] + peers:
        for i in range(120):
            roll = rng.random()
            if roll < 0.5:
                e.map_set("m", f"k{rng.randrange(12)}", rng.randrange(99))
            elif roll < 0.8:
                e.seq_insert("L", rng.randrange(e.seq_len("L") + 1), [i])
            elif e.seq_len("L"):
                e.seq_delete("L", rng.randrange(e.seq_len("L")), 1)
    # cross-apply so the store holds multi-client interleaved state
    for e in peers:
        eng.apply_records(e.records_since(), e.delete_set())

    native_bytes = v1.encode_state_as_update(eng)
    py_bytes = v1.encode_update(eng.records_since(), eng.delete_set())
    assert native_bytes == py_bytes
    # a FRESH requester's decoded (empty) state vector takes the same
    # native path and yields the same bytes
    from crdt_tpu.core.ids import StateVector

    assert v1.encode_state_as_update(eng, StateVector({})) == py_bytes
    # and the snapshot replays to the same document
    fresh = Engine(99)
    fresh.apply_records(*v1.decode_update(native_bytes))
    assert fresh.map_json("m") == eng.map_json("m")
    assert fresh.seq_json("L") == eng.seq_json("L")


def test_fuzz_all_content_kinds_both_ways():
    """Random record unions drawing from ALL TEN content kinds (plus
    Skip gaps from partial clock ranges): Python encode -> C decode
    must equal Python decode, and the C re-encode must reproduce the
    Python bytes exactly. The engine fuzz above only reaches the kinds
    engine ops emit; this covers the full wire surface."""
    from crdt_tpu.codec.lib0 import UNDEFINED
    from crdt_tpu.core.store import (
        K_ANY, K_BINARY, K_DELETED, K_DOC, K_EMBED, K_FORMAT, K_GC,
        K_JSON, K_STRING, K_TYPE,
    )

    rng = random.Random(424242)

    def rand_any(depth=0):
        roll = rng.random()
        if depth < 2 and roll < 0.15:
            return {f"k{i}": rand_any(depth + 1) for i in range(rng.randrange(3))}
        if depth < 2 and roll < 0.3:
            return [rand_any(depth + 1) for _ in range(rng.randrange(3))]
        return rng.choice([
            None, True, False, rng.randrange(-9999, 9999),
            rng.random(), "s" * rng.randrange(4), UNDEFINED,
        ])

    def rand_content(kind):
        if kind == K_JSON:
            return rng.choice([{"a": 1}, [1, 2], "x", 3, None, UNDEFINED])
        if kind == K_BINARY:
            return bytes(rng.randrange(256) for _ in range(rng.randrange(1, 6)))
        if kind == K_STRING:
            # single UTF-16 code units (astral pairs are covered by
            # the dedicated surrogate-run test)
            return rng.choice(["a", "é", "ß", "☃"])
        if kind == K_EMBED:
            return {"e": rng.randrange(9)}
        if kind == K_FORMAT:
            return (rng.choice(["b", "i"]), rng.choice([True, None, "x"]))
        if kind == K_DOC:
            return (f"g{rng.randrange(9)}", {"autoLoad": True})
        return rand_any()

    kinds = [K_GC, K_DELETED, K_JSON, K_BINARY, K_STRING, K_ANY,
             K_EMBED, K_FORMAT, K_DOC, K_TYPE]
    for trial in range(12):
        records = []
        ds = DeleteSet()
        for client in rng.sample(range(1, 200), rng.randrange(1, 5)):
            clock = 0
            ids = []
            for _ in range(rng.randrange(1, 12)):
                if rng.random() < 0.15:
                    clock += rng.randrange(1, 5)  # Skip gap on the wire
                kind = rng.choice(kinds)
                origin = rng.choice([None] + ids[-3:]) if ids else None
                right = (
                    rng.choice([None] + ids[-2:])
                    if ids and rng.random() < 0.3 else None
                )
                kw = dict(client=client, clock=clock, kind=kind)
                if kind != K_GC:
                    if origin is None and right is None:
                        if rng.random() < 0.5:
                            kw.update(parent_root=f"r{rng.randrange(3)}")
                        else:
                            kw.update(parent_item=(client, max(clock - 1, 0)))
                        if rng.random() < 0.4 and kind != K_TYPE:
                            kw.update(key=f"key{rng.randrange(4)}")
                    else:
                        kw.update(origin=origin, right=right)
                if kind == K_TYPE:
                    kw.update(type_ref=rng.randrange(2))
                elif kind not in (K_GC, K_DELETED):
                    kw.update(content=rand_content(kind))
                records.append(ItemRecord(**kw))
                ids.append((client, clock))
                clock += 1
            if ids and rng.random() < 0.5:
                c, k = rng.choice(ids)
                ds.add(c, k, 1)
        blob = v1.encode_update(records, ds)
        assert_matches_python([blob])
        dec = native.decode_updates_columns([blob])
        assert native.encode_from_columns(dec) == blob, f"trial {trial}"
