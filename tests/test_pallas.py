"""Differential tests: Pallas kernels vs their jnp twins.

The suite runs on the CPU mesh (tests/conftest.py), so the kernels
execute in interpreter mode — the same kernel bodies that compile via
Mosaic on TPU (verified on hardware; bench.py exercises the compiled
path). Each test drives the pallas function directly against the pure
jnp implementation on identical inputs.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_tpu.ops import pallas_kernels as pk


@pytest.fixture(autouse=True)
def _force_interpret(monkeypatch):
    monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")


def _jnp_ds_mask(*args):
    """The REAL searchsorted path of deleteset.apply_mask, reached by
    pinning the dispatch env var (no hand-inlined copy to drift)."""
    from crdt_tpu.ops import deleteset

    saved = os.environ.get("CRDT_TPU_PALLAS")
    os.environ["CRDT_TPU_PALLAS"] = "0"
    try:
        return deleteset.apply_mask(*args)
    finally:
        if saved is None:
            os.environ.pop("CRDT_TPU_PALLAS", None)
        else:
            os.environ["CRDT_TPU_PALLAS"] = saved


def _random_ds_case(rng, n, d, num_clients=40, max_clock=2000):
    """Items plus a NORMALIZED delete set (sorted-disjoint ranges per
    client — the DeleteSet invariant both kernels assume; the
    searchsorted path is free to give different answers on overlapping
    ranges, which the engine never produces)."""
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.ops.deleteset import ranges_to_device

    client = rng.integers(0, num_clients, n).astype(np.int32)
    clock = rng.integers(0, max_clock, n).astype(np.int64)
    valid = rng.random(n) < 0.9
    dset = DeleteSet()
    for _ in range(d):
        dset.add(
            int(rng.integers(0, num_clients)),
            int(rng.integers(0, max_clock)),
            int(rng.integers(1, 64)),
        )
    dset.normalize()
    dc, ds, de = ranges_to_device(dset)
    # keep at least one range and requested-size padding with nulls
    dc = np.asarray(list(dc) + [-1] * (d - len(dc)), np.int32)[:d]
    ds = np.asarray(list(ds) + [-1] * (d - len(ds)), np.int64)[:d]
    de = np.asarray(list(de) + [-1] * (d - len(de)), np.int64)[:d]
    return tuple(jnp.asarray(x) for x in (client, clock, valid, dc, ds, de))


@pytest.mark.parametrize("n,d", [(1, 1), (100, 3), (1000, 64), (8192, 200), (5000, 1)])
def test_ds_mask_matches_searchsorted(n, d):
    rng = np.random.default_rng(n * 1000 + d)
    args = _random_ds_case(rng, n, d)
    ref = _jnp_ds_mask(*args)
    got = pk.ds_mask(*args)
    assert bool(jnp.all(ref == got))


def test_ds_mask_null_padded_ranges_match_nothing():
    # bucket padding in merge_records fills ranges with (-1, -1, -1)
    client = jnp.asarray(np.array([0, 1, 2], np.int32))
    clock = jnp.asarray(np.array([0, 5, 7], np.int64))
    valid = jnp.ones(3, bool)
    dc = jnp.asarray(np.array([1, -1, -1], np.int32))
    ds = jnp.asarray(np.array([5, -1, -1], np.int64))
    de = jnp.asarray(np.array([6, -1, -1], np.int64))
    got = np.asarray(pk.ds_mask(client, clock, valid, dc, ds, de))
    assert got.tolist() == [False, True, False]


def test_ds_mask_invalid_rows_stay_false():
    client = jnp.asarray(np.array([3, 3], np.int32))
    clock = jnp.asarray(np.array([10, 10], np.int64))
    valid = jnp.asarray(np.array([True, False]))
    dc = jnp.asarray(np.array([3], np.int32))
    ds = jnp.asarray(np.array([0], np.int64))
    de = jnp.asarray(np.array([100], np.int64))
    got = np.asarray(pk.ds_mask(client, clock, valid, dc, ds, de))
    assert got.tolist() == [True, False]


def test_ds_mask_range_budget_enforced():
    rng = np.random.default_rng(0)
    args = _random_ds_case(rng, 16, pk._DS_MAX_RANGES + 1)
    with pytest.raises(ValueError, match="SMEM budget"):
        pk.ds_mask(*args)


def _jnp_missing(svs):
    deficit = jnp.maximum(svs[:, None, :] - svs[None, :, :], 0)
    return deficit.sum(axis=-1)


@pytest.mark.parametrize("r,c", [(1, 1), (2, 7), (9, 130), (130, 64), (16, 256)])
def test_sv_deficit_matches_jnp(r, c):
    rng = np.random.default_rng(r * 1000 + c)
    svs = jnp.asarray(rng.integers(0, 100000, (r, c)).astype(np.int64))
    ref = _jnp_missing(svs)
    got = pk.sv_deficit(svs)
    assert got.dtype == svs.dtype
    assert bool(jnp.all(ref == got))


def test_sv_deficit_zero_and_identical_rows():
    svs = jnp.asarray(np.zeros((4, 12), np.int64))
    assert bool(jnp.all(pk.sv_deficit(svs) == 0))
    svs = jnp.asarray(np.tile(np.arange(12, dtype=np.int64), (4, 1)))
    assert bool(jnp.all(pk.sv_deficit(svs) == 0))


def test_ds_mask_exact_beyond_int32():
    """Clocks past 2**31 (the framework allows < 2**40): the hi/lo
    split compares must not truncate — a range straddling the int32
    boundary was the review repro that a plain i32 cast got wrong."""
    big = 2**31
    client = jnp.asarray(np.array([1, 1, 1], np.int32))
    clock = jnp.asarray(np.array([big, big - 10, 2**39], np.int64))
    valid = jnp.ones(3, bool)
    dc = jnp.asarray(np.array([1, 1], np.int32))
    ds = jnp.asarray(np.array([big - 5, 2**39 - 1], np.int64))
    de = jnp.asarray(np.array([big + 5, 2**39 + 1], np.int64))
    got = np.asarray(pk.ds_mask(client, clock, valid, dc, ds, de))
    assert got.tolist() == [True, False, True]
    ref = np.asarray(_jnp_ds_mask(client, clock, valid, dc, ds, de))
    assert got.tolist() == ref.tolist()


def test_sv_deficit_exact_beyond_int32():
    """Absolute clocks past 2**31 with small spreads: the per-column
    centering must keep the i32 kernel exact (the review repro showed
    a plain cast flipping the anti-entropy plan's direction)."""
    big = 2**31
    svs = jnp.asarray(
        np.array([[big + 10, 2**39], [0 + big, 2**39 + 7]], np.int64)
    )
    ref = _jnp_missing(svs)
    got = pk.sv_deficit(svs)
    assert bool(jnp.all(ref == got))
    assert int(got[0, 1]) == 10 and int(got[1, 0]) == 7


def test_dispatch_respects_env(monkeypatch):
    monkeypatch.setenv("CRDT_TPU_PALLAS", "0")
    assert not pk.use_pallas()
    monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")
    assert pk.use_pallas() and pk._interpret()
    monkeypatch.setenv("CRDT_TPU_PALLAS", "auto")
    assert pk.use_pallas() == (jax.default_backend() == "tpu")


def test_apply_mask_dispatch_equivalence(monkeypatch):
    """deleteset.apply_mask gives identical answers through both paths."""
    from crdt_tpu.ops import deleteset

    rng = np.random.default_rng(7)
    args = _random_ds_case(rng, 3000, 50)
    monkeypatch.setenv("CRDT_TPU_PALLAS", "0")
    ref = deleteset.apply_mask(*args)
    monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")
    got = deleteset.apply_mask(*args)
    assert bool(jnp.all(ref == got))


def test_missing_dispatch_equivalence(monkeypatch):
    from crdt_tpu.ops import statevec

    rng = np.random.default_rng(8)
    svs = jnp.asarray(rng.integers(0, 5000, (10, 40)).astype(np.int64))
    monkeypatch.setenv("CRDT_TPU_PALLAS", "0")
    ref = statevec.missing(svs)
    monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")
    got = statevec.missing(svs)
    assert bool(jnp.all(ref == got))


def test_exact_missing_matches_dense():
    from crdt_tpu.ops import statevec

    rng = np.random.default_rng(9)
    svs = jnp.asarray(rng.integers(0, 5000, (13, 29)).astype(np.int64))
    assert bool(jnp.all(statevec.exact_missing(svs) == _jnp_missing(svs)))


def test_sv_deficit_overflow_falls_back_exact():
    """Spreads past 2**31 (one replica lagging another by >2e9 clocks
    on one client) must take the exact int64 path, not wrap in i32."""
    lag = 2**31 + 12345
    svs = jnp.asarray(np.array([[lag, 5], [0, 5], [7, 5]], np.int64))
    got = pk.sv_deficit(svs)
    ref = _jnp_missing(svs)
    assert bool(jnp.all(got == ref))
    assert int(got[0, 1]) == lag  # the value an i32 kernel would wrap


def test_apply_mask_crossover_uses_jnp_for_large_d(monkeypatch):
    """Dispatch sends D > _DS_PALLAS_CROSSOVER to the searchsorted
    path even when pallas is enabled (the SMEM cap is not the
    performance crossover)."""
    from crdt_tpu.ops import deleteset

    calls = []
    real = pk.ds_mask_static
    monkeypatch.setattr(
        pk, "ds_mask_static",
        lambda *a, **kw: calls.append(1) or real(*a, **kw),
    )
    rng = np.random.default_rng(11)
    big = _random_ds_case(rng, 256, pk._DS_PALLAS_CROSSOVER + 1)
    small = _random_ds_case(rng, 256, pk._DS_PALLAS_CROSSOVER)
    monkeypatch.setenv("CRDT_TPU_PALLAS", "interpret")
    deleteset.apply_mask(*big)
    assert not calls
    deleteset.apply_mask(*small)
    assert calls
