"""ReplicaFleet — flagship batched convergence model on the CPU mesh."""

import numpy as np
import pytest

from crdt_tpu.models import FleetStep, ReplicaFleet
from crdt_tpu.utils import Tracer, get_tracer, set_tracer


def test_fleet_step_shapes_and_handshake():
    fleet = ReplicaFleet(8, 16, n_devices=4, num_clients=10, num_segments=256)
    cols, dels = fleet.synth(num_maps=2, keys_per_map=8)
    out = fleet.step(cols, dels)
    assert isinstance(out, FleetStep)
    assert out.sv_local.shape == (8, 10)
    assert out.global_sv.shape == (10,)
    assert all(out.global_sv[r + 1] == 16 for r in range(8))
    assert out.deficit.shape == (8, 8)
    assert out.deficit[0][0] == 0 and out.deficit[0][1] == 16
    assert (out.winners >= 0).sum() > 0


def test_fleet_winners_match_scalar_oracle():
    """The fleet's converged LWW winners equal the host engine's on the
    same op set (differential test at the model level)."""
    from crdt_tpu.core.engine import Engine
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    fleet = ReplicaFleet(4, 8, n_devices=4, num_clients=8, num_segments=64)
    cols, dels = fleet.synth(num_maps=2, keys_per_map=4, seed=3)

    # replay the identical ops through the scalar engine
    records = []
    R, N = cols["client"].shape
    for r in range(R):
        for k in range(N):
            records.append(
                ItemRecord(
                    client=int(cols["client"][r, k]),
                    clock=int(cols["clock"][r, k]),
                    parent_root=f"m{int(cols['parent_a'][r, k])}",
                    key=f"k{int(cols['key_id'][r, k])}",
                    content=0,
                )
            )
    eng = Engine(0)
    eng.apply_records(records, DeleteSet())
    oracle = eng.map_winner_table()

    out = fleet.step(cols, dels)
    # map winner ids: reconstruct from flattened op order
    flat_client = cols["client"].reshape(-1)
    flat_clock = cols["clock"].reshape(-1)
    got = {}
    # fleet orders ops by sorted packed id; winners index into that order
    order = np.lexsort((flat_clock, flat_client))
    for w, vis in zip(out.winners, out.winner_visible):
        if w < 0 or w >= len(order):
            continue
        i = order[w]
        r, k = divmod(int(i), N)
        key = (("root", f"m{int(cols['parent_a'][r, k])}"),
               f"k{int(cols['key_id'][r, k])}")
        got[key] = ((int(flat_client[i]), int(flat_clock[i])), bool(vis))
    assert got == oracle


def test_fleet_rejects_uneven_sharding():
    with pytest.raises(ValueError, match="divide"):
        ReplicaFleet(5, 8, n_devices=4)


def test_fleet_traces_step():
    old = get_tracer()
    tr = set_tracer(Tracer(enabled=True))
    try:
        fleet = ReplicaFleet(4, 4, n_devices=2, num_clients=6, num_segments=64)
        cols, dels = fleet.synth(num_maps=1, keys_per_map=4)
        fleet.step(cols, dels)
        rep = tr.report()
        assert rep["spans"]["fleet.step"]["count"] == 1
        assert rep["counters"]["fleet.ops_converged"] == 16
    finally:
        set_tracer(old)


class TestTraceReplay:
    """BASELINE config #5 as a product API: trace replay + snapshot
    compaction through the firehose path, differential against the
    scalar document."""

    def _trace(self, n_peers=6, ops=12):
        from crdt_tpu.api.doc import Crdt

        blobs = []
        docs = []
        for i in range(n_peers):
            out = []
            d = Crdt(i + 1, on_update=lambda u, m: out.append(u))
            docs.append((d, out))
        for i, (d, out) in enumerate(docs):
            for k in range(ops):
                if k % 3 == 0:
                    d.push("log", [f"p{i}.{k}"])
                else:
                    d.set("m", f"k{(i * ops + k) % 10}", i * ops + k)
            # nested array under a map key (each doc creates its own;
            # LWW shadows all but one — the replay must agree with
            # the scalar document on which one and on its contents)
            d.set("nested", "l", f"n{i}", array_method="push")
            d.set("nested", "l", f"m{i}", array_method="push")
            d.delete("m", f"k{i % 10}")
            blobs.extend(out)
        return blobs

    def test_replay_matches_scalar_document(self):
        from crdt_tpu.api.doc import Crdt
        from crdt_tpu.models.replay import replay_trace

        blobs = self._trace()
        res = replay_trace(blobs)

        oracle = Crdt(999)
        oracle.apply_updates(blobs)
        assert res.cache == dict(oracle.c)
        assert res.n_ops > 0

        # the compacted snapshot alone rebuilds the same state
        fresh = Crdt(998)
        fresh.apply_update(res.snapshot)
        assert dict(fresh.c) == res.cache

    def test_replay_empty_and_single(self):
        from crdt_tpu.models.replay import replay_trace

        res = replay_trace([])
        assert res.cache == {} and res.n_ops == 0

        from crdt_tpu.api.doc import Crdt

        out = []
        d = Crdt(5, on_update=lambda u, m: out.append(u))
        d.set("solo", "k", [1, 2])
        res = replay_trace(out)
        assert res.cache == {"solo": {"k": [1, 2]}}

    def test_fully_tombstoned_root_still_materializes_empty(self):
        from crdt_tpu.api.doc import Crdt
        from crdt_tpu.models.replay import replay_trace

        out = []
        d = Crdt(3, on_update=lambda u, m: out.append(u))
        d.set("gone", "k", 1)
        d.delete("gone", "k")
        d.set("other", "x", 2)
        res = replay_trace(out)
        oracle = Crdt(99)
        oracle.apply_updates(out)
        assert res.cache == dict(oracle.c)
        assert res.cache["gone"] == {}


class TestFleetIntegration:
    def test_fleet_on_2d_mesh_matches_1d(self):
        """ReplicaFleet accepts a (hosts, replicas) mesh and produces
        the flat mesh's exact outputs."""
        from crdt_tpu.models import ReplicaFleet
        from crdt_tpu.parallel.gossip import make_mesh2d

        R, N = 16, 16
        flat = ReplicaFleet(R, N, n_devices=8, num_clients=R + 2,
                            num_segments=256)
        cols, dels = flat.synth(num_maps=2, keys_per_map=8, num_lists=2)
        out1 = flat.step(cols, dels)

        hier = ReplicaFleet(R, N, mesh=make_mesh2d(2, 4),
                            num_clients=R + 2, num_segments=256)
        out2 = hier.step(cols, dels)
        import numpy as np

        for name, a, b in zip(out1._fields, out1, out2):
            np.testing.assert_array_equal(a, b, err_msg=name)

    def test_fleet_delta_round(self):
        """The targeted anti-entropy round is reachable straight from
        the fleet: needed counts equal the per-replica fresh rows."""
        import numpy as np

        from crdt_tpu.models import ReplicaFleet
        from crdt_tpu.parallel.delta import synth_resident_columns

        fleet = ReplicaFleet(8, 104, n_devices=8, num_clients=10,
                             num_segments=256)
        cols = synth_resident_columns(8, 96, 8, seed=4)
        svs, deficit, needed, delta = fleet.delta_round(cols, budget=16)
        np.testing.assert_array_equal(needed, np.full(8, 8))
        assert len(delta["client"]) == 8 * 16  # R * budget, not R * N
        assert deficit[0, 1] == 8

    def test_replay_handles_prepends_and_inserts(self):
        """Honest right origins (multi-client prepends, mid-inserts)
        must replay to the document's exact order — the append-only
        kernel path hands those sequences to the host machinery."""
        from crdt_tpu.api.doc import Crdt
        from crdt_tpu.models.replay import replay_trace

        out1, out2 = [], []
        a = Crdt(1, on_update=lambda u, m: out1.append(u))
        b = Crdt(2, on_update=lambda u, m: out2.append(u))
        a.push("l", ["base1", "base2"])
        for u in out1:
            b.apply_update(u)
        b.unshift("l", "pre")
        b.insert("l", 2, "mid")
        a.insert("l", 1, "amid")
        blobs = out1 + out2
        res = replay_trace(blobs)
        oracle = Crdt(9)
        oracle.apply_updates(blobs)
        assert res.cache == dict(oracle.c), (res.cache, dict(oracle.c))
        fresh = Crdt(8)
        fresh.apply_update(res.snapshot)
        assert dict(fresh.c) == res.cache

    def test_replay_redelivered_blobs_with_map_rights(self):
        """Duplicate delivery of a blob containing crafted map rights
        must not drop keys (the dedup inside the scalar fallback)."""
        from crdt_tpu.api.doc import Crdt
        from crdt_tpu.codec import v1
        from crdt_tpu.core.records import ItemRecord
        from crdt_tpu.models.replay import replay_trace

        blob = v1.encode_update([
            ItemRecord(client=1, clock=0, parent_root="m", key="k",
                       content="A"),
            ItemRecord(client=2, clock=0, parent_root="m", key="k",
                       right=(1, 0), content="B"),
        ], None)
        res = replay_trace([blob, blob])  # at-least-once redelivery
        oracle = Crdt(9)
        oracle.apply_updates([blob, blob])
        assert res.cache == dict(oracle.c)
        assert res.cache["m"]["k"] == "A"
        # the compacted snapshot from the redelivered trace rebuilds
        # the same state (duplicate rows must not corrupt the encode)
        fresh = Crdt(8)
        fresh.apply_update(res.snapshot)
        assert dict(fresh.c) == res.cache

    def test_redelivered_interactive_trace_snapshot(self):
        """Prepends + inserts + cuts + a redelivered prefix: replay and
        its compacted snapshot both match the live document."""
        from crdt_tpu.api.doc import Crdt
        from crdt_tpu.models.replay import replay_trace

        out1, out2 = [], []
        a = Crdt(1, on_update=lambda u, m: out1.append(u))
        b = Crdt(2, on_update=lambda u, m: out2.append(u))
        a.push("text", ["one", "two", "three"])
        for u in out1:
            b.apply_update(u)
        b.unshift("text", "zero")
        b.insert("text", 2, "1.5")
        b.cut("text", 4)
        a.insert("text", 1, "a-mid")
        a.set("meta", "title", "notes")
        blobs = out1 + out2 + out1  # at-least-once prefix redelivery
        res = replay_trace(blobs)
        oracle = Crdt(9)
        oracle.apply_updates(blobs)
        assert res.cache == dict(oracle.c)
        fresh = Crdt(8)
        fresh.apply_update(res.snapshot)
        assert dict(fresh.c) == res.cache

    def test_dedup_does_not_alias_large_client_ids(self):
        """Distinct 31-bit clients differing by a multiple of 2^24 must
        both survive dedup (the old packed key aliased them)."""
        from crdt_tpu.api.doc import Crdt
        from crdt_tpu.models.replay import replay_trace

        c1, c2 = 1 << 24, 2 << 24
        out = []
        a = Crdt(c1, on_update=lambda u, m: out.append(u))
        b = Crdt(c2, on_update=lambda u, m: out.append(u))
        a.set("m", "ka", "A")
        b.set("m", "kb", "B")
        res = replay_trace(out + out)  # with redelivery
        assert res.cache["m"] == {"ka": "A", "kb": "B"}

    def test_mixed_append_and_prepend_parents_stay_selective(self):
        """Only right-bearing parents re-order on host; a pure-append
        list in the same trace keeps its (correct) kernel order."""
        from crdt_tpu.api.doc import Crdt
        from crdt_tpu.models.replay import replay_trace

        out = []
        a = Crdt(1, on_update=lambda u, m: out.append(u))
        b = Crdt(2, on_update=lambda u, m: out.append(u))
        for i in range(10):
            a.push("appendy", [i])
        a.push("edity", ["base"])
        for u in list(out):
            b.apply_update(u)
        b.unshift("edity", "pre")
        res = replay_trace(out)
        oracle = Crdt(9)
        oracle.apply_updates(out)
        assert res.cache == dict(oracle.c)
        assert res.cache["edity"] == ["pre", "base"]
        assert res.cache["appendy"] == list(range(10))


from crdt_tpu.models import replay_trace


class TestReplayRoutes:
    """replay_trace's convergence engines must be interchangeable:
    "device" (packed pipeline, the differential-oracle default),
    "host" (the identical fused kernel on the local CPU backend), and
    "replica" (the incremental machinery a resident replica uses to
    ingest the same backlog) produce identical results; "auto" picks
    by the session-calibrated crossover and records its choice."""

    def test_all_routes_agree(self):
        import bench as B

        for builder in (B.build_trace, B.build_conflict_trace,
                        B.build_text_trace):
            blobs = builder(30, 20)
            dev = replay_trace(blobs, route="device")
            host = replay_trace(blobs, route="host")
            rep = replay_trace(blobs, route="replica")
            assert dev.path == "device" and host.path == "host"
            assert rep.path == "replica"
            assert host.cache == dev.cache, builder.__name__
            assert host.snapshot == dev.snapshot, builder.__name__
            assert rep.cache == dev.cache, builder.__name__
            assert rep.snapshot == dev.snapshot, builder.__name__

    def test_auto_records_its_choice(self):
        import bench as B

        blobs = B.build_trace(10, 10)
        res = replay_trace(blobs, route="auto")
        assert res.path in ("host", "replica", "device")
        assert res.cache == replay_trace(blobs, route="device").cache

    def test_unknown_route_rejected(self):
        import pytest

        with pytest.raises(ValueError):
            replay_trace([], route="warp")
