"""ReplicaFleet — flagship batched convergence model on the CPU mesh."""

import numpy as np
import pytest

from crdt_tpu.models import FleetStep, ReplicaFleet
from crdt_tpu.utils import Tracer, get_tracer, set_tracer


def test_fleet_step_shapes_and_handshake():
    fleet = ReplicaFleet(8, 16, n_devices=4, num_clients=10, num_segments=256)
    cols, dels = fleet.synth(num_maps=2, keys_per_map=8)
    out = fleet.step(cols, dels)
    assert isinstance(out, FleetStep)
    assert out.sv_local.shape == (8, 10)
    assert out.global_sv.shape == (10,)
    assert all(out.global_sv[r + 1] == 16 for r in range(8))
    assert out.deficit.shape == (8, 8)
    assert out.deficit[0][0] == 0 and out.deficit[0][1] == 16
    assert (out.winners >= 0).sum() > 0


def test_fleet_winners_match_scalar_oracle():
    """The fleet's converged LWW winners equal the host engine's on the
    same op set (differential test at the model level)."""
    from crdt_tpu.core.engine import Engine
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord
    from crdt_tpu.ops.merge import records_to_columns

    fleet = ReplicaFleet(4, 8, n_devices=4, num_clients=8, num_segments=64)
    cols, dels = fleet.synth(num_maps=2, keys_per_map=4, seed=3)

    # replay the identical ops through the scalar engine
    records = []
    R, N = cols["client"].shape
    for r in range(R):
        for k in range(N):
            records.append(
                ItemRecord(
                    client=int(cols["client"][r, k]),
                    clock=int(cols["clock"][r, k]),
                    parent_root=f"m{int(cols['parent_a'][r, k])}",
                    key=f"k{int(cols['key_id'][r, k])}",
                    content=0,
                )
            )
    eng = Engine(0)
    eng.apply_records(records, DeleteSet())
    oracle = eng.map_winner_table()

    out = fleet.step(cols, dels)
    # map winner ids: reconstruct from flattened op order
    flat_client = cols["client"].reshape(-1)
    flat_clock = cols["clock"].reshape(-1)
    got = {}
    # fleet orders ops by sorted packed id; winners index into that order
    order = np.lexsort((flat_clock, flat_client))
    for w, vis in zip(out.winners, out.winner_visible):
        if w < 0 or w >= len(order):
            continue
        i = order[w]
        r, k = divmod(int(i), N)
        key = (("root", f"m{int(cols['parent_a'][r, k])}"),
               f"k{int(cols['key_id'][r, k])}")
        got[key] = ((int(flat_client[i]), int(flat_clock[i])), bool(vis))
    assert got == oracle


def test_fleet_rejects_uneven_sharding():
    with pytest.raises(ValueError, match="divide"):
        ReplicaFleet(5, 8, n_devices=4)


def test_fleet_traces_step():
    old = get_tracer()
    tr = set_tracer(Tracer(enabled=True))
    try:
        fleet = ReplicaFleet(4, 4, n_devices=2, num_clients=6, num_segments=64)
        cols, dels = fleet.synth(num_maps=1, keys_per_map=4)
        fleet.step(cols, dels)
        rep = tr.report()
        assert rep["spans"]["fleet.step"]["count"] == 1
        assert rep["counters"]["fleet.ops_converged"] == 16
    finally:
        set_tracer(old)
