"""lib0 primitive codec tests: round-trips and golden byte patterns."""

import math
import random

import pytest

from crdt_tpu.codec.lib0 import UNDEFINED, Decoder, Encoder


def roundtrip_uint(n):
    e = Encoder()
    e.write_var_uint(n)
    d = Decoder(e.to_bytes())
    out = d.read_var_uint()
    assert not d.has_content()
    return out


def roundtrip_int(n):
    e = Encoder()
    e.write_var_int(n)
    d = Decoder(e.to_bytes())
    out = d.read_var_int()
    assert not d.has_content()
    return out


def test_var_uint_golden():
    # 7-bit boundary behavior of base-128 little-endian varints
    cases = {
        0: b"\x00",
        1: b"\x01",
        127: b"\x7f",
        128: b"\x80\x01",
        300: b"\xac\x02",
        16383: b"\xff\x7f",
        16384: b"\x80\x80\x01",
    }
    for n, expected in cases.items():
        e = Encoder()
        e.write_var_uint(n)
        assert e.to_bytes() == expected, n


def test_var_uint_roundtrip():
    for n in [0, 1, 63, 64, 127, 128, 255, 2**20, 2**31 - 1, 2**53]:
        assert roundtrip_uint(n) == n
    rng = random.Random(7)
    for _ in range(500):
        n = rng.getrandbits(rng.randint(1, 53))
        assert roundtrip_uint(n) == n


def test_var_int_roundtrip():
    for n in [0, 1, -1, 63, -63, 64, -64, 8191, -8192, 2**31 - 1, -(2**31)]:
        assert roundtrip_int(n) == n
    rng = random.Random(8)
    for _ in range(500):
        n = rng.getrandbits(rng.randint(1, 40)) * rng.choice([1, -1])
        assert roundtrip_int(n) == n


def test_var_int_sign_bit_layout():
    # -1 => continue=0, sign=0x40, payload 1 => 0x41
    e = Encoder()
    e.write_var_int(-1)
    assert e.to_bytes() == b"\x41"
    e = Encoder()
    e.write_var_int(1)
    assert e.to_bytes() == b"\x01"
    # 64 needs a second byte: first = 0x80 | (64 & 0x3f) = 0x80, then 1
    e = Encoder()
    e.write_var_int(64)
    assert e.to_bytes() == b"\x80\x01"


def test_var_string_roundtrip():
    for s in ["", "a", "hello", "héllo wörld", "日本語テキスト", "👍🏽emoji", "a" * 1000]:
        e = Encoder()
        e.write_var_string(s)
        d = Decoder(e.to_bytes())
        assert d.read_var_string() == s
        assert not d.has_content()


@pytest.mark.parametrize(
    "value",
    [
        None,
        UNDEFINED,
        True,
        False,
        0,
        1,
        -1,
        2**30,
        -(2**30),
        2**40,
        2**54,  # bigint path
        0.5,
        1.25,  # exact float32
        0.1,  # needs float64
        "text",
        b"\x00\x01\xff",
        [1, "two", None, [3.5, True]],
        {"a": 1, "b": {"c": [1, 2, 3]}, "d": None},
        {"nested": {"deep": {"list": [{"x": 1}]}}},
    ],
)
def test_any_roundtrip(value):
    e = Encoder()
    e.write_any(value)
    d = Decoder(e.to_bytes())
    out = d.read_any()
    assert not d.has_content()
    assert out == value or (value is UNDEFINED and out is UNDEFINED)


def test_any_type_bytes():
    # golden type tags from the lib0 wire format
    def tag(v):
        e = Encoder()
        e.write_any(v)
        return e.to_bytes()[0]

    assert tag(UNDEFINED) == 127
    assert tag(None) == 126
    assert tag(5) == 125
    assert tag(0.5) == 124
    assert tag(0.1) == 123
    assert tag(2**40) == 125  # safe ints stay varInt
    assert tag(2**54) == 122
    assert tag(False) == 121
    assert tag(True) == 120
    assert tag("s") == 119
    assert tag({}) == 118
    assert tag([]) == 117
    assert tag(b"") == 116


def test_float_precision():
    e = Encoder()
    e.write_any(math.pi)
    d = Decoder(e.to_bytes())
    assert d.read_any() == math.pi


def test_truncated_buffers_raise():
    e = Encoder()
    e.write_any({"k": "hello world", "b": b"\x01\x02\x03", "f": 0.1})
    wire = e.to_bytes()
    # every strict prefix must raise, never silently decode short
    for cut in range(len(wire)):
        with pytest.raises(ValueError):
            try:
                Decoder(wire[:cut]).read_any()
            except Exception as ex:
                raise ValueError(str(ex)) from ex


def test_any_float_boundary_values():
    """Floats at/above the f32 rounding boundary are legal f64 payloads
    (the old f32 probe let struct's OverflowError escape)."""
    from crdt_tpu.codec.lib0 import Decoder, Encoder

    for v in (3.4028235677973366e38, -3.4028235677973366e38, 1e300,
              3.4028234663852886e38):  # last = exact float32 max
        e = Encoder()
        e.write_any(v)
        assert Decoder(e.to_bytes()).read_any() == v
