"""Tier-1 guard over the round-19 multi-process tracing leg.

``bench.py --fleet-trace`` spawns three REAL subprocess replicas over
UDP routers under the seeded round-7 fault schedule, with two
children permanently partitioned (their traffic forced through the
rendezvous relay), and asserts the acceptance numbers internally:
100% cross-process path reconstruction, digest convergence, < 5%
trace-context wire overhead, a three-pid merged Perfetto timeline.
Running it here keeps the evidence pipeline live in every tier-1 run
— and, via ``BENCH_FLEET_ARTIFACT``, produces the observability
artifact CI uploads (same pattern as ``BENCH_SMOKE_ARTIFACT``).
"""

import json
import os
import pathlib
import subprocess
import sys


def test_fleet_trace_leg(tmp_path):
    art = (pathlib.Path(os.environ["BENCH_FLEET_ARTIFACT"])
           if os.environ.get("BENCH_FLEET_ARTIFACT")
           else tmp_path / "fleet_trace.json")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial a tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_FLEET_OUT"] = str(art)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--fleet-trace"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["ok"] is True
    ft = out["fleet_trace"]
    # the acceptance numbers, re-asserted on the emitted evidence
    # (the leg's own asserts are the gate; this pins the SHAPE)
    assert ft["procs"] == 3
    assert ft["pair_rate"] == 1.0
    assert ft["traced_recvs"] > 0
    assert ft["converged"] is True
    assert ft["wire_overhead_ratio"] < 0.05
    assert ft["relay_frames_forwarded"] > 0
    for route in ("direct", "relayed", "sync_answer"):
        assert ft["routes"].get(route, 0) > 0, route
    # multi-hop deliveries really happened (the relay incrementer)
    assert ft["hops"].get("2", 0) > 0
    # the artifact CI uploads carries the full evidence
    full = json.loads(art.read_text())
    assert full["fleet_trace"]["pair_rate"] == 1.0
    assert len(full["perfetto_pids"]) >= 3
    assert full["latency"]["paths"]["pair_rate"] == 1.0
    assert full["latency"]["routes"]  # per-route leg percentiles
