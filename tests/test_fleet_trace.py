"""Real-trace fleet rounds (VERDICT r4 item 1: the mesh axis as a
product capability, not a synthetic model).

Per-replica v1 wire blobs — the bytes each peer would ``propagate``
(crdt.js:385,445) — staged into the fleet's sharded columns and merged
as ONE gossip round over the 8-device virtual mesh must reproduce the
scalar engine's document exactly, including right-origin mid-inserts,
deletes, overwrites, and redelivered (overlapping) blobs.
"""

import jax
import numpy as np
import pytest

from crdt_tpu.codec import v1
from crdt_tpu.core.engine import Engine
from crdt_tpu.models.fleet import (
    fleet_for_trace,
    fleet_replay,
    load_trace,
)
from crdt_tpu.models.replay import replay_trace


def build_round_blobs(R: int, K: int, seed: int = 0, *, deletes: bool = True):
    """One gossip round's worth of per-replica broadcast blobs.

    Replica 0 (client 7) is the shared base: its blob carries the
    initial document (lists + map). Replicas 1..R-1 each apply the
    base, make K concurrent local edits with their own sparse client
    id (mid-inserts anchored into base items, LWW overwrites,
    deletes), and broadcast only their delta — exactly the causally
    complete union one full-mesh round would merge."""
    rng = np.random.default_rng(seed)
    base = Engine(7)
    for i in range(12):
        base.seq_insert("log", i, [f"b{i}"])
    for i in range(6):
        base.map_set("cfg", f"k{i}", {"v": i})
    blob0 = v1.encode_state_as_update(base)
    base_sv = base.state_vector()

    blobs = [blob0]
    for r in range(1, R):
        eng = Engine(100 + 13 * r)
        v1.apply_update(eng, blob0)
        for j in range(K):
            kind = rng.integers(0, 4 if deletes else 3)
            if kind == 0:
                eng.map_set("cfg", f"k{rng.integers(0, 8)}", [r, j])
            elif kind == 1:
                n_vis = len(eng.to_json().get("log", []))
                eng.seq_insert(
                    "log", int(rng.integers(0, n_vis + 1)), [f"r{r}j{j}"]
                )
            elif kind == 2:
                eng.seq_insert("log", 0, [f"h{r}j{j}"])
            else:
                n_vis = len(eng.to_json().get("log", []))
                if n_vis > 1:
                    eng.seq_delete("log", int(rng.integers(0, n_vis - 1)), 1)
                else:
                    eng.map_set("cfg", "k0", f"d{r}{j}")
        blobs.append(v1.encode_state_as_update(eng, base_sv))
    return blobs


def oracle_cache(blobs):
    eng = Engine(10**6)
    for b in blobs:
        v1.apply_update(eng, b)
    return eng.to_json()


@pytest.fixture(scope="module")
def mesh8():
    from crdt_tpu.parallel.gossip import make_mesh

    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_mesh(8)


class TestLoadTrace:
    def test_shapes_padding_and_interning(self):
        blobs = build_round_blobs(5, 6, seed=1)
        tr = load_trace(blobs, replicas_multiple=8)
        R, N = tr.row_map.shape
        assert R == 8  # 5 blobs padded up to the mesh multiple
        assert tr.cols["client"].shape == (R, N)
        # padding rows are invalid and map to no union row
        assert not tr.cols["valid"][5:].any()
        assert (tr.row_map[5:] == -1).all()
        # interned clients are dense 1..C and order-preserving
        iclients = tr.cols["client"][tr.cols["valid"]]
        assert iclients.min() >= 1 and iclients.max() <= len(tr.clients)
        raw = tr.clients[iclients - 1]
        flat_rows = tr.row_map[tr.cols["valid"]]
        np.testing.assert_array_equal(raw, tr.dec["client"][flat_rows])
        # every admitted union row appears exactly once across replicas
        rows = tr.row_map[tr.row_map >= 0]
        assert len(np.unique(rows)) == len(rows)

    def test_ops_bucket_too_small_raises(self):
        blobs = build_round_blobs(3, 8, seed=2)
        with pytest.raises(ValueError):
            load_trace(blobs, ops_bucket=2)

    def test_empty_blob_set(self):
        tr = load_trace([v1.encode_update([], None)])
        assert tr.n_ops == 0

    def test_wide_client_ids_no_packing_alias(self, mesh8):
        """Honest Yjs client ids are random 32-bit; two ids sharing
        their low 24 bits must NOT alias in the attribution packing
        (they would if raw ids were shifted into the 40-bit clock
        field). Interned packing keeps them distinct."""
        base = Engine(0x00ABCD12)
        for i in range(4):
            base.seq_insert("log", i, [f"b{i}"])
        blob0 = v1.encode_state_as_update(base)
        sv = base.state_vector()
        eng = Engine(0x01ABCD12)  # same low 24 bits, different client
        v1.apply_update(eng, blob0)
        eng.seq_insert("log", 2, ["mid"])
        eng.map_set("cfg", "k", "v")
        blobs = [blob0, v1.encode_state_as_update(eng, sv)]
        tr = load_trace(blobs, replicas_multiple=8)
        # both replicas staged all their rows
        assert (tr.row_map[0] >= 0).sum() == 4
        assert (tr.row_map[1] >= 0).sum() == 2
        out = fleet_replay(blobs, mesh=mesh8)
        assert out.cache == oracle_cache(blobs)


class TestFleetReplay:
    def test_matches_engine_and_host_route(self, mesh8):
        """The full differential: fleet round == host machinery ==
        scalar engine on identical per-replica broadcasts."""
        for seed in range(3):
            blobs = build_round_blobs(8, 10, seed=seed)
            want = oracle_cache(blobs)
            host = replay_trace(blobs, route="host")
            assert host.cache == want
            # auto on a tiny trace: below CRDT_TPU_SHARD_MIN_ROWS the
            # mapping falls back to the replicated round
            out = fleet_replay(blobs, mesh=mesh8)
            assert out.path == "fleet"
            assert out.cache == want, f"seed {seed} diverges"
            # the explicit sharded mapping always shards — and agrees
            sh = fleet_replay(blobs, mesh=mesh8, shard="sharded")
            assert sh.path == "fleet-sharded"
            assert sh.cache == want, f"seed {seed} sharded diverges"

    def test_overlapping_blobs_idempotent(self, mesh8):
        """Redelivered ops (one replica's blob carried twice, plus a
        blob that embeds another's ops) merge idempotently — the
        kernel's duplicate-id drop is Yjs's idempotent applyUpdate."""
        blobs = build_round_blobs(6, 8, seed=11)
        dup = blobs + [blobs[2], blobs[4]]
        want = oracle_cache(blobs)
        out = fleet_replay(dup, mesh=mesh8)
        assert out.cache == want

    def test_replica_counts_not_multiple_of_mesh(self, mesh8):
        """R is padded with empty replicas up to the mesh size."""
        blobs = build_round_blobs(5, 5, seed=3)
        out = fleet_replay(blobs, mesh=mesh8)
        assert out.cache == oracle_cache(blobs)

    def test_single_device_mesh(self):
        """The single-chip shape: replica axis batched on one device."""
        from crdt_tpu.parallel.gossip import make_mesh

        blobs = build_round_blobs(4, 6, seed=4)
        out = fleet_replay(blobs, mesh=make_mesh(1))
        assert out.cache == oracle_cache(blobs)

    def test_route_fleet_through_replay_trace(self, mesh8):
        """The product seam: replay_trace(route='fleet')."""
        blobs = build_round_blobs(4, 5, seed=5)
        out = replay_trace(blobs, route="fleet")
        # tiny trace: auto falls back to the replicated mapping
        assert out.path == "fleet"
        assert out.cache == oracle_cache(blobs)

    def test_route_fleet_auto_shards_past_gate(self, mesh8,
                                               monkeypatch):
        """With the size gate cleared, the 8-device mesh resolves the
        auto mapping to the round-13 sharded converge."""
        from crdt_tpu.ops import shard as shard_ops

        monkeypatch.setenv(shard_ops.MIN_ROWS_ENV, "1")
        blobs = build_round_blobs(4, 5, seed=5)
        out = replay_trace(blobs, route="fleet")
        assert out.path == "fleet-sharded"
        assert out.cache == oracle_cache(blobs)

    def test_trace_reuse_shares_compiled_step(self, mesh8):
        """Two traces staged with the same buckets drive ONE fleet
        (one compiled step) — the bench's scaling-loop contract."""
        b1 = build_round_blobs(8, 8, seed=6)
        b2 = build_round_blobs(8, 8, seed=7)
        t1 = load_trace(b1, replicas_multiple=8, ops_bucket=64)
        t2 = load_trace(b2, replicas_multiple=8, ops_bucket=64)
        assert t1.row_map.shape == t2.row_map.shape
        fleet = fleet_for_trace(t1, mesh=mesh8)
        for blobs, tr in ((b1, t1), (b2, t2)):
            if tr.num_clients <= fleet.num_clients and \
               tr.num_segments <= fleet.num_segments:
                out = fleet_replay(blobs, trace=tr, fleet=fleet)
                assert out.cache == oracle_cache(blobs)

    def test_reused_fleet_rejects_oversized_trace(self, mesh8):
        """A trace whose buckets exceed a reused fleet's compiled
        bounds must raise, not silently overflow the SV table."""
        b1 = build_round_blobs(4, 5, seed=9)
        b2 = build_round_blobs(16, 8, seed=10)  # more clients
        t1 = load_trace(b1, replicas_multiple=8)
        t2 = load_trace(b2, replicas_multiple=8)
        fleet = fleet_for_trace(t1, mesh=mesh8)
        assert t2.num_clients > fleet.num_clients
        with pytest.raises(ValueError):
            fleet_replay(b2, trace=t2, fleet=fleet)

    def test_segment_sharded_matches_engine(self, mesh8):
        """The scaling mode: union partitioned by segment, each device
        converging only its shard, must still reproduce the engine —
        and its SV handshake must match the replica-sharded step's."""
        from crdt_tpu.models.fleet import (
            SegmentedFleet,
            load_trace,
            shard_trace,
        )

        for seed in range(3):
            blobs = build_round_blobs(8, 10, seed=30 + seed)
            want = oracle_cache(blobs)
            out = fleet_replay(blobs, mesh=mesh8, shard="segments")
            assert out.cache == want, f"seed {seed} diverges"
            # handshake parity: per-replica SVs from the segment
            # layout equal the replica layout's
            tr = load_trace(blobs, replicas_multiple=8)
            fl = fleet_for_trace(tr, mesh=mesh8)
            rep_out = fl.step(tr.cols, tr.dels)
            tr1 = load_trace(blobs, replicas_multiple=1)
            sh = shard_trace(tr1, 8)
            seg_out = SegmentedFleet(sh, mesh=mesh8).step(sh)
            np.testing.assert_array_equal(
                rep_out.global_sv, seg_out.global_sv
            )
            R = len(blobs)
            np.testing.assert_array_equal(
                rep_out.sv_local[:R], seg_out.svs[:R]
            )
            np.testing.assert_array_equal(
                rep_out.deficit[:R, :R], seg_out.deficit[:R, :R]
            )

    def test_segment_sharded_single_device(self):
        from crdt_tpu.parallel.gossip import make_mesh

        blobs = build_round_blobs(5, 8, seed=40)
        out = fleet_replay(blobs, mesh=make_mesh(1), shard="segments")
        assert out.cache == oracle_cache(blobs)

    def test_segmented_step_rejects_out_of_bounds_trace(self, mesh8):
        """A reused SegmentedFleet fed a trace exceeding its compiled
        bounds (segment bucket, replica count, device count) must
        raise, not unpack wrong offsets into silently wrong winners —
        the mirror of the ReplicaFleet reuse guard (ADVICE r5)."""
        from crdt_tpu.models.fleet import (
            SegmentedFleet,
            load_trace,
            shard_trace,
        )
        from crdt_tpu.parallel.gossip import make_mesh

        blobs = build_round_blobs(4, 4, seed=50)
        tr = load_trace(blobs, replicas_multiple=1)
        sh = shard_trace(tr, 8)
        sf = SegmentedFleet(sh, mesh=mesh8)

        # bigger segment bucket than compiled
        big = sh._replace(num_segments=sh.num_segments * 2)
        with pytest.raises(ValueError, match="does not fit"):
            sf.step(big)
        # replica-count mismatch (deficit block unpack would shear)
        wrong_r = sh._replace(n_replicas=sh.n_replicas + 1)
        with pytest.raises(ValueError, match="does not fit"):
            sf.step(wrong_r)
        # sharded for a different mesh width
        sh2 = shard_trace(tr, 2)
        with pytest.raises(ValueError, match="does not fit"):
            sf.step(sh2)
        # the matching trace still steps after the rejections
        out = sf.step(sh)
        assert out.winners.shape[0] == 8

    def test_snapshot_replays_to_same_cache(self, mesh8):
        """The compacted snapshot a fleet round emits is a valid v1
        blob that cold-replays to the identical document."""
        blobs = build_round_blobs(6, 6, seed=8)
        out = fleet_replay(blobs, mesh=mesh8)
        again = replay_trace([out.snapshot], route="host")
        assert again.cache == out.cache
