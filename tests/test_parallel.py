"""Sharded gossip step on the 8-device virtual CPU mesh.

Validates the multi-chip path the driver dry-runs (SURVEY.md §5
distributed backend): replica-sharded op columns, all-gather fan-in,
replicated union convergence, SV handshake collectives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_tpu.parallel.gossip import make_gossip_step, make_mesh, synth_columns


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_mesh(8)


def run_step(mesh, cols, dels, num_segments, num_clients):
    from crdt_tpu.parallel.gossip import (
        fleet_out_sizes,
        pack_cols,
        pack_dels,
        unpack_fleet_out,
    )

    step = make_gossip_step(mesh, num_segments=num_segments, num_clients=num_clients)
    vec = np.asarray(step(
        jnp.asarray(pack_cols(cols)), jnp.asarray(pack_dels(dels))
    ))
    R, N = np.asarray(cols["client"]).shape
    parts = unpack_fleet_out(vec, R, N, num_clients, num_segments)
    return [
        parts[name]
        for name, _ in fleet_out_sizes(R, N, num_clients, num_segments)
    ]


def test_gossip_step_shapes_and_svs(mesh):
    R, N = 16, 32
    C = R + 2
    cols, dels = synth_columns(R, N, num_maps=2, keys_per_map=16)
    sv_local, global_sv, deficit, winners, visible, *_ = run_step(
        mesh, cols, dels, 256, C)
    assert sv_local.shape == (R, C)
    # replica r knows exactly its own clocks before gossip
    for r in range(R):
        assert sv_local[r, r + 1] == N
        assert sv_local[r].sum() == N
    # merged vector knows everyone
    assert all(global_sv[r + 1] == N for r in range(R))
    # anti-entropy plan: every pair owes the other N clocks
    assert deficit[0, 0] == 0 and deficit[3, 5] == N and deficit[5, 3] == N


def test_gossip_winners_match_host_kernel(mesh):
    """The sharded union converge must equal the single-device kernel
    on the flattened union."""
    from functools import partial

    from crdt_tpu.ops.merge import converge_maps

    R, N = 16, 32
    cols, dels = synth_columns(R, N, num_maps=2, keys_per_map=16, seed=3)
    _, _, _, winners, visible, *_ = run_step(mesh, cols, dels, 256, R + 2)

    flat = {k: np.asarray(v).reshape(-1) for k, v in cols.items()}
    out = partial(converge_maps, num_segments=256)(
        jnp.asarray(flat["client"]),
        jnp.asarray(flat["clock"]),
        jnp.asarray(flat["parent_is_root"]),
        jnp.asarray(flat["parent_a"]),
        jnp.asarray(flat["parent_b"]),
        jnp.asarray(flat["key_id"]),
        jnp.asarray(flat["origin_client"]),
        jnp.asarray(flat["origin_clock"]),
        jnp.asarray(flat["valid"]),
        jnp.asarray(dels[0]),
        jnp.asarray(dels[1]),
        jnp.asarray(dels[2]),
    )
    ref_winners, ref_visible = np.asarray(out[2]), np.asarray(out[3])
    np.testing.assert_array_equal(winners, ref_winners)
    np.testing.assert_array_equal(visible, ref_visible)


def test_gossip_with_deletes(mesh):
    R, N = 8, 16
    cols, _ = synth_columns(R, N, num_maps=1, keys_per_map=4, seed=5)
    # tombstone all of replica 1's ops
    dels = (
        np.asarray([1] + [-1] * 15, np.int32),
        np.asarray([0] + [-1] * 15, np.int64),
        np.asarray([N] + [-1] * 15, np.int64),
    )
    _, _, _, winners, visible, *_ = run_step(mesh, cols, dels, 64, R + 2)
    assert (winners >= 0).sum() > 0


def test_gossip_sequences_match_engine_oracle(mesh):
    """Mesh-sharded YATA: the sharded step's sequence order over the
    union must equal the scalar engine's integrate order on the same
    ops (VERDICT r1 item #4: sequences in the fleet)."""
    from crdt_tpu.core.engine import Engine
    from crdt_tpu.core.records import ItemRecord

    R, N = 8, 16
    num_maps, num_lists = 2, 3
    cols, dels = synth_columns(
        R, N, num_maps=num_maps, keys_per_map=8, num_lists=num_lists, seed=11
    )
    out = run_step(mesh, cols, dels, 256, R + 2)
    seq_order, seq_seg, seq_rank = out[5], out[6], out[7]

    # device order per sequence: rows sorted by rank within segment
    flat = {k: np.asarray(v).reshape(-1) for k, v in cols.items()}
    n = len(flat["client"])
    by_seg = {}
    for pos in range(len(seq_rank)):
        if seq_rank[pos] < 0:
            continue
        row = seq_order[pos]
        assert row < n
        by_seg.setdefault(int(seq_seg[pos]), []).append(
            (int(seq_rank[pos]), (int(flat["client"][row]), int(flat["clock"][row])))
        )
    dev_orders = {}
    for sid, pairs in by_seg.items():
        pairs.sort()
        # identify the sequence by its root id (all rows share parent_a)
        row0 = seq_order[[p for p in range(len(seq_seg)) if seq_seg[p] == sid][0]]
        dev_orders[int(flat["parent_a"][row0])] = [i for _, i in pairs]

    # oracle: feed the same records through the scalar engine
    eng = Engine(0)
    records = []
    for i in range(n):
        if flat["key_id"][i] >= 0:
            records.append(ItemRecord(
                client=int(flat["client"][i]), clock=int(flat["clock"][i]),
                parent_root=f"m{flat['parent_a'][i]}",
                key=f"k{flat['key_id'][i]}", content=i,
            ))
        else:
            org = None
            if flat["origin_client"][i] >= 0:
                org = (int(flat["origin_client"][i]), int(flat["origin_clock"][i]))
            records.append(ItemRecord(
                client=int(flat["client"][i]), clock=int(flat["clock"][i]),
                parent_root=f"l{flat['parent_a'][i]}", origin=org, content=i,
            ))
    eng.apply_records(records)
    oracle = eng.seq_order_table()
    assert len(dev_orders) == num_lists
    for lid, ids in dev_orders.items():
        assert oracle[("root", f"l{lid}")] == ids, f"list {lid} diverges"


def test_hierarchical_2d_mesh_matches_flat_gossip():
    """The (hosts, replicas) two-tier fan-in (ICI all-gather then DCN
    all-gather) must produce exactly the flat 1D step's outputs on the
    same columns — the multi-host mapping changes the fabric, not the
    CRDT result."""
    from crdt_tpu.parallel.gossip import (
        make_hierarchical_gossip_step,
        make_mesh2d,
    )

    from crdt_tpu.parallel.gossip import (
        fleet_out_sizes,
        pack_cols,
        pack_dels,
        unpack_fleet_out,
    )

    R, N = 16, 24
    cols, dels = synth_columns(R, N, num_maps=2, keys_per_map=8,
                               num_lists=2, seed=21)
    flat = run_step(make_mesh(8), cols, dels, 256, R + 2)

    mesh2d = make_mesh2d(n_hosts=2, devices_per_host=4)
    step2d = make_hierarchical_gossip_step(mesh2d, num_segments=256,
                                           num_clients=R + 2)
    vec = np.asarray(step2d(
        jnp.asarray(pack_cols(cols)), jnp.asarray(pack_dels(dels))
    ))
    parts = unpack_fleet_out(vec, R, N, R + 2, 256)
    hier = [
        parts[name] for name, _ in fleet_out_sizes(R, N, R + 2, 256)
    ]

    for (name, _), a, b in zip(
        fleet_out_sizes(R, N, R + 2, 256), flat, hier,
    ):
        np.testing.assert_array_equal(a, b, err_msg=f"{name} diverges")
