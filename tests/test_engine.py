"""Engine semantics + multi-replica convergence property tests.

The convergence tests play the role the reference delegates entirely to
Yjs's merge guarantees (SURVEY.md §4): after arbitrary op interleavings
and full-state exchange, every replica materializes identical JSON.
"""

import random

from crdt_tpu.core.engine import Engine
from crdt_tpu.core.store import TYPE_ARRAY


def sync(a: Engine, b: Engine) -> None:
    """Bidirectional full-state exchange (reference broadcasts full state,
    crdt.js:443; dedupe relies on idempotent merge)."""
    ra, dsa = a.records_since(None), a.delete_set()
    rb, dsb = b.records_since(None), b.delete_set()
    b.apply_records(ra, dsa)
    a.apply_records(rb, dsb)


def sync_all(engines) -> None:
    for i in range(len(engines)):
        for j in range(len(engines)):
            if i != j:
                engines[j].apply_records(
                    engines[i].records_since(None), engines[i].delete_set()
                )
    # second pass so late arrivals propagate everywhere
    for i in range(len(engines)):
        for j in range(len(engines)):
            if i != j:
                engines[j].apply_records(
                    engines[i].records_since(None), engines[i].delete_set()
                )


def test_local_map_ops():
    e = Engine(1)
    e.map_set("users", "alice", {"age": 30})
    e.map_set("users", "bob", 5)
    assert e.map_json("users") == {"alice": {"age": 30}, "bob": 5}
    assert e.map_get("users", "alice") == {"age": 30}
    e.map_set("users", "alice", "replaced")
    assert e.map_get("users", "alice") == "replaced"
    assert e.map_delete("users", "bob")
    assert e.map_json("users") == {"alice": "replaced"}
    assert not e.map_delete("users", "bob")  # already gone
    assert e.map_get("users", "bob") is None


def test_local_seq_ops():
    e = Engine(1)
    e.seq_insert("log", 0, ["a", "b", "c"])
    e.seq_insert("log", 1, ["x"])
    assert e.seq_json("log") == ["a", "x", "b", "c"]
    e.seq_insert("log", 4, ["end"])
    assert e.seq_json("log") == ["a", "x", "b", "c", "end"]
    assert e.seq_delete("log", 1, 2) == 2
    assert e.seq_json("log") == ["a", "c", "end"]
    e.seq_insert("log", 0, ["front"])
    assert e.seq_json("log") == ["front", "a", "c", "end"]


def test_concurrent_map_set_lww():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "k", "from-a")
    b.map_set("m", "k", "from-b")
    sync(a, b)
    # same-origin conflict: higher client wins (YATA sibling order)
    assert a.map_get("m", "k") == "from-b"
    assert b.map_get("m", "k") == "from-b"
    # causal overwrite by lower client beats old higher-client value
    a.map_set("m", "k", "later-from-a")
    sync(a, b)
    assert a.map_get("m", "k") == "later-from-a"
    assert b.map_get("m", "k") == "later-from-a"


def test_concurrent_set_vs_delete():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "k", "v1")
    sync(a, b)
    a.map_delete("m", "k")
    b.map_set("m", "k", "v2")  # concurrent overwrite wins over delete
    sync(a, b)
    assert a.map_get("m", "k") == "v2"
    assert b.map_get("m", "k") == "v2"


def test_concurrent_seq_inserts_converge():
    a, b = Engine(1), Engine(2)
    a.seq_insert("s", 0, ["a1", "a2"])
    sync(a, b)
    a.seq_insert("s", 1, ["A"])
    b.seq_insert("s", 1, ["B"])
    sync(a, b)
    assert a.seq_json("s") == b.seq_json("s")
    got = a.seq_json("s")
    # both inserted between a1 and a2; no interleaving violation
    assert got[0] == "a1" and got[-1] == "a2"
    assert set(got[1:-1]) == {"A", "B"}


def test_same_position_interleaving_blocks():
    """Runs typed concurrently at the same spot must not interleave."""
    a, b = Engine(1), Engine(2)
    a.seq_insert("s", 0, ["base"])
    sync(a, b)
    for i, ch in enumerate("AAA"):
        a.seq_insert("s", 1 + i, [ch])
    for i, ch in enumerate("BBB"):
        b.seq_insert("s", 1 + i, [ch])
    sync(a, b)
    assert a.seq_json("s") == b.seq_json("s")
    body = "".join(a.seq_json("s")[1:])
    assert body in ("AAABBB", "BBBAAA"), body


def test_nested_array_in_map():
    a, b = Engine(1), Engine(2)
    a.map_set_type("m", "list", TYPE_ARRAY)
    spec = a.map_entry_spec("m", "list")
    a.seq_insert("", 0, [1, 2, 3], parent=spec)
    sync(a, b)
    assert b.map_json("m") == {"list": [1, 2, 3]}
    # b edits the nested array
    bspec = b.map_entry_spec("m", "list")
    b.seq_insert("", 3, [4], parent=bspec)
    sync(a, b)
    assert a.map_json("m") == {"list": [1, 2, 3, 4]}
    assert b.map_json("m") == {"list": [1, 2, 3, 4]}


def test_out_of_order_delivery_pending():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "x", 1)
    a.map_set("m", "x", 2)
    a.seq_insert("s", 0, ["p", "q"])
    recs = a.records_since(None)
    ds = a.delete_set()
    # deliver in reverse causal order: pending machinery must hold and
    # integrate once deps arrive
    for rec in sorted(recs, key=lambda r: -r.clock):
        b.apply_records([rec])
    b.apply_records([], ds)
    assert b.map_json("m") == a.map_json("m")
    assert b.seq_json("s") == a.seq_json("s")
    assert not b.pending


def test_partial_delivery_stays_pending():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "x", 1)
    a.map_set("m", "x", 2)
    recs = sorted(a.records_since(None), key=lambda r: r.clock)
    b.apply_records([recs[1]])  # dep missing
    assert b.pending and b.map_json("m") == {}
    b.apply_records([recs[0]])
    assert not b.pending
    assert b.map_get("m", "x") == 2


def test_idempotent_reapply():
    a, b = Engine(1), Engine(2)
    a.map_set("m", "k", "v")
    a.seq_insert("s", 0, [1, 2, 3])
    recs, ds = a.records_since(None), a.delete_set()
    for _ in range(3):
        b.apply_records(recs, ds)
    assert b.map_json("m") == {"k": "v"}
    assert b.seq_json("s") == [1, 2, 3]
    assert len(b.store) == len(a.store)


def _random_op(rng, e: Engine, peers):
    kind = rng.randrange(6)
    if kind == 0:
        e.map_set("m", rng.choice("abcd"), rng.randrange(100))
    elif kind == 1:
        e.map_delete("m", rng.choice("abcd"))
    elif kind == 2:
        n = len(e.seq_json("s"))
        e.seq_insert("s", rng.randint(0, n), [rng.randrange(100)])
    elif kind == 3:
        n = len(e.seq_json("s"))
        if n:
            e.seq_delete("s", rng.randrange(n), 1)
    elif kind == 4:
        spec = e.map_entry_spec("m", "nested")
        if spec is None:
            e.map_set_type("m", "nested", TYPE_ARRAY)
            spec = e.map_entry_spec("m", "nested")
        n = len(e.map_get("m", "nested") or [])
        e.seq_insert("", rng.randint(0, n), [rng.randrange(100)], parent=spec)
    else:
        # random pairwise sync mid-stream
        other = rng.choice(peers)
        if other is not e:
            e.apply_records(other.records_since(None), other.delete_set())


def test_fuzz_convergence():
    rng = random.Random(1234)
    for trial in range(8):
        engines = [Engine(i + 1) for i in range(4)]
        for _ in range(120):
            _random_op(rng, rng.choice(engines), engines)
        sync_all(engines)
        jsons = [e.to_json() for e in engines]
        for j in jsons[1:]:
            assert j == jsons[0], f"divergence in trial {trial}"
        assert not any(e.pending for e in engines)


def test_delete_set_symmetry_on_concurrent_map_set():
    """Losers of concurrent map sets are tombstoned identically on both
    replicas (Yjs deletes the loser at integration on each side)."""
    a, b = Engine(1), Engine(2)
    a.map_set("m", "k", "a")
    b.map_set("m", "k", "b")
    sync(a, b)
    assert a.delete_set() == b.delete_set()
    assert a.delete_set().contains(1, 0)  # the loser (client 1's item)
    assert not a.delete_set().contains(2, 0)


def test_records_since_is_o_delta():
    """An SV-diff touches only the rows the requester lacks — the
    ready-probe on a big doc must not scan the whole store (VERDICT r1
    weak #7: records_since was a full-store scan per probe)."""
    a = Engine(1)
    for i in range(500):
        a.map_set("m", f"k{i % 50}", i)
    b = Engine(2)
    b.apply_records(a.records_since())  # b catches up fully
    sv_full = b.state_vector()
    for i in range(10):
        a.map_set("m", f"fresh{i}", i)

    calls = []
    orig = Engine.record_of_row

    def counting(self, row):
        calls.append(row)
        return orig(self, row)

    Engine.record_of_row = counting
    try:
        delta = a.records_since(sv_full)
    finally:
        Engine.record_of_row = orig
    assert len(delta) == 10
    assert len(calls) == 10, f"touched {len(calls)} rows for a 10-row delta"
    # and the delta is exactly what b needs to converge
    b.apply_records(delta)
    assert b.to_json() == a.to_json()


def test_records_since_unknown_client_and_empty_sv():
    a = Engine(1)
    a.map_set("m", "k", 1)
    a.seq_insert("l", 0, ["x", "y"])
    from crdt_tpu.core.ids import StateVector

    # empty SV = full state; unknown client watermark = everything
    assert len(a.records_since(StateVector())) == 3
    assert len(a.records_since(StateVector({99: 10}))) == 3
    # covered prefix excluded
    assert len(a.records_since(StateVector({1: 2}))) == 1


def test_admission_is_linear_not_quadratic():
    """Reverse-ordered delivery of a long dependency chain must park
    each record once and wake it once — not re-scan the remainder per
    round (r1's integrate loop was O(n^2) here)."""
    from crdt_tpu.core.records import ItemRecord

    n = 2000
    recs = [
        ItemRecord(client=1, clock=k, parent_root="s",
                   origin=(1, k - 1) if k else None, content=k)
        for k in range(n)
    ]
    recs_rev = list(reversed(recs))

    calls = []
    orig = Engine._try_admit

    def counting(self, rec):
        calls.append(rec.clock)
        return orig(self, rec)

    e = Engine(9)
    Engine._try_admit = counting
    try:
        e.apply_records(recs_rev)
    finally:
        Engine._try_admit = orig
    assert not e.pending
    assert e.seq_json("s") == list(range(n))
    # each record attempts once while blocked + once on wake: <= 2n
    assert len(calls) <= 2 * n + 10, f"{len(calls)} attempts for {n} records"


def test_admission_wakes_cross_client_chains():
    """Dependencies across clients in adversarial order still converge
    through the wake list, and true orphans stay pending."""
    from crdt_tpu.core.records import ItemRecord

    a = ItemRecord(client=1, clock=0, parent_root="s", content="a")
    b = ItemRecord(client=2, clock=0, parent_root="s", origin=(1, 0),
                   content="b")
    c = ItemRecord(client=3, clock=0, parent_root="s", origin=(2, 0),
                   content="c")
    orphan = ItemRecord(client=4, clock=0, parent_root="s", origin=(9, 9),
                        content="x")
    e = Engine(8)
    e.apply_records([orphan, c, b, a])
    assert e.seq_json("s") == ["a", "b", "c"]
    assert [r.client for r in e.pending] == [4]
    # the missing dep arriving later frees the orphan
    e.apply_records([ItemRecord(client=9, clock=0, parent_root="s",
                                content="dep")
                     ] + [ItemRecord(client=9, clock=k, parent_root="s",
                                     origin=(9, k - 1), content=k)
                          for k in range(1, 10)])
    assert not e.pending
    assert "x" in e.seq_json("s")


def test_malformed_record_mid_batch_preserves_pending():
    """A record that raises during admission must not wipe previously
    stashed pending records or the rest of the batch."""
    from crdt_tpu.core.records import ItemRecord

    e = Engine(5)
    # stash: waits on (9, 0) which never arrived
    e.apply_records([ItemRecord(client=9, clock=1, parent_root="s",
                                origin=(9, 0), content="stashed")])
    assert len(e.pending) == 1
    # malformed: no parent, no origin (decoder could never produce it,
    # but a buggy caller can) raises inside _try_admit
    bad = ItemRecord(client=2, clock=0, content="bad")
    good = ItemRecord(client=3, clock=1, parent_root="s", origin=(3, 0),
                      content="also-waiting")
    import pytest as _pytest

    with _pytest.raises(AssertionError):
        e.apply_records([bad, good])
    ids = {r.id for r in e.pending}
    assert (9, 1) in ids, "prior stash wiped"
    assert (3, 1) in ids, "rest of batch wiped"
