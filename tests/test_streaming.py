"""Streamed/chunked executor vs the one-shot oracle — byte-identical.

The overlapped streaming replay (crdt_tpu.models.streaming) must
produce EXACTLY the one-shot device pipeline's final state — winners,
sequence orders, materialized cache, and the encoded snapshot bytes —
for every chunking of the blob stream (single-blob chunks, odd sizes,
the whole stream at once) and every convergence-shard count, including
delete-set-only chunks, right-origin mid-inserts, and nested
collections. The one-shot path is itself oracle-pinned elsewhere
(tests/test_models.py, tests/test_grand_differential.py), so equality
here chains the streamed path to the scalar reference.
"""

import numpy as np

from crdt_tpu.codec import native, v1
from crdt_tpu.core.engine import Engine
from crdt_tpu.core.ids import DeleteSet
from crdt_tpu.core.records import ItemRecord
from crdt_tpu.models import replay_trace, stream_replay
from crdt_tpu.models import replay as rp
from crdt_tpu.models import streaming as sm


def mixed_blobs(R=12, K=18, seed=0):
    """Per-replica blobs: chained map sets over two maps, own-chain
    list appends over two lists, shared-anchor attaches (cross-blob
    origin chains — the shape that forces cross-chunk parent
    resolution), and per-replica delete ranges."""
    rng = np.random.default_rng(seed)
    blobs = []
    for r in range(R):
        client = r + 1
        recs = []
        last = {}
        for k in range(K):
            kind = int(rng.integers(0, 3))
            if kind == 0:
                recs.append(ItemRecord(
                    client=client, clock=k, parent_root=f"m{k % 2}",
                    key=f"k{int(rng.integers(0, 6))}", content=[r, k],
                ))
            elif kind == 1 and r > 0:
                # attach to replica 1's own chain head (cross-blob)
                recs.append(ItemRecord(
                    client=client, clock=k, origin=(1, 0),
                    content=f"x{r}-{k}",
                ))
            else:
                lst = int(rng.integers(0, 2))
                prev = last.get(lst)
                recs.append(ItemRecord(
                    client=client, clock=k, parent_root=f"l{lst}",
                    origin=(client, prev) if prev is not None else None,
                    content=k,
                ))
                last[lst] = k
        ds = DeleteSet()
        ds.add(client, int(rng.integers(0, K)))
        blobs.append(v1.encode_update(recs, ds))
    # ensure replica 1's clock-0 op exists as a list head
    return blobs


def text_blobs(R=8, K=16, seed=3):
    """Right-origin mid-inserts into one shared sequence."""
    rng = np.random.default_rng(seed)
    blobs = []
    for r in range(R):
        client = r + 1
        chain = []
        recs = []
        for k in range(K):
            if chain and rng.random() < 0.3:
                j = int(rng.integers(0, len(chain)))
                recs.append(ItemRecord(
                    client=client, clock=k, parent_root="text",
                    origin=chain[j - 1] if j > 0 else None,
                    right=chain[j], content=k))
                chain.insert(j, (client, k))
            else:
                recs.append(ItemRecord(
                    client=client, clock=k, parent_root="text",
                    origin=chain[-1] if chain else None, content=k))
                chain.append((client, k))
        blobs.append(v1.encode_update(recs, DeleteSet()))
    return blobs


def nested_blobs():
    """Nested collections (array-in-map, map-in-array) via the scalar
    engine — the root-subtree co-location case: a chunk must own a
    type item together with its child segments."""
    blobs = []
    for cid in (5, 9, 13):
        eng = Engine(cid)
        eng.map_set("cfg", f"plain{cid}", [1, cid])
        t = eng.map_set_type("cfg", f"narr{cid}")  # array-in-map
        eng.seq_insert(
            "", 0, [cid * 10, cid * 11], parent=("item",) + t.id
        )
        for i in range(4):
            eng.seq_insert("log", i, [[cid, i]])
        blobs.append(v1.encode_state_as_update(eng))
    return blobs


def _one_shot(blobs):
    return replay_trace(blobs, route="device")


def _assert_identical(blobs, chunk_blobs, max_shards):
    one = _one_shot(blobs)
    ph = {}
    st = stream_replay(
        blobs, chunk_blobs=chunk_blobs, max_shards=max_shards,
        min_shard_rows=1, phases=ph,
    )
    assert st.cache == one.cache, (chunk_blobs, max_shards)
    assert st.snapshot == one.snapshot, (chunk_blobs, max_shards)
    assert st.n_ops == one.n_ops
    return ph


class TestStreamedDifferential:
    def test_chunk_size_matrix(self):
        """{1 blob, odd sizes, whole-stream} x shard counts."""
        blobs = mixed_blobs()
        for chunk in (1, 3, len(blobs)):
            for shards in (1, 2, 3):
                _assert_identical(blobs, chunk, shards)

    def test_delete_set_only_chunks(self):
        """Blobs carrying ONLY delete ranges (no structs) must merge
        through the chunked decode — including as single-blob chunks
        where a whole chunk is delete-set-only."""
        blobs = mixed_blobs(R=8, K=12, seed=4)
        ds1, ds2 = DeleteSet(), DeleteSet()
        ds1.add(1, 2, 3)
        ds2.add(3, 0, 2)
        ds2.add(5, 1, 4)
        blobs = (
            blobs[:3]
            + [v1.encode_update([], ds1)]
            + blobs[3:]
            + [v1.encode_update([], ds2)]
        )
        for chunk in (1, 2, len(blobs)):
            _assert_identical(blobs, chunk, 2)

    def test_right_origin_mid_inserts(self):
        """Attachment groups (rights) route through the exact host
        machinery in both paths; results stay byte-identical."""
        blobs = text_blobs()
        for chunk in (1, 5, len(blobs)):
            _assert_identical(blobs, chunk, 2)

    def test_nested_collections_stay_co_located(self):
        blobs = nested_blobs()
        for chunk in (1, 2):
            _assert_identical(blobs, chunk, 3)

    def test_cross_chunk_origin_chains_resolve(self):
        """Rows whose implicit parents resolve through ANOTHER chunk's
        rows: the merged decode must equal the one-shot decode
        column-for-column (the r6 cross-chunk resolution pass)."""
        blobs = mixed_blobs(R=10, K=14, seed=7)
        one = rp.decode(blobs)
        chunks = [blobs[i:i + 1] for i in range(len(blobs))]
        decs = [native.decode_updates_columns_any(c) for c in chunks]
        merged = native.dedup_columns(native.merge_decoded(decs))
        for k in native._COLUMN_KEYS:
            np.testing.assert_array_equal(merged[k], one[k], err_msg=k)
        assert merged["roots"] == one["roots"]
        assert merged["keys"] == one["keys"]
        assert merged["contents"] == one["contents"]
        np.testing.assert_array_equal(
            np.asarray(merged["ds"]), np.asarray(one["ds"])
        )

    def test_phase_accounting_shape(self):
        """Every pipeline lane must report busy time: a phase silently
        re-serializing (or dropping out of the accounting) fails here
        without a scale run."""
        blobs = mixed_blobs(R=10, K=16, seed=9)
        ph = _assert_identical(blobs, 3, 3)
        for key in ("decode", "merge", "columns", "partition", "pack",
                    "converge", "gather", "materialize", "compact",
                    "busy_sum_s", "wall_s", "wall_vs_phases",
                    "overlap_efficiency"):
            assert key in ph, key
        assert ph["busy_sum_s"] > 0
        assert 0.0 <= ph["overlap_efficiency"] <= 1.0

    def test_redelivered_blobs_dedup(self):
        """Duplicate blob delivery (at-least-once transport) across
        DIFFERENT chunks must dedup exactly like the one-shot path."""
        blobs = mixed_blobs(R=6, K=10, seed=11)
        dup = blobs + blobs[:3]
        for chunk in (1, 4):
            _assert_identical(dup, chunk, 2)

    def test_crafted_map_rights_repair_per_chunk(self):
        """Hostile rights on MAP rows (the chain-tail repair path):
        the per-chunk repair with its shared union-id set must match
        the one-shot path's whole-union repair."""
        blobs = mixed_blobs(R=6, K=10, seed=21)
        recs = [
            ItemRecord(client=101, clock=0, parent_root="m0", key="kx",
                       content="A"),
            # right = A stops the scan at the head: B lands BEFORE A
            ItemRecord(client=102, clock=0, parent_root="m0", key="kx",
                       right=(101, 0), content="B"),
        ]
        blobs = blobs + [v1.encode_update(recs, DeleteSet())]
        for chunk in (1, 4):
            _assert_identical(blobs, chunk, 3)

    def test_empty_and_deletes_only_streams(self):
        """A cold start ([] blobs) and a stream of ONLY delete-set
        blobs must return the same empty-union results as the other
        routes instead of crashing the partitioner."""
        one = replay_trace([], route="device")
        st = stream_replay([], phases={})
        assert st.cache == one.cache == {}
        assert st.snapshot == one.snapshot
        ds = DeleteSet()
        ds.add(2, 0, 5)
        only = [v1.encode_update([], ds)] * 2
        one = replay_trace(only, route="device")
        st = stream_replay(only, chunk_blobs=1, min_shard_rows=1)
        assert st.cache == one.cache
        assert st.snapshot == one.snapshot

    def test_route_stream_through_replay_trace(self):
        blobs = mixed_blobs(R=6, K=10, seed=12)
        one = replay_trace(blobs, route="device")
        st = replay_trace(blobs, route="stream")
        assert st.cache == one.cache
        assert st.snapshot == one.snapshot
        assert st.path == "stream"


class TestPartition:
    def test_whole_segments_and_roots_per_shard(self):
        """No segment — and no root subtree — may split across
        shards (the executor's exactness precondition)."""
        blobs = mixed_blobs(R=10, K=16, seed=13)
        dec = rp.decode(blobs)
        cols, _ = rp.stage(dec)
        shard_rows, seg, _ = sm.partition_shards(cols, 3)
        n = len(cols["client"])
        owner = np.full(n, -1)
        for g, rows in enumerate(shard_rows):
            assert (owner[rows] == -1).all()
            owner[rows] = g
        assert (owner >= 0).all()
        # each segment wholly in one shard
        for s in np.unique(seg):
            assert len(np.unique(owner[seg == s])) == 1
