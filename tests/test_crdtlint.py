"""Unit tests for the crdtlint analyzer itself (tools/crdtlint).

Synthetic in-memory snippets per checker — positive (fires),
negative (clean), suppressed (inline disable), and baseline-matched —
plus the suppression-comment and baseline-file round-trips. The
tier-1 gate over the real package lives in tests/test_lint.py; THIS
file proves the analyzer's own semantics, so a checker regression
shows up as a unit failure, not as silently-green lint.
"""

import json
import os
import sys
import textwrap

import pytest

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tools.crdtlint.core import (  # noqa: E402
    BaselineError,
    LintConfig,
    load_baseline,
    run_lint,
    write_baseline,
)
from tools.crdtlint.registry import Registry  # noqa: E402


def lint(files, registry=None, baseline=None):
    """Lint {relpath: source} snippets with an empty default registry
    (synthetic runs opt into documented names explicitly)."""
    config = LintConfig(
        repo_root="/synthetic", readme_path="", smoke_test_path="",
        baseline_path="/synthetic/absent.json",
    )
    return run_lint(
        [(path, textwrap.dedent(src)) for path, src in files.items()],
        config=config,
        baseline=baseline or {},
        shared={
            "metric_registry":
                registry if registry is not None else Registry()
        },
    )


def codes(result):
    return sorted(f.code for f in result.findings)


# ---------------------------------------------------------------------------
# CL101 use-after-donate


DONATING_DEF = '''
    from functools import partial
    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def _converge(mat, n):
        return mat * n

    def _converge_nodonate(mat, n):
        return mat * n
'''


def test_cl101_read_after_donate_fires():
    r = lint({"crdt_tpu/ops/x.py": DONATING_DEF + '''
    def caller(mat):
        out = _converge(mat, 3)
        return mat.sum() + out
    '''})
    assert "CL101" in codes(r)


def test_cl101_rebind_is_clean():
    r = lint({"crdt_tpu/ops/x.py": DONATING_DEF + '''
    def caller(mat):
        mat = _converge(mat, 3)
        return mat.sum()
    '''})
    assert "CL101" not in codes(r)


def test_cl101_self_attribute_tracking():
    r = lint({"crdt_tpu/ops/x.py": DONATING_DEF + '''
    class C:
        def run(self):
            out = _converge(self._mat, 3)
            return self._mat.shape
    '''})
    assert "CL101" in codes(r)


def test_cl101_branches_do_not_cross():
    # donation in one branch must not poison the sibling branch
    r = lint({"crdt_tpu/ops/x.py": DONATING_DEF + '''
    def caller(mat, flag):
        if flag:
            out = _converge(mat, 3)
        else:
            out = mat.sum()
        return out
    '''})
    assert "CL101" not in codes(r)


def test_cl101_donation_in_if_test_fires():
    # a donation INSIDE the if-test expression flows into both
    # branches and past the if (the test is evaluated exactly once,
    # before either branch runs)
    r = lint({"crdt_tpu/ops/x.py": DONATING_DEF + '''
    def caller(mat):
        if _converge(mat, 3):
            return mat.sum()
        return mat.shape
    '''})
    assert codes(r).count("CL101") == 2


def test_cl101_loop_without_rebind_fires():
    r = lint({"crdt_tpu/ops/x.py": DONATING_DEF + '''
    def caller(mat):
        acc = 0
        for i in range(3):
            acc += _converge(mat, i)
        return acc
    '''})
    assert any(
        f.code == "CL101" and "loop" in f.message for f in r.findings
    )


def test_cl101_loop_with_rebind_clean():
    r = lint({"crdt_tpu/ops/x.py": DONATING_DEF + '''
    def caller(mat):
        for i in range(3):
            mat = _converge(mat, i)
        return mat
    '''})
    assert "CL101" not in codes(r)


def test_cl101_factory_result_in_loop():
    # the gossip factory pattern: step donates arg 0; packed rebuilt
    # each round is clean, reused is a finding
    src = '''
    import jax

    def make_step(n):
        def step(block, dels):
            return block * n
        return jax.jit(step, donate_argnums=(0,))

    def good(n, build):
        step = make_step(n)
        for i in range(4):
            block = build(i)
            out = step(block, ())
        return out

    def bad(n, block):
        step = make_step(n)
        for i in range(4):
            out = step(block, ())
        return out
    '''
    r = lint({"crdt_tpu/parallel/x.py": src})
    bad_lines = [f for f in r.findings if f.code == "CL101"]
    assert len(bad_lines) == 1
    assert "block" in bad_lines[0].message


def test_cl102_missing_twin_and_satisfied_twin():
    src = '''
    from functools import partial
    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def _converge_solo(mat):
        return mat
    '''
    r = lint({"crdt_tpu/ops/x.py": src})
    assert "CL102" in codes(r)
    # DONATING_DEF has a _nodonate twin: no CL102
    r2 = lint({"crdt_tpu/ops/y.py": DONATING_DEF})
    assert "CL102" not in codes(r2)


# ---------------------------------------------------------------------------
# CL201/202/203 registry conformance


def reg(*names, events=()):
    r = Registry()
    for n in names:
        r.add(n, "metric", "README.md", 1)
    for n in events:
        r.add(n, "event", "README.md", 2)
    return r


def test_cl201_unregistered_metric_fires():
    r = lint(
        {"crdt_tpu/core/x.py": '''
    def f(tracer):
        tracer.count("engine.bogus_counter", 1)
    '''},
        registry=reg("engine.real"),
    )
    assert "CL201" in codes(r)
    # and the documented-but-dead entry fires the other way
    assert "CL202" in codes(r)


def test_cl202_documented_and_emitted_clean():
    r = lint(
        {"crdt_tpu/core/x.py": '''
    def f(tracer):
        tracer.count("engine.real", 1)
    '''},
        registry=reg("engine.real"),
    )
    assert codes(r) == []


def test_cl203_computed_name_fires_and_emits_declares():
    src_bad = '''
    def f(tracer, name):
        tracer.count(name, 1)
    '''
    r = lint({"crdt_tpu/core/x.py": src_bad}, registry=reg("engine.real"))
    assert "CL203" in codes(r)

    src_declared = '''
    def f(rec, kind):
        # crdtlint: emits=fault.drop,fault.dup
        rec.record(f"fault.{kind}", size=1)
    '''
    r2 = lint(
        {"crdt_tpu/net/x.py": src_declared},
        registry=reg(events=("fault.drop", "fault.dup")),
    )
    assert "CL203" not in codes(r2)
    assert "CL202" not in codes(r2)  # declared names count as emitted


def test_cl203_symbol_uses_innermost_enclosing_function():
    # the old lineno-keyed map attributed a closure's lines to the
    # OUTERMOST function, so two closures' findings could collide on
    # one symbol (and an allowlisted outer name would leak to nested
    # helpers); the fingerprint must anchor on the innermost def
    r = lint({"crdt_tpu/core/x.py": '''
    def outer(tracer, name):
        def inner():
            tracer.count(name, 1)
        return inner
    '''}, registry=reg("engine.real"))
    cl = [f for f in r.findings if f.code == "CL203"]
    assert [f.symbol for f in cl] == ["inner:count"]


def test_cl201_counter_kwarg_literal_checked():
    r = lint(
        {"crdt_tpu/storage/x.py": '''
    def f():
        retry(lambda: 0, counter="persist.bogus")
    '''},
        registry=reg("persist.real"),
    )
    assert "CL201" in codes(r)


# ---------------------------------------------------------------------------
# CL301/302/303 exception discipline


def test_cl301_bare_except_in_codec():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_thing(b):
        try:
            return b[0]
        except:
            return None
    '''})
    assert "CL301" in codes(r)


def test_cl302_decode_raises_non_valueerror():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_thing(b):
        if not b:
            raise KeyError("empty")
        return b[0]
    '''})
    assert "CL302" in codes(r)


def test_cl302_valueerror_and_encode_paths_clean():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_thing(b):
        if not b:
            raise ValueError("empty")
        return b[0]

    def write_thing(v):
        raise TypeError("encode paths may type-check")
    '''})
    assert "CL302" not in codes(r)


def test_cl303_guard_catches_simulated_crash():
    r = lint({"crdt_tpu/guard/x.py": '''
    def ladder(fn):
        try:
            return fn()
        except SimulatedCrash:
            return None
    '''})
    assert "CL303" in codes(r)


def test_cl30x_out_of_scope_module_clean():
    r = lint({"crdt_tpu/models/x.py": '''
    def decode_thing(b):
        try:
            raise KeyError("x")
        except:
            pass
    '''})
    assert "CL301" not in codes(r)
    assert "CL302" not in codes(r)


# ---------------------------------------------------------------------------
# CL401 transfer seam


def test_cl401_device_put_outside_seam():
    r = lint({"crdt_tpu/models/x.py": '''
    import jax

    def upload(arr):
        return jax.device_put(arr)
    '''})
    assert "CL401" in codes(r)


def test_cl401_asarray_of_dispatch_result():
    r = lint({"crdt_tpu/models/x.py": DONATING_DEF + '''
    import numpy as np

    def fetch(mat):
        out = _converge_nodonate(mat, 2)
        dev = _converge(out, 2)
        return np.asarray(dev)
    '''})
    assert any(
        f.code == "CL401" and "asarray" in f.message for f in r.findings
    )


def test_cl401_rebind_through_xfer_fetch_is_clean():
    # `dev = xfer_fetch(dev, n)` yields a HOST array — the later
    # asarray/.item() is not a seam bypass (the whole-function taint
    # pass used to flag it anyway, forcing bogus baseline entries)
    r = lint({"crdt_tpu/models/x.py": DONATING_DEF + '''
    import numpy as np

    def fetch(mat, xfer_fetch):
        dev = _converge(mat, 2)
        dev = xfer_fetch(dev, 8)
        return np.asarray(dev) + dev.item()
    '''})
    assert "CL401" not in codes(r)


def test_cl401_asarray_before_dispatch_is_clean():
    # the host-materialization textually PRECEDES the dispatch that
    # binds the name — order matters, this is not a bypass
    r = lint({"crdt_tpu/models/x.py": DONATING_DEF + '''
    import numpy as np

    def fetch(dev, mat):
        host = np.asarray(dev)
        dev = _converge(mat, 2)
        return host
    '''})
    assert "CL401" not in codes(r)


def test_cl401_same_line_rebinding_asarray_still_fires():
    # `x = np.asarray(x)` on a tainted x is the bypass itself; the
    # rebind must not untaint before the use is checked
    r = lint({"crdt_tpu/models/x.py": DONATING_DEF + '''
    import numpy as np

    def fetch(mat):
        dev = _converge(mat, 2)
        dev = np.asarray(dev)
        return dev
    '''})
    assert any(
        f.code == "CL401" and "asarray" in f.message for f in r.findings
    )


def test_cl401_seam_module_itself_clean():
    r = lint({"crdt_tpu/ops/device.py": '''
    import jax

    def xfer_put(arr):
        return jax.device_put(arr)
    '''})
    assert "CL401" not in codes(r)


# ---------------------------------------------------------------------------
# CL501-504 determinism


def test_cl501_time_time_in_core():
    r = lint({"crdt_tpu/ops/x.py": '''
    import time

    def stamp():
        return time.time()
    '''})
    assert "CL501" in codes(r)
    # perf_counter is fine; net/ modules are out of scope
    r2 = lint({"crdt_tpu/ops/y.py": '''
    import time

    def span():
        return time.perf_counter()
    '''})
    assert "CL501" not in codes(r2)
    r3 = lint({"crdt_tpu/net/x.py": '''
    import time

    def stamp():
        return time.time()
    '''})
    assert "CL501" not in codes(r3)


def test_cl502_unseeded_randomness():
    r = lint({"crdt_tpu/parallel/x.py": '''
    import random
    import numpy as np

    def jitter():
        return random.random()

    def rng():
        return np.random.default_rng()
    '''})
    assert codes(r).count("CL502") == 2
    r2 = lint({"crdt_tpu/parallel/y.py": '''
    import numpy as np

    def rng(seed):
        return np.random.default_rng(seed)
    '''})
    assert "CL502" not in codes(r2)


def test_cl503_unseeded_fault_schedule():
    faults = '''
    class FaultSchedule:
        def __init__(self, seed: int = 0, *, drop=0.0):
            self.seed = seed
    '''
    user_bad = '''
    from crdt_tpu.net.faults import FaultSchedule

    def chaos():
        return FaultSchedule(drop=0.5)
    '''
    user_good = '''
    from crdt_tpu.net.faults import FaultSchedule

    def chaos():
        return FaultSchedule(seed=7, drop=0.5)
    '''
    r = lint({
        "crdt_tpu/net/faults.py": faults,
        "crdt_tpu/parallel/bad.py": user_bad,
        "crdt_tpu/parallel/good.py": user_good,
    })
    hits = [f for f in r.findings if f.code == "CL503"]
    assert len(hits) == 1
    assert hits[0].path.endswith("bad.py")


def test_cl504_set_iteration():
    r = lint({"crdt_tpu/core/x.py": '''
    def pack(items):
        out = []
        for k in set(items):
            out.append(k)
        return out
    '''})
    assert "CL504" in codes(r)
    r2 = lint({"crdt_tpu/core/y.py": '''
    def pack(items):
        return [k for k in sorted(set(items))]
    '''})
    assert "CL504" not in codes(r2)


# ---------------------------------------------------------------------------
# CL601 thread-shared state


def test_cl601_unlocked_global_fires_and_locked_clean():
    r = lint({"crdt_tpu/obs/tracer.py": '''
    import threading

    _tracer = dict()
    _LOCK = threading.Lock()


    def set_bad(v):
        global _tracer
        _tracer = v


    def set_good(v):
        global _tracer
        with _LOCK:
            _tracer = v


    def mutate_bad(k, v):
        _tracer[k] = v


    def mutate_good(k, v):
        with _LOCK:
            _tracer[k] = v
    '''})
    cl = [f for f in r.findings if f.code == "CL601"]
    assert {f.symbol for f in cl} == {"set_bad:_tracer",
                                      "mutate_bad:_tracer"}


def test_cl601_lock_like_names_only():
    # `self._blocker` contains 'lock' as a raw substring (b·lock) but
    # is NOT a lock — it must not silence the checker; real lock
    # spellings (_CACHE_LOCK, threading.Lock(), cacheLock) all count
    r = lint({"crdt_tpu/obs/tracer.py": '''
    import threading

    _events = dict()
    _CACHE_LOCK = threading.Lock()
    cacheLock = threading.Lock()


    class W:
        def mutate_blocker(self, k, v):
            with self._blocker:
                _events[k] = v

        def mutate_unblocked(self, k, v):
            with _unblocked_region():
                _events[k] = v

        def mutate_const_lock(self, k, v):
            with _CACHE_LOCK:
                _events[k] = v

        def mutate_ctor_lock(self, k, v):
            with threading.Lock():
                _events[k] = v

        def mutate_camel_lock(self, k, v):
            with cacheLock:
                _events[k] = v
    '''})
    cl = {f.symbol for f in r.findings if f.code == "CL601"}
    assert cl == {"mutate_blocker:_events", "mutate_unblocked:_events"}


def test_cl601_untargeted_module_clean():
    r = lint({"crdt_tpu/core/x.py": '''
    _cache = {}

    def put(k, v):
        _cache[k] = v
    '''})
    assert "CL601" not in codes(r)


# ---------------------------------------------------------------------------
# suppression + baseline machinery


VIOLATION = '''
import jax

def upload(arr):
    return jax.device_put(arr)
'''


def test_inline_disable_suppresses():
    src = '''
    import jax

    def upload(arr):
        return jax.device_put(arr)  # crdtlint: disable=CL401
    '''
    r = lint({"crdt_tpu/models/x.py": src})
    assert "CL401" not in codes(r)
    assert any(f.code == "CL401" for f in r.suppressed)


def test_inline_disable_on_line_above():
    src = '''
    import jax

    def upload(arr):
        # crdtlint: disable=CL401
        return jax.device_put(arr)
    '''
    r = lint({"crdt_tpu/models/x.py": src})
    assert "CL401" not in codes(r)


def test_inline_disable_wrong_code_does_not_suppress():
    src = '''
    import jax

    def upload(arr):
        return jax.device_put(arr)  # crdtlint: disable=CL999
    '''
    r = lint({"crdt_tpu/models/x.py": src})
    assert "CL401" in codes(r)


def test_disable_file_directive():
    src = '''
    # crdtlint: disable-file=CL401
    import jax

    def upload(arr):
        return jax.device_put(arr)

    def download(arr):
        return jax.device_get(arr)
    '''
    r = lint({"crdt_tpu/models/x.py": src})
    assert "CL401" not in codes(r)
    assert len([f for f in r.suppressed if f.code == "CL401"]) == 2


def test_baseline_roundtrip(tmp_path):
    # 1. a violation fires
    r = lint({"crdt_tpu/models/x.py": VIOLATION})
    assert len(r.findings) == 1
    # 2. write it to a baseline file, justify it, reload
    path = tmp_path / "baseline.json"
    write_baseline(str(path), r.findings)
    data = json.loads(path.read_text())
    assert len(data["entries"]) == 1
    data["entries"][0]["justification"] = "synthetic fixture"
    path.write_text(json.dumps(data))
    base = load_baseline(str(path))
    # 3. the same violation is now baselined, not open
    r2 = lint({"crdt_tpu/models/x.py": VIOLATION}, baseline=base)
    assert r2.findings == []
    assert len(r2.baselined) == 1
    # 4. fixing the code leaves a stale baseline entry (warned)
    r3 = lint(
        {"crdt_tpu/models/x.py": "def upload(arr):\n    return arr\n"},
        baseline=base,
    )
    assert r3.findings == [] and r3.baselined == []
    assert len(r3.stale_baseline) == 1


def test_baseline_requires_justification(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({
        "entries": [{"fingerprint": "a|CL401|b", "justification": ""}]
    }))
    with pytest.raises(BaselineError):
        load_baseline(str(path))


def test_baseline_fingerprint_survives_line_moves(tmp_path):
    r = lint({"crdt_tpu/models/x.py": VIOLATION})
    fp = r.findings[0].fingerprint
    shifted = "'''module docstring'''\nX = 1\n" + VIOLATION
    r2 = lint({"crdt_tpu/models/x.py": shifted})
    assert r2.findings[0].fingerprint == fp


def test_syntax_error_is_a_finding():
    r = lint({"crdt_tpu/models/x.py": "def broken(:\n"})
    assert [f.code for f in r.findings] == ["CL000"]


def test_run_lint_public_entry(tmp_path):
    """The public run_lint() surface used by the CLI and bench."""
    config = LintConfig(
        repo_root=str(tmp_path), readme_path="", smoke_test_path="",
        baseline_path=str(tmp_path / "none.json"),
    )
    result = run_lint(
        [("crdt_tpu/models/x.py", VIOLATION)], config=config
    )
    assert [f.code for f in result.findings] == ["CL401"]
    assert result.total_raw == 1


# ---------------------------------------------------------------------------
# review-pass regressions: analyzer gaps found after the first run


def test_cl601_annotated_module_global_fires():
    # `X: set = set()` binds the same shared state as `X = set()` —
    # a type annotation must not silence CL601 (the ops/device.py
    # memo-cache shape the first review pass slipped through)
    r = lint({"crdt_tpu/ops/device.py": '''
    _CACHE: set = set()

    def remember(key):
        _CACHE.add(key)
    '''})
    assert "CL601" in codes(r)


def test_cl601_annotated_global_locked_clean():
    r = lint({"crdt_tpu/ops/device.py": '''
    import threading

    _CACHE: set = set()
    _CACHE_LOCK = threading.Lock()

    def remember(key):
        with _CACHE_LOCK:
            _CACHE.add(key)
    '''})
    assert "CL601" not in codes(r)


def test_cl401_method_form_block_until_ready_fires():
    # `out.block_until_ready()` — the array-method spelling — is the
    # same wait as `jax.block_until_ready(out)` and must not slip
    # past the seam checker
    r = lint({"crdt_tpu/models/x.py": '''
    def wait(out):
        out.block_until_ready()
        return out
    '''})
    assert "CL401" in codes(r)


def test_cl401_method_form_on_call_result_fires():
    # even with no dotted receiver (`f(x).block_until_ready()`)
    r = lint({"crdt_tpu/models/x.py": '''
    def wait(f, x):
        f(x).block_until_ready()
    '''})
    assert "CL401" in codes(r)


def test_cl101_local_def_shadows_foreign_donating_name():
    # module B's own non-donating `_step` shadows module A's donating
    # `_step`: reading the arg after B's local call is NOT a
    # use-after-donate (the collision used to invent one)
    r = lint({
        "crdt_tpu/ops/a.py": '''
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def _step(mat):
            return mat
        ''',
        "crdt_tpu/models/b.py": '''
        def _step(mat):
            return mat + 1

        def caller(mat):
            out = _step(mat)
            return mat.sum() + out
        ''',
    })
    assert "CL101" not in codes(r)


def test_cl101_same_name_donating_defs_keep_their_argnums():
    # two modules donate under one name with DIFFERENT argnums; the
    # old name-keyed index let one overwrite the other, hiding one
    # module's real use-after-donate — both must fire
    r = lint({
        "crdt_tpu/ops/a.py": '''
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def _step(x, y):
            return x + y

        def caller(x, y):
            out = _step(x, y)
            return x.sum() + out
        ''',
        "crdt_tpu/ops/b.py": '''
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(1,))
        def _step(x, y):
            return x + y

        def caller(x, y):
            out = _step(x, y)
            return y.sum() + out
        ''',
    })
    assert codes(r).count("CL101") == 2


def test_cl101_import_resolves_defining_module():
    # B imports A's donating `_step`; the import picks A's argnums
    # even though B defines nothing itself
    r = lint({
        "crdt_tpu/ops/a.py": '''
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def _step(mat):
            return mat
        ''',
        "crdt_tpu/models/b.py": '''
        from crdt_tpu.ops.a import _step

        def caller(mat):
            out = _step(mat)
            return mat.sum() + out
        ''',
    })
    assert "CL101" in codes(r)


MOD_ATTR_DEFS = {
    "crdt_tpu/ops/a.py": '''
    from functools import partial
    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def _step(x, y):
        return x + y
    ''',
    "crdt_tpu/ops/b.py": '''
    from functools import partial
    import jax

    @partial(jax.jit, donate_argnums=(1,))
    def _step(x, y):
        return x + y
    ''',
}


def test_cl101_module_attr_call_resolves_receiver_module():
    # `b._step(x, y)` must take b's argnums (donates y), not whichever
    # same-named def was scanned first — reading x stays clean, reading
    # y fires
    r = lint({
        **MOD_ATTR_DEFS,
        "crdt_tpu/models/c.py": '''
        from crdt_tpu.ops import b

        def caller(x, y):
            out = b._step(x, y)
            return x.sum() + y.sum() + out
        ''',
    })
    hits = [f for f in r.findings if f.code == "CL101"]
    assert len(hits) == 1 and "`y`" in hits[0].message


def test_cl101_module_attr_full_dotted_path_resolves():
    # plain `import crdt_tpu.ops.a` — the attribute chain spells the
    # real module path, so a's argnums (donates x) apply
    r = lint({
        **MOD_ATTR_DEFS,
        "crdt_tpu/models/c.py": '''
        import crdt_tpu.ops.a

        def caller(x, y):
            out = crdt_tpu.ops.a._step(x, y)
            return x.sum() + out
        ''',
    })
    hits = [f for f in r.findings if f.code == "CL101"]
    assert len(hits) == 1 and "`x`" in hits[0].message


def test_cl101_module_attr_without_donating_def_refuses_guess():
    # the receiver resolves to a module that defines NO donating
    # `_step` — another module's same-named argnums must not be lent
    r = lint({
        **MOD_ATTR_DEFS,
        "crdt_tpu/ops/plain.py": '''
        def _step(x, y):
            return x + y
        ''',
        "crdt_tpu/models/c.py": '''
        from crdt_tpu.ops import plain

        def caller(x, y):
            out = plain._step(x, y)
            return x.sum() + y.sum() + out
        ''',
    })
    assert "CL101" not in codes(r)


def test_write_baseline_preserves_justifications(tmp_path):
    # regenerating a baseline must MERGE: hand-written justifications
    # for still-live findings survive verbatim, only open findings get
    # TODO skeletons (the old CLI wrote open-findings-only, wiping the
    # whole ledger)
    r = lint({"crdt_tpu/models/x.py": VIOLATION})
    path = tmp_path / "baseline.json"
    write_baseline(str(path), r.findings)
    data = json.loads(path.read_text())
    data["entries"][0]["justification"] = "hand-written reason"
    path.write_text(json.dumps(data))
    base = load_baseline(str(path))

    # a second, new violation appears; the first is baselined
    two = VIOLATION + '''
    def upload2(arr):
        return jax.device_put(arr)
    '''
    r2 = lint({"crdt_tpu/models/x.py": two}, baseline=base)
    assert len(r2.findings) == 1 and len(r2.baselined) == 1
    # the __main__ --write-baseline flow: preserved = still-live
    # baseline entries, skeletons only for the open finding
    live = {f.fingerprint for f in r2.baselined}
    preserved = [e for fp, e in base.items() if fp in live]
    write_baseline(str(path), r2.findings, preserved)
    merged = load_baseline(str(path))
    assert len(merged) == 2
    justs = sorted(e["justification"] for e in merged.values())
    assert justs == ["TODO: justify or fix", "hand-written reason"]


def test_write_baseline_cli_merges_not_clobbers(tmp_path, capsys):
    """End-to-end through the CLI entry: pointing --write-baseline at
    the live baseline file must not wipe existing justifications."""
    from tools.crdtlint.__main__ import main

    src = tmp_path / "ops" / "bad.py"
    src.parent.mkdir()
    src.write_text(
        "import jax\n\n\ndef f(x):\n    return jax.device_put(x)\n"
    )
    bl = tmp_path / "bl.json"
    # generation 1: one skeleton; justify it by hand
    assert main([str(src), "--baseline", str(bl),
                 "--write-baseline", str(bl)]) == 0
    data = json.loads(bl.read_text())
    assert len(data["entries"]) == 1
    data["entries"][0]["justification"] = "hand-written reason"
    bl.write_text(json.dumps(data))
    # a second violation lands; regenerate against the live baseline
    src.write_text(
        src.read_text()
        + "\n\ndef g(x):\n    return jax.device_get(x)\n"
    )
    assert main([str(src), "--baseline", str(bl),
                 "--write-baseline", str(bl)]) == 0
    merged = load_baseline(str(bl))
    assert len(merged) == 2
    justs = sorted(e["justification"] for e in merged.values())
    assert justs == ["TODO: justify or fix", "hand-written reason"]
    # --no-baseline only changes reporting; combined with
    # --write-baseline it must STILL merge against the ledger, not
    # rewrite every live entry as a TODO skeleton
    assert main([str(src), "--baseline", str(bl), "--no-baseline",
                 "--write-baseline", str(bl)]) == 0
    remerged = load_baseline(str(bl))
    assert len(remerged) == 2
    justs = sorted(e["justification"] for e in remerged.values())
    assert justs == ["TODO: justify or fix", "hand-written reason"]
    capsys.readouterr()


# ---------------------------------------------------------------------------
# round 16: call-graph + CFG core


def test_callgraph_resolution_and_thread_reachability():
    """Cross-module strong resolution, weak method fan-out, thread
    roots, and the reachability closure the CL803 discovery rides."""
    from tools.crdtlint.callgraph import build_callgraph, STRONG
    from tools.crdtlint.core import Module

    mods = [
        Module("crdt_tpu/a.py", textwrap.dedent('''
            from crdt_tpu.b import helper
            import threading

            def start():
                t = threading.Thread(target=entry)
                t.start()

            def entry():
                helper()
        ''')),
        Module("crdt_tpu/b.py", textwrap.dedent('''
            def helper():
                pass
        ''')),
    ]
    cg = build_callgraph(mods)
    assert "crdt_tpu/a.py:entry" in cg.thread_roots
    assert "crdt_tpu/b.py:helper" in cg.thread_reachable
    edges = {(c.callee, c.confidence)
             for c in cg.edges.get("crdt_tpu/a.py:entry", ())}
    assert ("crdt_tpu/b.py:helper", STRONG) in edges


def test_callgraph_collision_links_all_candidates():
    """Two classes defining the same method name: an unresolvable
    receiver fans out to BOTH (weak edges) and the collision is
    counted — over-approximation is the right direction for
    reachability, and the stats make the guessing visible."""
    from tools.crdtlint.callgraph import build_callgraph, WEAK
    from tools.crdtlint.core import Module

    mods = [
        Module("crdt_tpu/a.py", textwrap.dedent('''
            class A:
                def run(self):
                    pass

            class B:
                def run(self):
                    pass

            def call(x):
                x.run()
        ''')),
    ]
    cg = build_callgraph(mods)
    callees = {c.callee for c in cg.edges.get("crdt_tpu/a.py:call", ())}
    assert callees == {"crdt_tpu/a.py:A.run", "crdt_tpu/a.py:B.run"}
    assert all(
        c.confidence == WEAK
        for c in cg.edges["crdt_tpu/a.py:call"]
    )
    assert cg.collisions >= 1
    assert cg.stats()["functions"] == 3


def test_callgraph_local_def_shadows_import():
    """A local def wins over a same-named def in another module —
    the donate checker's shadowing rule, now shared machinery."""
    from tools.crdtlint.callgraph import build_callgraph
    from tools.crdtlint.core import Module

    mods = [
        Module("crdt_tpu/a.py", textwrap.dedent('''
            def helper():
                pass

            def caller():
                helper()
        ''')),
        Module("crdt_tpu/b.py", textwrap.dedent('''
            def helper():
                pass
        ''')),
    ]
    cg = build_callgraph(mods)
    callees = {c.callee
               for c in cg.edges.get("crdt_tpu/a.py:caller", ())}
    assert callees == {"crdt_tpu/a.py:helper"}


def test_cfg_exception_edges():
    """The lite CFG's exception edges: a statement inside try lands
    in the handler; a finally is reached on both the normal and the
    unwinding path."""
    import ast as _ast

    from tools.crdtlint.cfg import CFG, EXIT, RAISE, every_path_hits

    fn = _ast.parse(textwrap.dedent('''
        def f(work, cleanup):
            try:
                work()
            finally:
                cleanup()
    ''')).body[0]
    cfg = CFG(fn)

    def hits_cleanup(st):
        return any(
            isinstance(n, _ast.Call) and getattr(n.func, "id", "")
            == "cleanup"
            for n in _ast.walk(st)
        )

    # every path — normal AND raising — passes through cleanup()
    missing = every_path_hits(
        cfg, cfg.entry, hits_cleanup, with_exc=True
    )
    assert missing is None

    fn2 = _ast.parse(textwrap.dedent('''
        def g(work, cleanup):
            work()
            cleanup()
    ''')).body[0]
    cfg2 = CFG(fn2)

    # without try/finally, work()'s exception edge skips cleanup
    missing = every_path_hits(
        cfg2, cfg2.entry, hits_cleanup, with_exc=True
    )
    assert missing == RAISE
    # ...but every NORMAL path still hits it
    assert every_path_hits(cfg2, cfg2.entry, hits_cleanup) is None


# ---------------------------------------------------------------------------
# CL7xx trace purity


def test_cl701_tracer_call_in_jitted_body():
    r = lint({"crdt_tpu/ops/x.py": '''
    import jax
    from crdt_tpu.obs.tracer import get_tracer

    @jax.jit
    def step(x):
        get_tracer().count("engine.ticks")
        return x
    '''})
    assert "CL701" in codes(r)


def test_cl701_interprocedural_through_helper():
    """The side effect sits one call away from the jit root — only
    the call-graph closure sees it."""
    r = lint({"crdt_tpu/ops/x.py": '''
    import jax

    def note(x):
        print("traced!", x)
        return x

    @jax.jit
    def step(x):
        return note(x)
    '''})
    assert "CL701" in codes(r)


def test_cl701_host_dispatcher_clean():
    """The same tracer call OUTSIDE any traced body is the sanctioned
    dispatcher pattern."""
    r = lint({"crdt_tpu/ops/x.py": '''
    import jax
    from crdt_tpu.obs.tracer import get_tracer

    @jax.jit
    def step(x):
        return x

    def dispatch(x):
        get_tracer().count("engine.ticks")
        return step(x)
    '''})
    assert "CL701" not in codes(r)


def test_cl702_env_read_in_lax_cond_branch():
    """The sv_deficit shape that motivated the fix: a nested def
    passed to lax.cond reads the env at trace time."""
    r = lint({"crdt_tpu/ops/x.py": '''
    import os
    import jax

    def outer(x):
        def a(v):
            if os.environ.get("CRDT_TPU_PALLAS"):
                return v
            return v + 1

        def b(v):
            return v

        return jax.lax.cond(x.sum() > 0, a, b, x)
    '''})
    assert "CL702" in codes(r)


def test_cl702_host_env_read_clean():
    r = lint({"crdt_tpu/ops/x.py": '''
    import os

    def mode():
        return os.environ.get("CRDT_TPU_PALLAS", "auto")
    '''})
    assert "CL702" not in codes(r)


def test_cl703_host_sync_in_traced_body():
    r = lint({"crdt_tpu/ops/x.py": '''
    import jax
    import numpy as np

    @jax.jit
    def step(x):
        h = np.asarray(x)
        return h.sum()
    '''})
    assert "CL703" in codes(r)


def test_cl704_captured_mutation_and_local_clean():
    r = lint({"crdt_tpu/ops/x.py": '''
    import jax

    _MEMO = {}

    @jax.jit
    def bad(x):
        _MEMO["x"] = x
        return x

    @jax.jit
    def good(x):
        local = {}
        local["x"] = x
        return x
    '''})
    found = [f for f in r.findings if f.code == "CL704"]
    assert len(found) == 1 and "bad" in found[0].symbol


def test_cl7xx_suppressed_and_baselined():
    src = '''
    import jax
    from crdt_tpu.obs.tracer import get_tracer

    @jax.jit
    def step(x):
        get_tracer().count("engine.ticks")  # crdtlint: disable=CL701
        return x
    '''
    r = lint({"crdt_tpu/ops/x.py": src})
    assert "CL701" not in codes(r)
    assert any(f.code == "CL701" for f in r.suppressed)
    # baselined: same snippet without the inline disable
    src2 = src.replace("  # crdtlint: disable=CL701", "")
    r2 = lint({"crdt_tpu/ops/x.py": src2})
    fp = next(f for f in r2.findings if f.code == "CL701").fingerprint
    r3 = lint({"crdt_tpu/ops/x.py": src2}, baseline={
        fp: {"fingerprint": fp, "justification": "pinned by test"}
    })
    assert "CL701" not in codes(r3)
    assert any(f.code == "CL701" for f in r3.baselined)


# ---------------------------------------------------------------------------
# CL8xx lock discipline


def test_cl801_lock_order_cycle_fires_and_ordered_clean():
    bad = {
        "crdt_tpu/a.py": '''
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def ab():
        with LOCK_A:
            with LOCK_B:
                pass

    def ba():
        with LOCK_B:
            with LOCK_A:
                pass
    '''}
    assert "CL801" in codes(lint(bad))
    good = {
        "crdt_tpu/a.py": '''
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def ab():
        with LOCK_A:
            with LOCK_B:
                pass

    def ab2():
        with LOCK_A:
            with LOCK_B:
                pass
    '''}
    assert "CL801" not in codes(lint(good))


def test_cl801_interprocedural_cycle():
    """The inversion hides behind a call: f holds A and calls g,
    which takes B; h holds B and calls k, which takes A."""
    r = lint({"crdt_tpu/a.py": '''
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def take_b():
        with LOCK_B:
            pass

    def take_a():
        with LOCK_A:
            pass

    def f():
        with LOCK_A:
            take_b()

    def h():
        with LOCK_B:
            take_a()
    '''})
    assert "CL801" in codes(r)


def test_cl801_lock_alias_suppresses_phantom_cycle():
    """`self._lock = other._lock` aliases the two identities: the
    apparent A->B / B->A inversion is one lock taken twice in one
    direction — no cycle."""
    r = lint({"crdt_tpu/a.py": '''
    import threading

    class Owner:
        def __init__(self):
            self._lock = threading.Lock()

    class Borrower:
        def __init__(self, owner):
            self._lock = owner._lock

        def locked_pair(self, owner):
            with self._lock:
                with owner._lock:
                    pass

        def locked_pair_rev(self, owner):
            with owner._lock:
                with self._lock:
                    pass
    '''})
    assert "CL801" not in codes(r)


def test_cl802_blocking_under_lock_and_outside_clean():
    bad = {"crdt_tpu/a.py": '''
    import subprocess
    import threading

    _BUILD_LOCK = threading.Lock()

    def build():
        with _BUILD_LOCK:
            subprocess.run(["make"])
    '''}
    assert "CL802" in codes(lint(bad))
    good = {"crdt_tpu/a.py": '''
    import subprocess
    import threading

    _BUILD_LOCK = threading.Lock()

    def build():
        subprocess.run(["make"])
        with _BUILD_LOCK:
            done = True
        return done
    '''}
    assert "CL802" not in codes(lint(good))


def test_cl802_interprocedural_blocking_callee():
    """The kv.py _load shape: the blocking call hides inside a
    helper invoked under the lock."""
    r = lint({"crdt_tpu/a.py": '''
    import subprocess
    import threading

    _lib_lock = threading.Lock()

    def _build_so():
        subprocess.run(["g++"])

    def _load():
        with _lib_lock:
            _build_so()
    '''})
    found = [f for f in r.findings if f.code == "CL802"]
    assert found and "via `_build_so`" in found[0].message


def test_cl803_thread_shared_guarded_field():
    src = '''
    import threading

    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            with self._lock:
                self.n += 1

        def reset(self):
            self.n = 0

    def worker():
        Shared().bump()

    def spawn():
        return threading.Thread(target=worker)
    '''
    r = lint({"crdt_tpu/models/x.py": src})
    found = [f for f in r.findings if f.code == "CL803"]
    assert len(found) == 1
    assert "reset" in found[0].symbol
    # consistent locking is clean
    src_good = src.replace(
        "        def reset(self):\n            self.n = 0",
        "        def reset(self):\n            with self._lock:\n"
        "                self.n = 0",
    )
    assert src_good != src
    r2 = lint({"crdt_tpu/models/x.py": src_good})
    assert "CL803" not in codes(r2)


def test_cl803_init_writes_exempt_and_unthreaded_clean():
    """__init__ writes don't count (object unshared), and a class no
    thread reaches is out of scope entirely."""
    r = lint({"crdt_tpu/models/x.py": '''
    import threading

    class NotShared:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            with self._lock:
                self.n += 1

        def reset(self):
            self.n = 0
    '''})
    assert "CL803" not in codes(r)


# ---------------------------------------------------------------------------
# CL9xx async-handle / paired-protocol discipline


def test_cl901_dropped_handle_fires():
    r = lint({"crdt_tpu/models/x.py": '''
    from crdt_tpu.ops import packed

    def leak(plan):
        h = packed.converge_async(plan)
        return 0
    '''})
    assert "CL901" in codes(r)


def test_cl901_branch_without_fetch_fires():
    r = lint({"crdt_tpu/models/x.py": '''
    from crdt_tpu.ops import packed

    def maybe(plan, flag):
        h = packed.converge_async(plan)
        if flag:
            return packed.converge_fetch(h)
        return None
    '''})
    assert "CL901" in codes(r)


def test_cl901_all_paths_consumed_clean():
    r = lint({"crdt_tpu/models/x.py": '''
    from crdt_tpu.ops import packed

    def both(plan, flag):
        h = packed.converge_async(plan)
        if flag:
            return packed.converge_fetch(h)
        return packed.converge_fetch(h)

    def queued(plan, q):
        h = packed.converge_async(plan)
        q.put((h, 1))

    def returned(plan):
        return packed.converge_async(plan)
    '''})
    assert "CL901" not in codes(r)


def test_cl901_loop_rebind_without_consume_fires():
    r = lint({"crdt_tpu/models/x.py": '''
    from crdt_tpu.ops import packed

    def spin(plans):
        for p in plans:
            h = packed.converge_async(p)
        return packed.converge_fetch(h)
    '''})
    assert "CL901" in codes(r)


def test_cl901_bare_expression_drop_fires():
    r = lint({"crdt_tpu/models/x.py": '''
    from crdt_tpu.ops import packed

    def fire_and_forget(plan):
        packed.converge_async(plan)
    '''})
    found = [f for f in r.findings if f.code == "CL901"]
    assert found and "drop" in found[0].symbol


def test_cl902_exception_edge_skips_closer():
    r = lint({"crdt_tpu/obs/x.py": '''
    import jax

    def capture(log_dir, work):
        jax.profiler.start_trace(log_dir)
        work()
        jax.profiler.stop_trace()
    '''})
    found = [f for f in r.findings if f.code == "CL902"]
    assert found and "exception" in found[0].symbol


def test_cl902_finally_closes_clean():
    r = lint({"crdt_tpu/obs/x.py": '''
    import jax

    def capture(log_dir, work):
        jax.profiler.start_trace(log_dir)
        try:
            work()
        finally:
            jax.profiler.stop_trace()
    '''})
    assert "CL902" not in codes(r)


def test_cl902_alias_resolution():
    """The profiling.py shape: locally aliased opener/closer."""
    r = lint({"crdt_tpu/obs/x.py": '''
    import jax

    def capture(log_dir, work):
        start = jax.profiler.start_trace
        stop = jax.profiler.stop_trace
        start(log_dir)
        work()
        stop()
    '''})
    assert "CL902" in codes(r)
    r2 = lint({"crdt_tpu/obs/x.py": '''
    import jax

    def capture(log_dir, work):
        start = jax.profiler.start_trace
        stop = jax.profiler.stop_trace
        start(log_dir)
        try:
            work()
        finally:
            stop()
    '''})
    assert "CL902" not in codes(r2)


def test_cl902_protocol_object_exempt():
    """install/uninstall pairs on one class are the context-manager
    discipline, not a leak."""
    r = lint({"crdt_tpu/guard/x.py": '''
    from crdt_tpu.ops.device import set_device_fault_hook

    class Plan:
        def install(self):
            self._old = set_device_fault_hook(self)
            return self

        def uninstall(self):
            set_device_fault_hook(self._old)
    '''})
    assert "CL902" not in codes(r)


def test_cl902_bare_acquire_without_release_fires():
    r = lint({"crdt_tpu/models/x.py": '''
    def f(my_lock, work):
        my_lock.acquire()
        work()
        my_lock.release()
    '''})
    found = [f for f in r.findings if f.code == "CL902"]
    assert found
    r2 = lint({"crdt_tpu/models/x.py": '''
    def f(my_lock, work):
        my_lock.acquire()
        try:
            work()
        finally:
            my_lock.release()
    '''})
    assert "CL902" not in codes(r2)


# ---------------------------------------------------------------------------
# round-16 review regressions (each was a demonstrated failure)


def test_cl902_specific_except_with_finally_clean():
    """Review finding: a raise inside an except handler must route
    through the finally (which holds the closer) — the canonical
    close-in-finally-with-specific-except pattern is NOT a leak."""
    r = lint({"crdt_tpu/models/x.py": '''
    def f(my_lock, work, handle):
        my_lock.acquire()
        try:
            work()
        except ValueError:
            handle()
        finally:
            my_lock.release()
    '''})
    assert "CL902" not in codes(r)


def test_cl7xx_partial_shard_map_body_is_traced():
    """Review finding: @partial(shard_map, ...) — the repo's dominant
    traced-step shape — must join the traced set like
    @partial(jax.jit, ...)."""
    r = lint({"crdt_tpu/parallel/x.py": '''
    import os
    from functools import partial

    from crdt_tpu.compat import shard_map

    @partial(shard_map, mesh=None)
    def step(x):
        if os.environ.get("CRDT_TPU_PALLAS"):
            return x
        return x + 1
    '''})
    assert "CL702" in codes(r)


def test_reach_closure_complete_through_call_cycles():
    """Review finding: mutually recursive helpers must not poison the
    closure memo — A<->B with B->D has D in BOTH closures (a blocking
    call in D behind the cycle must stay visible to CL801/CL802)."""
    from tools.crdtlint.callgraph import build_callgraph, reach_closure
    from tools.crdtlint.core import Module

    mods = [Module("crdt_tpu/a.py", textwrap.dedent('''
        def a():
            b()

        def b():
            a()
            d()

        def d():
            pass
    '''))]
    cg = build_callgraph(mods)
    memo = {}
    ca = reach_closure(cg, "crdt_tpu/a.py:a", strong_only=True,
                       memo=memo)
    cb = reach_closure(cg, "crdt_tpu/a.py:b", strong_only=True,
                       memo=memo)
    assert "crdt_tpu/a.py:d" in ca and "crdt_tpu/a.py:d" in cb
    assert "crdt_tpu/a.py:a" in ca  # cyclic: members reach themselves


def test_cl802_blocking_behind_mutual_recursion():
    """End-to-end: the blocking primitive sits behind a recursive
    helper pair under the lock — the SCC closure must surface it."""
    r = lint({"crdt_tpu/a.py": '''
    import subprocess
    import threading

    _build_lock = threading.Lock()

    def ping(n):
        if n:
            pong(n - 1)

    def pong(n):
        ping(n)
        subprocess.run(["make"])

    def build():
        with _build_lock:
            ping(3)
    '''})
    assert "CL802" in codes(r)


def test_cl902_return_inside_try_finally_clean():
    """Review round 2: return/break inside the protected region must
    route through the finally — `acquire(); try: return f() finally:
    release()` is the RECOMMENDED pattern, not a leak."""
    r = lint({"crdt_tpu/models/x.py": '''
    def ret_form(my_lock, work):
        my_lock.acquire()
        try:
            return work()
        finally:
            my_lock.release()

    def brk_form(my_lock, items):
        for it in items:
            my_lock.acquire()
            try:
                if it:
                    break
            finally:
                my_lock.release()
    '''})
    assert "CL902" not in codes(r)


def test_callgraph_nested_class_does_not_shadow_toplevel():
    """Review round 2: a class defined inside a function must keep
    the enclosing qual prefix — previously its methods overwrote a
    same-named top-level class's methods in the graph, a silent
    blind spot for every downstream checker."""
    from tools.crdtlint.callgraph import build_callgraph
    from tools.crdtlint.core import Module

    mods = [Module("crdt_tpu/x.py", textwrap.dedent('''
        class A:
            def f(self):
                pass

        def factory():
            class A:
                def f(self):
                    pass
            return A
    '''))]
    cg = build_callgraph(mods)
    assert "crdt_tpu/x.py:A.f" in cg.funcs
    assert "crdt_tpu/x.py:factory.<locals>.A.f" in cg.funcs


# ---------------------------------------------------------------------------
# CL1001-CL1004 wire taint (round 17)


def test_cl1001_tainted_index_fires():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        return d.data[n]
    '''})
    assert "CL1001" in codes(r)


def test_cl1001_tainted_slice_bound_fires():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d, buf):
        off = d.read_var_uint()
        return buf[2:off]
    '''})
    assert "CL1001" in codes(r)


def test_cl1001_comparison_guard_sanitizes():
    """A comparison-guarded branch on the tainted value kills the
    taint past the guard (the CFG-aware sanitization edge)."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d, buf):
        n = d.read_var_uint()
        if n >= len(buf):
            raise ValueError("offset past buffer")
        return buf[n]
    '''})
    assert codes(r) == []


def test_cl1001_use_before_guard_still_fires():
    """The guard kills taint only downstream: an index BEFORE the
    comparison is still hostile."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d, buf):
        n = d.read_var_uint()
        first = buf[n]
        if n >= len(buf):
            raise ValueError("late")
        return first
    '''})
    assert "CL1001" in codes(r)


def test_cl1001_min_clamp_sanitizes():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d, buf):
        n = min(d.read_var_uint(), len(buf) - 1)
        return buf[n]
    '''})
    assert codes(r) == []


def test_cl1001_declared_sanitizer_helper_kills_taint():
    """A `# crdtlint: sanitizes` helper owns the admission check:
    its result is clean at every caller."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def _read_bounded(d):  # crdtlint: sanitizes
        v = d.read_var_uint()
        if v >= (1 << 31):
            raise ValueError("bound")
        return v

    def decode_x(d, buf):
        n = _read_bounded(d)
        return buf[n]
    '''})
    assert "CL1001" not in codes(r)


def test_cl1001_rebind_from_clean_value_kills_taint():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d, buf):
        n = d.read_var_uint()
        n = 3
        return buf[n]
    '''})
    assert codes(r) == []


def test_cl1001_out_of_scope_module_clean():
    """The taint pass scopes to codec/storage/net — the same snippet
    in ops/ is some kernel's business, not the wire fence's."""
    r = lint({"crdt_tpu/ops/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        return d.data[n]
    '''})
    assert codes(r) == []


def test_cl1001_suppressed_inline():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        return d.data[n]  # crdtlint: disable=CL1001
    '''})
    assert "CL1001" not in codes(r)
    assert any(f.code == "CL1001" for f in r.suppressed)


def test_cl1001_baselined():
    files = {"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        return d.data[n]
    '''}
    first = lint(files)
    (f,) = [f for f in first.findings if f.code == "CL1001"]
    r = lint(files, baseline={f.fingerprint: {
        "fingerprint": f.fingerprint,
        "justification": "trusted test fixture path",
    }})
    assert "CL1001" not in codes(r)
    assert any(f2.code == "CL1001" for f2 in r.baselined)


def test_cl1002_tainted_allocation_fires():
    for alloc in ("bytearray(n)", "np.zeros(n)", "list(range(n))",
                  "b'x' * n", "[0] * n"):
        r = lint({"crdt_tpu/codec/x.py": f'''
    import numpy as np

    def decode_x(d):
        n = d.read_var_uint()
        return {alloc}
    '''})
        assert "CL1002" in codes(r), alloc


def test_cl1002_buffer_guard_sanitizes():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        if d.pos + n > len(d.data):
            raise ValueError("tail")
        return bytearray(n)
    '''})
    assert "CL1002" not in codes(r)


def test_cl1002_tainted_attribute_store_propagates():
    """Attribute stores on decoder objects carry taint (the
    `self.declared_len = n` shape)."""
    r = lint({"crdt_tpu/codec/x.py": '''
    class D:
        def read_header(self, d):
            self.declared = d.read_var_uint()
            return bytearray(self.declared)
    '''})
    assert "CL1002" in codes(r)


def test_cl1003_unconsuming_loop_fires_and_reader_loop_clean():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        out = []
        for _ in range(n):
            out.append(1)
        return out
    '''})
    assert "CL1003" in codes(r)
    # a body that reads the wire each iteration is buffer-capped
    r2 = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        out = []
        for _ in range(n):
            out.append(d.read_uint8())
        return out
    '''})
    assert "CL1003" not in codes(r2)


def test_cl1003_budget_check_in_body_sanitizes():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d, budget):
        n = d.read_var_uint()
        total = 0
        out = []
        for _ in range(n):
            total += 1
            if total > budget:
                raise ValueError("budget")
            out.append(1)
        return out
    '''})
    assert "CL1003" not in codes(r)


def test_cl1003_comprehension_bound_fires():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        return [0 for _ in range(n)]
    '''})
    assert "CL1003" in codes(r)
    r2 = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        return [d.read_any() for _ in range(n)]
    '''})
    assert "CL1003" not in codes(r2)


def test_cl1004_staging_crossing_fires_and_guarded_clean():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d, cols):
        n = d.read_var_uint()
        return stage(cols, rows=n)
    '''})
    assert "CL1004" in codes(r)
    r2 = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d, cols):
        n = d.read_var_uint()
        if n >= (1 << 40):
            raise ValueError("clock bound")
        return stage(cols, rows=n)
    '''})
    assert "CL1004" not in codes(r2)


def test_taints_directive_marks_custom_source():
    """`# crdtlint: taints` on a def makes its result hostile at
    every caller — the kv/udp seam annotation workflow."""
    r = lint({"crdt_tpu/storage/x.py": '''
    def fetch_blob(h):  # crdtlint: taints
        return h.raw()

    def index_of(h, table):
        n = fetch_blob(h)
        return table[n]
    '''})
    assert "CL1001" in codes(r)


def test_return_taint_closes_over_wrappers():
    """A wrapper returning a source's result is itself a source for
    its callers (the interprocedural fixpoint over STRONG edges)."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def _wrap(d):
        return d.read_var_uint()

    def _wrap2(d):
        return _wrap(d) + 1

    def decode_x(d, buf):
        n = _wrap2(d)
        return buf[n]
    '''})
    assert "CL1001" in codes(r)


def test_kv_receiver_results_are_tainted():
    """kv get/scan results taint without a directive when the
    receiver spelling names the store."""
    r = lint({"crdt_tpu/storage/x.py": '''
    def last_seq(kv, table):
        raw = kv.get(b"seq")
        return table[raw]
    '''})
    assert "CL1001" in codes(r)
    # a plain dict .get is NOT a kv source
    r2 = lint({"crdt_tpu/storage/x.py": '''
    def last_seq(cache, table):
        raw = cache.get(b"seq")
        return table[raw]
    '''})
    assert "CL1001" not in codes(r2)


# ---------------------------------------------------------------------------
# CL1101/CL1102 decode-allocation contracts (round 17)


def test_cl1101_absolute_guard_fires_buffer_guard_clean():
    weak = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        if n > (1 << 31):
            raise ValueError("cap")
        return bytearray(n)
    '''})
    assert "CL1101" in codes(weak)
    assert "CL1002" not in codes(weak)  # the guard did kill the taint
    anchored = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        if d.pos + n > len(d.data):
            raise ValueError("tail")
        return bytearray(n)
    '''})
    assert "CL1101" not in codes(anchored)


def test_cl1101_budget_variable_counts_as_anchored():
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d, data):
        budget = max(1 << 20, 4096 * len(data))
        n = d.read_var_uint()
        if n > budget:
            raise ValueError("budget")
        return bytearray(n)
    '''})
    assert "CL1101" not in codes(r)


def test_cl1101_only_on_decode_entries():
    """A non-decode-named function with the same weak guard is
    CL1002-country (when unguarded) or clean — the stricter
    buffer-anchored standard applies to decode entries only."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def helper_alloc(d):
        n = d.read_var_uint()
        if n > (1 << 31):
            raise ValueError("cap")
        return bytearray(n)
    '''})
    assert "CL1101" not in codes(r)


def test_cl1101_sanitizer_params_held_to_contract():
    """A `# crdtlint: sanitizes` helper's PARAMETERS are treated as
    hostile — the helper claims to own the admission check, so an
    absolute-bound-only fence inside it is a contract violation."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def read_block(d, n):  # crdtlint: sanitizes
        if n > (1 << 20):
            raise ValueError("cap")
        return bytearray(n)
    '''})
    assert "CL1101" in codes(r)


def test_cl1101_suppressed_and_baselined():
    src = {"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        if n > (1 << 31):
            raise ValueError("cap")
        # crdtlint: disable=CL1101
        return bytearray(n)
    '''}
    r = lint(src)
    assert "CL1101" not in codes(r)
    assert any(f.code == "CL1101" for f in r.suppressed)
    clean_src = {"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        if n > (1 << 31):
            raise ValueError("cap")
        return bytearray(n)
    '''}
    first = lint(clean_src)
    (f,) = [f for f in first.findings if f.code == "CL1101"]
    r2 = lint(clean_src, baseline={f.fingerprint: {
        "fingerprint": f.fingerprint,
        "justification": "absolute cap is the doc-level contract here",
    }})
    assert "CL1101" not in codes(r2)
    assert any(f2.code == "CL1101" for f2 in r2.baselined)


def test_cl1102_helper_raise_fires():
    r = lint({"crdt_tpu/codec/x.py": '''
    def _helper(b):
        raise KeyError("boom")

    def decode_x(b):
        return _helper(b)
    '''})
    assert "CL1102" in codes(r)


def test_cl1102_valueerror_and_bare_reraise_clean():
    r = lint({"crdt_tpu/codec/x.py": '''
    def _helper(b):
        if not b:
            raise ValueError("empty")
        try:
            return b[0]
        except IndexError:
            raise
    '''  '''
    def decode_x(b):
        return _helper(b)
    '''})
    assert "CL1102" not in codes(r)


def test_cl1102_decode_named_helper_left_to_cl302():
    """A helper that is itself decode-named is CL302's lexical job —
    CL1102 must not double-report it."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def _read_part(b):
        raise KeyError("boom")

    def decode_x(b):
        return _read_part(b)
    '''})
    assert "CL302" in codes(r)
    assert "CL1102" not in codes(r)


def test_cl1102_cross_module_strong_edge():
    r = lint({
        "crdt_tpu/codec/util.py": '''
    def unpack_head(b):
        raise AssertionError("no head")
    ''',
        "crdt_tpu/codec/x.py": '''
    from crdt_tpu.codec.util import unpack_head

    def decode_x(b):
        return unpack_head(b)
    ''',
    })
    found = [f for f in r.findings if f.code == "CL1102"]
    assert found and found[0].path == "crdt_tpu/codec/util.py"


def test_cl1102_weak_edge_never_convicts():
    """A by-method-name (weak) edge must not drag a helper into the
    decode closure — attribute calls on unknown receivers stay out."""
    r = lint({"crdt_tpu/codec/x.py": '''
    class Other:
        def finish(self):
            raise RuntimeError("not mine")

    def decode_x(b, obj):
        return obj.finish()
    '''})
    assert "CL1102" not in codes(r)


def test_cl1102_two_entries_one_finding():
    """Two decode entries reaching the same raise produce ONE
    finding (stable fingerprint for the baseline ledger)."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def _helper(b):
        raise KeyError("boom")

    def decode_x(b):
        return _helper(b)

    def decode_y(b):
        return _helper(b)
    '''})
    assert [f.code for f in r.findings].count("CL1102") == 1


def test_cl1102_suppressed_at_raise_site():
    r = lint({"crdt_tpu/codec/x.py": '''
    def _helper(b):
        raise KeyError("boom")  # crdtlint: disable=CL1102

    def decode_x(b):
        return _helper(b)
    '''})
    assert "CL1102" not in codes(r)
    assert any(f.code == "CL1102" for f in r.suppressed)


def test_open_by_family_buckets_four_digit_codes():
    """CL1001 counts under cl10 (wire taint), never under the donate
    family cl1 — the round-17 family split in LintResult."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def decode_x(d):
        n = d.read_var_uint()
        return d.data[n]
    '''})
    fams = r.open_by_family()
    assert fams["cl10"] >= 1
    assert fams["cl1"] == 0
    assert "cl11" in fams


# every round-17 code: the positive snippet, its inline-suppressed
# twin, and a baseline round-trip — (code, clean lint must fire it;
# the marked line carries the disable comment in the suppressed twin)
_R17_POSITIVES = {
    "CL1001": ("crdt_tpu/codec/x.py", '''
    def decode_x(d):
        n = d.read_var_uint()
        return d.data[n]{MARK}
    '''),
    "CL1002": ("crdt_tpu/codec/x.py", '''
    def decode_x(d):
        n = d.read_var_uint()
        return bytearray(n){MARK}
    '''),
    "CL1003": ("crdt_tpu/codec/x.py", '''
    def decode_x(d):
        n = d.read_var_uint()
        out = []
        for _ in range(n):{MARK}
            out.append(1)
        return out
    '''),
    "CL1004": ("crdt_tpu/codec/x.py", '''
    def decode_x(d, cols):
        n = d.read_var_uint()
        return stage(cols, rows=n){MARK}
    '''),
    "CL1101": ("crdt_tpu/codec/x.py", '''
    def decode_x(d):
        n = d.read_var_uint()
        if n > (1 << 31):
            raise ValueError("cap")
        return bytearray(n){MARK}
    '''),
    "CL1102": ("crdt_tpu/codec/x.py", '''
    def _helper(b):
        raise KeyError("boom"){MARK}

    def decode_x(b):
        return _helper(b)
    '''),
}


@pytest.mark.parametrize("code", sorted(_R17_POSITIVES))
def test_r17_code_suppressed_and_baselined_roundtrip(code):
    path, template = _R17_POSITIVES[code]
    plain = template.replace("{MARK}", "")
    r = lint({path: plain})
    assert code in codes(r), f"{code} positive snippet does not fire"
    # inline suppression on the finding's line
    marked = template.replace(
        "{MARK}", f"  # crdtlint: disable={code}"
    )
    r_supp = lint({path: marked})
    assert code not in codes(r_supp)
    assert any(f.code == code for f in r_supp.suppressed), code
    # baseline round-trip on the plain variant's fingerprint
    (f,) = [f for f in r.findings if f.code == code]
    r_base = lint({path: plain}, baseline={f.fingerprint: {
        "fingerprint": f.fingerprint,
        "justification": "intentional for this synthetic case",
    }})
    assert code not in codes(r_base)
    assert any(f2.code == code for f2 in r_base.baselined), code


def test_cl1102_reraise_of_bound_valueerror_clean():
    """Review fix: `except ValueError as e: raise e` preserves the
    contract — the checker must report the HANDLER's type, never the
    variable name."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def _helper(b):
        try:
            return b[0]
        except ValueError as e:
            raise e

    def decode_x(b):
        return _helper(b)
    '''})
    assert "CL1102" not in codes(r)


def test_cl1102_reraise_of_bound_foreign_type_fires():
    r = lint({"crdt_tpu/codec/x.py": '''
    def _helper(b):
        try:
            return b[0]
        except KeyError as e:
            raise e

    def decode_x(b):
        return _helper(b)
    '''})
    found = [f for f in r.findings if f.code == "CL1102"]
    assert found and "KeyError" in found[0].message


def test_cl1102_unresolvable_variable_raise_stays_silent():
    """A constructed exception variable cannot be traced — the
    conservative direction is silence, never invention."""
    r = lint({"crdt_tpu/codec/x.py": '''
    def _helper(kind):
        exc = RuntimeError("x") if kind else ValueError("y")
        raise exc

    def decode_x(b):
        return _helper(b)
    '''})
    assert "CL1102" not in codes(r)


def test_cl1004_strong_resolved_ops_callee_fires():
    """Review fix: a STRONG-resolved callee under crdt_tpu/ops/ is a
    staging sink even when its name is not a hard-coded stage tail —
    the ops candidate index makes the documented rule real."""
    r = lint({
        "crdt_tpu/ops/packer.py": '''
    def pack_columns(rows, cols):
        return rows
    ''',
        "crdt_tpu/codec/x.py": '''
    from crdt_tpu.ops.packer import pack_columns

    def decode_x(d, cols):
        n = d.read_var_uint()
        return pack_columns(n, cols)
    ''',
    })
    found = [f for f in r.findings if f.code == "CL1004"]
    assert found and found[0].path == "crdt_tpu/codec/x.py"
