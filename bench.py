#!/usr/bin/env python
"""North-star benchmark: 1k-replica fan-in trace replay, end to end.

BASELINE.json config #5 — "1k-replica fan-in: 100k-op trace replay +
snapshot compaction" — measured honestly:

- **Forced-sync timing.** On the tunnelled `axon` platform, execution
  is LAZY until the first device->host transfer: `block_until_ready`
  returns without running anything, so pre-transfer timings measure
  nothing (r1's "0.05ms kernel" was this artifact — a 8192^2 matmul
  "runs" at 21,910 TFLOP/s, ~100x the hardware's peak, by the same
  measurement). This bench forces the platform into its synchronous
  mode FIRST and demonstrates the illusion with a before/after probe;
  every number below is a real execution time.
- **Timed region = ingest to visible state**, the same span as the
  reference's hot loop (crdt.js:294): v1 wire decode -> columnar
  staging -> merge -> winner/order gather -> cache materialization ->
  compacted snapshot encode. Nothing is pre-staged outside the timer.
- **The headline ``vs_baseline``** compares the DEVICE path against an
  OPTIMIZED SCALAR baseline: the same end-to-end pipeline with the
  merge done by vectorized numpy ports of the kernels on the host CPU
  (a fair stand-in for a tuned native CPU implementation), sharing the
  same decode/materialize/compact code. The pure-Python Yjs-semantics
  oracle — BASELINE.md's named baseline — is reported separately as
  ``vs_python_oracle``.
- **Platform fixed costs are measured and reported**: through this
  tunnel a host->device put pays ~0.1s fixed + ~30MB/s and a fetch
  ~0.1s fixed, so the device path's floor at 100k ops is transfer
  latency, not merge speed; the same pipeline on co-located hardware
  (PCIe/ICI) pays ~1ms. The scale sweep shows the crossover where the
  device overtakes the tuned CPU baseline even through the tunnel.

Prints ONE JSON line with the headline and all supporting numbers.

Env knobs: BENCH_REPLICAS (1000), BENCH_OPS (per replica, 100),
BENCH_ITERS (3), BENCH_SKIP_ORACLE=1, BENCH_SCALE (default 16: also
run a 16x-larger workload end to end on both paths; 0 skips),
BENCH_CONFLICT (default 1: also run the shared-anchor conflict
workload, oracle-checked; 0 skips), BENCH_TEXT (default 1: also run
the right-bearing collaborative-text workload, oracle-checked; 0
skips), BENCH_SWARM (default 1: replica-level loopback swarm timing
in all three merge modes; 0 skips), BENCH_ROUNDS (default 1:
steady-state incremental rounds on the scale doc with a host/device
crossover table + the session's auto-calibration; 0 skips; requires
the scale run), BENCH_ROUND_SIZES (comma list of per-round delta op
counts, default 250,1000,4000,16000,64000).
"""

from __future__ import annotations

import json
import os
import sys
import time
import warnings

import numpy as np

# CPU-backend runs have no buffer donation; jax warns once per
# compiled donated shape (the donation hint is deliberate — it pays
# off on TPU). Keep the bench's stderr signal-only.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


BENCH_OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_OUT.json")


def lint_digest() -> dict:
    """Run the crdtlint static pass over the package and digest the
    counts for the artifact: ``lint.findings`` is the TOTAL (open +
    baselined + suppressed), the number ``tools/metrics_diff.py``
    gates lower-is-better — the committed tree always has 0 open
    (tier-1 ``tests/test_lint.py``), so growth means a bigger
    baseline or new inline disables. Failure-proof: a broken lint
    environment yields an absent section, never a broken bench."""
    try:
        repo = os.path.dirname(os.path.abspath(__file__))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from tools.crdtlint.core import load_modules, run_lint

        mods = load_modules([os.path.join(repo, "crdt_tpu")], repo)
        res = run_lint(mods)
        digest = {
            "findings": res.total_raw,
            "open": len(res.findings),
            "baselined": len(res.baselined),
            "suppressed": len(res.suppressed),
            # rounds 16/17: per-family OPEN counts for the new code
            # families — metrics_diff gates each lower-is-better with
            # count semantics (the committed tree holds them at 0, so
            # ANY new open CL7xx/CL8xx/CL9xx/CL10xx/CL11xx finding is
            # a visible regression, not noise)
            "open_by_family": {
                k: v for k, v in res.open_by_family().items()
                if k in ("cl7", "cl8", "cl9", "cl10", "cl11")
            },
        }
        # the memoized call graph's size stats ride the digest so
        # graph growth/decay (functions, edges, guessed-edge share)
        # is reviewable next to the finding counts
        if res.stats.get("callgraph"):
            digest["callgraph"] = res.stats["callgraph"]
        return digest
    except Exception as exc:  # noqa: BLE001 — evidence, not control flow
        log(f"lint digest skipped: {exc}")
        return {}


def emit_result(out: dict, *, path: str = BENCH_OUT,
                summary_keys=None) -> None:
    """Durable bench evidence (VERDICT r5 Next #1): the FULL result
    object is written to BENCH_OUT.json at the end of every full run,
    and stdout gets one line guaranteed to fit the driver's 2000-byte
    tail window (<=1500 bytes), so the tail always parses. When the
    full object already fits, it IS the stdout line; otherwise a
    scalar summary (headline metrics + per-section digests, pointing
    at the artifact for the rest) goes out instead.

    ``path=None`` skips the artifact — the smoke mode uses it so a
    tier-1 test run can never overwrite a real run's committed
    evidence with toy numbers."""
    if path is not None:
        # artifact-only: the smoke path (path=None) must not pay the
        # ~3s whole-tree lint pass on every tier-1 run for a digest
        # nothing reads
        if "lint" not in out:
            digest = lint_digest()
            if digest:
                out["lint"] = digest
        try:
            with open(path, "w") as f:
                json.dump(out, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError as exc:  # read-only checkout: stdout still works
            log(f"{path} not written: {exc}")
    line = json.dumps(out)
    if len(line) <= 1500:
        print(line)
        return
    summary = {"full_results": "BENCH_OUT.json"}
    keys = summary_keys or (
        "metric", "value", "unit", "vs_baseline", "vs_python_oracle",
        "kernel_dispatch_ops_per_s", "platform", "dispatch_floor_ms",
    )
    for k in keys:
        if k in out:
            summary[k] = out[k]
    # per-section one-number digests, added while they fit
    digests = []
    scale = out.get("scale_run") or {}
    if "vs_baseline" in scale:
        digests.append(("scale_vs_baseline", scale["vs_baseline"]))
    if "stream_vs_oneshot" in scale:
        digests.append(("stream_vs_oneshot", scale["stream_vs_oneshot"]))
    rounds = scale.get("rounds") or {}
    if "vs_cold_replay" in rounds:
        digests.append(("rounds_vs_cold_replay", rounds["vs_cold_replay"]))
    fleet = out.get("fleet_run") or {}
    if "fleet_vs_swarm_equiv" in fleet:
        eq = dict(fleet["fleet_vs_swarm_equiv"])
        digests.append(("fleet_vs_swarm_equiv_est",
                        eq.get("replicated")))
    for sec in ("conflict_run", "text_run", "swarm_run"):
        if out.get(sec):
            digests.append((f"{sec}_ok", "error" not in out[sec]))
    for k, v in digests:
        trial = dict(summary)
        trial[k] = v
        if len(json.dumps(trial)) > 1500:
            break
        summary[k] = v
    line = json.dumps(summary)
    if len(line) > 1500:  # hard guarantee, whatever the values held
        line = json.dumps({
            "metric": out.get("metric"),
            "value": out.get("value"),
            "unit": out.get("unit"),
            "full_results": "BENCH_OUT.json",
        })
    print(line)


# ---------------------------------------------------------------------------
# trace generation (not timed: this manufactures the wire input)
# ---------------------------------------------------------------------------


def build_trace(R: int, K: int, seed: int = 0, client_base: int = 0,
                map_frac: float = 0.6):
    """Per-replica v1 update blobs: ``map_frac`` map sets over 8 maps,
    the rest concurrent list appends over 8 lists (own-chain origins),
    5% of each replica's ops tombstoned in its final blob's delete
    set. ``client_base`` offsets the client ids (steady-state rounds
    need fresh writers whose ids do not collide with the base doc's);
    ``map_frac=1.0`` makes delta rounds touch only per-key map
    segments instead of whole lists."""
    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    rng = np.random.default_rng(seed)
    num_maps, num_lists = 8, 8
    keys_per_map = max(64, (R * K) // 64)
    n_map = int(K * map_frac)
    blobs = []
    for r in range(R):
        client = client_base + r + 1
        recs = []
        maps = rng.integers(0, num_maps, n_map)
        keys = rng.integers(0, keys_per_map, n_map)
        last_set: dict = {}
        for k in range(n_map):
            mk = (int(maps[k]), int(keys[k]))
            prev = last_set.get(mk)
            recs.append(ItemRecord(
                client=client, clock=k, parent_root=f"m{maps[k]}",
                key=f"k{keys[k]}", content=int(r * K + k),
                # chained like real Yjs map sets: origin = this
                # replica's previous entry for the key
                origin=(client, prev) if prev is not None else None,
            ))
            last_set[mk] = k
        lists = rng.integers(0, num_lists, K - n_map)
        last: dict = {}
        for j, k in enumerate(range(n_map, K)):
            lst = int(lists[j])
            prev = last.get(lst)
            recs.append(ItemRecord(
                client=client, clock=k, parent_root=f"l{lst}",
                origin=(client, prev) if prev is not None else None,
                content=int(r * K + k),
            ))
            last[lst] = k
        ds = DeleteSet()
        for k in rng.choice(K, size=max(1, K // 20), replace=False):
            ds.add(client, int(k))
        blobs.append(v1.encode_update(recs, ds))
    return blobs


def build_conflict_trace(R: int, K: int, seed: int = 2):
    """The YATA hard case the append-only trace never triggers: every
    replica keeps attaching to a handful of SHARED origin items, so
    sibling groups grow R wide and the conflict scan (client-ordered
    sibling resolution) does real work on every insert. Right origins
    are absent, as in real concurrent appends, so both contenders stay
    exact. 70% sequence ops (vs 40% in the main trace)."""
    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    rng = np.random.default_rng(seed)
    num_lists = 4
    n_map = (K * 3) // 10
    # shared attachment points (client 1's first seq ops), clamped so
    # small K never references anchors client 1 does not emit
    hot = min(16, K - n_map)
    hot -= hot % num_lists  # equal anchors per list (0 = no anchors)
    blobs = []
    for r in range(R):
        client = r + 1
        recs = []
        last_set: dict = {}
        for k in range(n_map):
            key = int(rng.integers(0, 64))
            prev_set = last_set.get(key)
            recs.append(ItemRecord(
                client=client, clock=k, parent_root="m0",
                key=f"k{key}", content=k,
                # chained like real Yjs map sets
                origin=(client, prev_set) if prev_set is not None else None,
            ))
            last_set[key] = k
        hot_per_list = hot // num_lists
        prev: dict = {}
        for k in range(n_map, K):
            if client == 1 and k < n_map + hot:
                # the hot anchors: client 1 heads each list round-robin
                lst = (k - n_map) % num_lists
                origin = None
            else:
                lst = int(rng.integers(0, num_lists))
                if hot_per_list and rng.random() < 0.5:
                    # pile onto a shared anchor OF THIS LIST -> R-wide
                    # same-origin sibling group
                    j = lst + num_lists * int(rng.integers(0, hot_per_list))
                    origin = (1, n_map + j)
                else:
                    origin = (client, prev[lst]) if lst in prev else None
            recs.append(ItemRecord(
                client=client, clock=k, parent_root=f"l{lst}",
                origin=origin, content=k,
            ))
            prev[lst] = k
        blobs.append(v1.encode_update(recs, DeleteSet()))
    return blobs


def build_text_trace(R: int, K: int, seed: int = 3):
    """Collaborative-text shape: every replica types its own runs into
    one shared document; 20% of ops are mid-inserts carrying BOTH
    origins (left = predecessor, right = the character that followed
    at insert time) — the workload whose right origins route ordering
    through the exact host machinery instead of the pure device
    sibling model. The numpy baseline does not model rights, so this
    run is referenced against the scalar oracle only."""
    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    rng = np.random.default_rng(seed)
    blobs = []
    for r in range(R):
        client = r + 1
        recs = []
        chain: list = []  # own chars in own document order
        for k in range(K):
            if chain and rng.random() < 0.2:
                j = int(rng.integers(0, len(chain)))
                recs.append(ItemRecord(
                    client=client, clock=k, parent_root="text",
                    origin=chain[j - 1] if j > 0 else None,
                    right=chain[j], content=k))
                chain.insert(j, (client, k))
            else:
                recs.append(ItemRecord(
                    client=client, clock=k, parent_root="text",
                    origin=chain[-1] if chain else None, content=k))
                chain.append((client, k))
        blobs.append(v1.encode_update(recs, DeleteSet()))
    return blobs


# ---------------------------------------------------------------------------
# shared pipeline stages (identical host work for both contenders)
# ---------------------------------------------------------------------------


# The pipeline stages ARE the product's replay API: bench times
# crdt_tpu.models.replay, not a private copy (see that module's doc).
from crdt_tpu.models import replay as rp

decode_stage = rp.decode
column_stage = rp.stage
materialize_stage = rp.materialize
compact_stage = rp.compact
visible_mask = rp.visible_mask


# ---------------------------------------------------------------------------
# optimized scalar baseline: numpy ports of both kernels (host CPU)
# ---------------------------------------------------------------------------


def numpy_converge(cols):
    """Vectorized host merge, exact for this workload (per-replica
    chained map sets -> segmented (client, clock) argmax; append-only
    lists -> DFS ranks via the same pointer-doubling scheme as the
    device kernel). Checked against the Python oracle below."""
    client = cols["client"]
    clock = cols["clock"]
    pa = cols["parent_a"]
    kid = cols["key_id"]
    oc = cols["origin_client"]
    ok = cols["origin_clock"]
    n = len(client)

    # --- map winners -----------------------------------------------
    # with per-replica chained sets (origin = own previous entry), the
    # Yjs tail for a key is the largest client's latest set: group by
    # (parent, key), take max (client, clock)
    is_map = kid >= 0
    order = np.lexsort((clock, client, kid, pa))
    order = order[is_map[order]]
    pa_s, kid_s = pa[order], kid[order]
    last = np.r_[pa_s[1:] != pa_s[:-1], True] | np.r_[
        kid_s[1:] != kid_s[:-1], True
    ]
    win_rows = order[last]

    # --- sequence DFS ranks (numpy pointer doubling) -------------------
    is_seq = ~is_map
    pack = (client.astype(np.int64) << 40) | clock
    sorder = np.argsort(pack)
    opack = np.where(oc >= 0, (oc.astype(np.int64) << 40) | ok, -1)
    pos = np.searchsorted(pack[sorder], opack)
    posc = np.clip(pos, 0, n - 1)
    found = (opack >= 0) & (pack[sorder[posc]] == opack)
    origin_idx = np.where(found, sorder[posc], -1)

    seq_roots = (
        np.unique(pa[is_seq]) if is_seq.any() else np.empty(0, np.int64)
    )
    S = len(seq_roots)
    seg = np.where(
        is_seq,
        np.searchsorted(
            seq_roots, np.where(is_seq, pa, seq_roots[0] if S else 0)
        ),
        -1,
    )
    m = n + S
    parent = np.where(is_seq & (origin_idx >= 0), origin_idx,
                      n + np.maximum(seg, 0))
    parent = np.where(is_seq, parent, m)

    skey = np.lexsort((-clock, client, parent))
    p_s = parent[skey]
    same = np.r_[p_s[1:] == p_s[:-1], False]
    nxt = np.where(same, np.roll(skey, -1), -1)
    next_sib = np.full(n, -1, np.int64)
    next_sib[skey] = nxt
    first = np.r_[True, p_s[1:] != p_s[:-1]] & is_seq[skey]
    first_child = np.full(m + 1, -1, np.int64)
    first_child[np.where(first, p_s, m)] = np.where(first, skey, -1)
    first_child = first_child[:m]

    idx_m = np.arange(m)
    pad_next = np.r_[next_sib, np.full(S, -1)]
    pad_parent = np.r_[parent, np.zeros(S, np.int64)]
    pad_isseq = np.r_[is_seq, np.zeros(S, bool)]
    is_last = (idx_m < n) & (pad_next == -1) & pad_isseq
    g = np.where(is_last, pad_parent, idx_m)
    for _ in range(max(1, (max(m, 2) - 1).bit_length() + 1)):
        g = g[g]
    y_next = pad_next[np.clip(g, 0, m - 1)]
    succ = np.where((g >= n) | (y_next < 0), idx_m, y_next)
    succ = np.where(first_child >= 0, np.clip(first_child, 0, m - 1), succ)
    succ = np.where(pad_isseq | (idx_m >= n), succ, idx_m)
    dist = np.where(succ != idx_m, 1, 0)
    for _ in range(max(1, (max(m, 2) - 1).bit_length() + 1)):
        dist = dist + dist[succ]
        succ = succ[succ]
    root_dist = dist[n + np.maximum(seg, 0)]
    rank = np.where(is_seq, root_dist - dist[:n] - 1, -1)
    return win_rows, seg, rank


def numpy_gather(dec, ds, np_win, np_seg, np_rank):
    """Vectorized assembly for the numpy contender — the same
    rank-sorted split the device path's packed fetch uses, so both
    sides get the best host assembly."""
    is_ranked = np_seg >= 0
    skey = np.where(
        is_ranked,
        (np_seg.astype(np.int64) << 32) | np_rank.astype(np.int64),
        np.int64(2**62),
    )
    dorder = np.argsort(skey, kind="stable")
    k = int(is_ranked.sum())
    rows = dorder[:k]
    segs = np_seg[rows]
    seq_orders = {}
    if k:
        cuts = np.r_[0, np.flatnonzero(segs[1:] != segs[:-1]) + 1, k]
        for a, b in zip(cuts[:-1], cuts[1:]):
            chunk = rows[a:b].tolist()
            seq_orders[rp.parent_spec(dec, chunk[0])] = chunk
    vis = visible_mask(dec, list(np_win), ds)
    return list(np_win), vis, seq_orders


# ---------------------------------------------------------------------------


def _xfer_counters():
    """Snapshot of the unlabelled xfer.* counters (the byte-accounting
    seam in crdt_tpu.ops.device); {} when tracing is off."""
    from crdt_tpu.obs.tracer import get_tracer

    tr = get_tracer()
    if not tr.enabled:
        return {}
    return {
        k: v for k, v in tr.counters("xfer.").items() if "{" not in k
    }


def _xfer_diff(before, after):
    """Per-workload bytes-on-link: counter growth across one leg."""
    return {
        k.replace("xfer.", ""): after[k] - before.get(k, 0)
        for k in after
        if after[k] != before.get(k, 0)
    }


def min_time(fn, n):
    """(best_seconds, runs) for n timed calls of ``fn`` — the ONE
    min-of-N idiom every published headline uses, so both sides of
    any ratio get identical noise treatment. Returns the last call's
    result too: (best_s, runs_s, last_result)."""
    best, runs, out = float("inf"), [], None
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        runs.append(round(dt, 3))
        best = min(best, dt)
    return best, runs, out


def run_oracle(blobs, *, with_deletes=True):
    """Decode a trace and replay it through the scalar-semantics
    engine (BASELINE.md's named baseline). Returns (engine, seconds)."""
    from crdt_tpu.codec import v1
    from crdt_tpu.core.engine import Engine
    from crdt_tpu.core.ids import DeleteSet

    t0 = time.perf_counter()
    eng = Engine(0)
    recs, ds = [], DeleteSet()
    for blob in blobs:
        rr, dd = v1.decode_update(blob)
        recs.extend(rr)
        if with_deletes:
            for c, k, length in dd.iter_all():
                ds.add(c, k, length)
    eng.apply_records(recs, ds)
    return eng, time.perf_counter() - t0


def force_sync_mode():
    """Flip the platform into synchronous execution and PROVE the lazy
    trap: time the same dispatch before and after the first D2H."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(np.arange(1 << 17, dtype=np.int64))

    def timed_dispatch():
        t0 = time.perf_counter()
        y = x
        for _ in range(4):
            y = jnp.sort(y)
        jax.block_until_ready(y)
        return time.perf_counter() - t0

    timed_dispatch()  # compile
    t_lazy = timed_dispatch()
    np.asarray(x + 1)  # the first D2H: flips the tunnel to sync mode
    t_true = min(timed_dispatch() for _ in range(3))
    illusory = t_true > 5 * max(t_lazy, 1e-9)
    verdict = (
        "LAZY platform: pre-D2H timings are fiction, all numbers "
        "below are forced-sync" if illusory
        else "platform executes eagerly"
    )
    log(f"lazy-exec probe: pre-D2H {t_lazy*1e3:.2f}ms vs post-D2H "
        f"{t_true*1e3:.2f}ms ({verdict})")
    return {"pre_d2h_ms": round(t_lazy * 1e3, 2),
            "post_d2h_ms": round(t_true * 1e3, 2),
            "lazy_platform": bool(illusory)}


def platform_costs():
    """Fixed host<->device costs that floor the e2e device path."""
    import jax

    one_mb = np.zeros((1 << 20) // 8, np.int64)

    def best(fn, iters=3):
        return min(fn() for _ in range(iters))

    def put():
        t0 = time.perf_counter()
        d = jax.device_put(one_mb)
        jax.block_until_ready(d)
        return time.perf_counter() - t0

    dev = jax.device_put(one_mb)
    jax.block_until_ready(dev)

    def fetch():
        t0 = time.perf_counter()
        np.asarray(dev + 0)
        return time.perf_counter() - t0

    import jax.numpy as jnp

    small = jnp.arange(1024)

    def dispatch():
        t0 = time.perf_counter()
        jax.block_until_ready(small + 1)
        return time.perf_counter() - t0

    costs = {
        "h2d_1mb_ms": round(best(put) * 1e3, 1),
        "d2h_1mb_ms": round(best(fetch) * 1e3, 1),
        "dispatch_ms": round(best(dispatch) * 1e3, 1),
    }
    log(f"platform fixed costs: {costs}")
    return costs


def run_device(blobs, phases):
    """Full device-path replay; phases dict gets per-stage seconds."""
    from crdt_tpu.ops import packed

    def timed(name, fn, *a):
        t = time.perf_counter()
        out = fn(*a)
        phases[name] = round(time.perf_counter() - t, 4)
        return out

    # snapshot compaction runs SERIALLY in both contenders: an earlier
    # revision overlapped it on a background thread for the device leg
    # only, which mixed a pipeline-structure advantage into the merge
    # comparison (advisor finding, round 2)
    from crdt_tpu.ops.device import xfer_put

    dec = timed("decode", decode_stage, blobs)
    cols, ds = timed("columns", column_stage, dec)
    # above the eager-shipping threshold "pack" includes transfer
    # INITIATION (async accounted put per staged row) and "converge"
    # the wait — the sum stays the honest total either way; below it a
    # single put inside converge is cheaper (fixed per-put latency)
    big = len(cols["client"]) >= packed.EAGER_PUT_MIN_ROWS
    plan = timed(
        "pack",
        lambda c: packed.stage(c, put=xfer_put if big else None),
        cols,
    )
    detail = {}
    res = timed("converge", packed.converge, plan, detail)
    phases["converge_detail"] = detail  # upload_wait/dispatch/fetch
    win_rows, win_vis, seq_orders = timed(
        "gather", rp.gather, dec, ds, ("packed", res)
    )
    cache = timed("materialize", materialize_stage,
                  dec, ds, win_rows, win_vis, seq_orders)
    snap = timed("compact", compact_stage, dec, ds)
    return cache, snap, dec, ds, win_rows, win_vis, seq_orders


def run_stream(blobs, phases):
    """The overlapped streaming executor (the device path's DEFAULT
    engine for the scale replay): chunked decode, async double-
    buffered converge, incremental materialize. ``phases`` receives
    per-lane busy seconds + overlap accounting (wall vs sum-of-phases,
    overlap_efficiency) from crdt_tpu.models.streaming."""
    from crdt_tpu.models import stream_replay

    return stream_replay(blobs, phases=phases)


def run_numpy(blobs, phases):
    def timed(name, fn, *a):
        t = time.perf_counter()
        out = fn(*a)
        phases[name] = round(time.perf_counter() - t, 4)
        return out

    dec = timed("decode", decode_stage, blobs)
    cols, ds = timed("columns", column_stage, dec)
    np_win, np_seg, np_rank = timed("merge", numpy_converge, cols)
    win_rows, vis, seq_orders = timed(
        "gather", numpy_gather, dec, ds, np_win, np_seg, np_rank
    )
    cache = timed("materialize", materialize_stage,
                  dec, ds, win_rows, vis, seq_orders)
    snap = timed("compact", compact_stage, dec, ds)
    return cache, snap


def _ensure_live_backend():
    """The axon tunnel, when down, HANGS backend init (the
    sitecustomize hook dials it even under JAX_PLATFORMS=cpu). Probe
    device init in a subprocess with a timeout; on failure re-exec
    this benchmark on the CPU backend so the run still produces an
    honest JSON line (its `platform` field records what actually ran).
    """
    import subprocess

    if os.environ.get("BENCH_BACKEND_CHECKED"):
        return
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=240,
        )
        if probe.returncode == 0:
            os.environ["BENCH_BACKEND_CHECKED"] = "1"
            return
        reason = probe.stderr.decode(errors="replace")[-300:]
    except subprocess.TimeoutExpired:
        reason = "backend init hung (tunnel down?)"
    log(f"TPU backend probe failed; re-running on CPU: {reason}")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # skips axon registration
    env.update({"JAX_PLATFORMS": "cpu", "BENCH_BACKEND_CHECKED": "1"})
    os.execve(sys.executable, [sys.executable, __file__], env)


def fleet_mesh_child(argv):
    """Subprocess leg of the fleet bench: WEAK-scaling gossip rounds on
    a virtual CPU device mesh (the driver's multichip rig). Fixed
    replicas-per-device; the mesh grows; each round converges the
    whole union from real per-replica v1 blobs. Prints one JSON line.

    IMPORTANT rig caveat, measured: this box exposes ONE physical
    core (nproc=1), so the 8 "devices" serialize and wall-clock
    tracks TOTAL work, not per-device work. That makes the honest
    mesh-leverage signal here STRONG scaling on a fixed union:

    - ``replicated`` (the reference's full-mesh shape: all-gather +
      replicated converge): total work grows with the mesh, so round
      time GROWS ~linearly in device count — the cost of the
      no-division mapping, visible exactly as predicted.
    - ``segmented`` (union partitioned by segment, each device
      converging only its shard): total work is CONSTANT in device
      count, so round time stays ~FLAT — the work really divides,
      which on real parallel chips becomes ~1/nd wall-clock.

    A weak-scaling table (fixed replicas/device, union grows with the
    mesh) is recorded for shape as well; on one core its wall-clock
    necessarily grows with the union for both mappings.
    """
    import jax

    jax.config.update("jax_enable_x64", True)
    from crdt_tpu.models.fleet import (
        SegmentedFleet,
        fleet_for_trace,
        load_trace,
        shard_trace,
    )
    from crdt_tpu.parallel.gossip import make_mesh

    r_fixed, K_f = int(argv[0]), int(argv[1])
    nds = [int(x) for x in argv[2:]]
    out = {"fixed_union_replicas": r_fixed, "ops_per_replica": K_f,
           "strong_scaling": {}, "weak_scaling": {}}

    def best3(fn):
        fn()  # compile (untimed)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            fn()
            times.append(round(time.perf_counter() - t0, 3))
        return min(times), times

    # strong scaling: ONE union (R_fixed replicas), growing mesh
    blobs = build_trace(r_fixed, K_f, seed=9)
    for nd in nds:
        mesh = make_mesh(nd)
        tr = load_trace(blobs, replicas_multiple=nd)
        fleet = fleet_for_trace(tr, mesh=mesh)
        t_rep, runs_rep = best3(lambda: fleet.step(tr.cols, tr.dels))
        sh = shard_trace(tr, nd)
        sf = SegmentedFleet(sh, mesh=mesh)
        t_seg, runs_seg = best3(lambda: sf.step(sh))
        out["strong_scaling"][str(nd)] = {
            "ops": r_fixed * K_f,
            "replicated_round_s": t_rep,
            "segmented_round_s": t_seg,
            "replicated_runs_s": runs_rep,
            "segmented_runs_s": runs_seg,
        }
    # weak scaling: union grows with the mesh (shape record)
    for nd in nds:
        R_w = max(r_fixed // max(nds), 8) * nd
        blobs_w = build_trace(R_w, K_f, seed=9)
        mesh = make_mesh(nd)
        tr = load_trace(blobs_w, replicas_multiple=nd)
        sh = shard_trace(tr, nd)
        sf = SegmentedFleet(sh, mesh=mesh)
        t_seg, runs_seg = best3(lambda: sf.step(sh))
        out["weak_scaling"][str(nd)] = {
            "replicas": R_w, "ops": R_w * K_f,
            "segmented_round_s": t_seg,
            "ops_per_s": round(R_w * K_f / t_seg),
            "segmented_runs_s": runs_seg,
        }
    print(json.dumps(out))


MULTICHIP_OUT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "MULTICHIP_r06.json"
)


def multichip_child(argv):
    """Subprocess leg of ``--multichip``: the 100k-replica scale
    replay on THIS process's forced device count (the parent pins
    JAX_PLATFORMS/XLA_FLAGS before spawn). Times the staged converge
    (the sharded piece) and the whole replay, digests the outputs for
    the parent's cross-device byte-identity assert, and prints ONE
    JSON line — a child that executed nothing prints nothing, which
    the parent treats as a loud failure."""
    import hashlib

    import jax

    jax.config.update("jax_enable_x64", True)
    from crdt_tpu.models import replay as rp
    from crdt_tpu.obs import Tracer, set_tracer
    from crdt_tpu.ops import packed
    from crdt_tpu.ops import shard as shard_ops

    R, K = int(argv[0]), int(argv[1])
    nd = len(jax.devices())
    tracer = set_tracer(Tracer(enabled=True))
    blobs = build_trace(R, K, seed=13)
    dec = rp.decode(blobs)
    cols, ds = rp.stage(dec)
    n = len(cols["client"])

    def one_stage():
        if nd > 1:
            splan = shard_ops.stage(cols, n_shards=nd)
            assert splan is not None, "sharded staging refused"
            return shard_ops, splan
        plan = packed.stage(cols)
        assert plan is not None, "packed staging refused"
        return packed, plan

    eng, plan = one_stage()
    res = eng.converge(plan)  # compile (untimed)
    # staging is HOST work, identical in total across device counts
    # (each shard stages its slice) — itemized separately so
    # converge_s carries the pure upload+dispatch+fetch the mesh
    # actually divides, the same discipline as converge_detail
    conv_runs = []
    pack_runs = []
    c_before = None
    for _ in range(3):
        t0 = time.perf_counter()
        eng, plan = one_stage()
        pack_runs.append(round(time.perf_counter() - t0, 3))
        c_before = tracer.counters()
        t0 = time.perf_counter()
        res = eng.converge(plan)
        conv_runs.append(round(time.perf_counter() - t0, 3))
    c_after = tracer.counters()

    def per_round(name):
        return c_after.get(name, 0) - (c_before or {}).get(name, 0)
    win_rows, win_vis, seq_orders = rp.gather(dec, ds, ("packed", res))
    cache = rp.materialize(dec, ds, win_rows, win_vis, seq_orders)
    snap = rp.compact(dec, ds)
    # one timed end-to-end replay (decode..snapshot; the host phases
    # are constant across device counts, so the scaling signal lives
    # in converge_s — both are published)
    t0 = time.perf_counter()
    full = rp.replay_trace(blobs)
    e2e_s = round(time.perf_counter() - t0, 3)
    assert full.cache == cache and full.snapshot == snap, \
        "replay route diverges from the explicit converge"
    gauges = tracer.report()["gauges"]
    digest = hashlib.sha256(
        json.dumps(cache, sort_keys=True).encode()
        + hashlib.sha256(snap).digest()
    ).hexdigest()
    sv_digest = None
    if nd > 1 and getattr(res, "global_sv", None) is not None:
        sv_digest = hashlib.sha256(
            np.ascontiguousarray(res.global_sv).tobytes()
        ).hexdigest()
    print(json.dumps({
        "n_devices": nd,
        "replicas": R,
        "ops": n,
        "converge_s": min(conv_runs),
        "converge_runs_s": conv_runs,
        "pack_s": min(pack_runs),
        "pack_runs_s": pack_runs,
        "e2e_s": e2e_s,
        "boundary_bytes": per_round("shard.boundary_bytes"),
        "staged_bytes": per_round("xfer.staged_bytes"),
        "wyllie_rounds": gauges.get("converge.wyllie_rounds"),
        "seam_rows": per_round("shard.seam_rows"),
        "digest": digest,
        "sv_digest": sv_digest,
    }))


def multichip(argv=None) -> int:
    """The ``--multichip`` harness (round 13): the scale replay
    sharded over 1/2/4/8 virtual devices, one subprocess per device
    count (XLA's forced host-platform device count is fixed at
    backend init, so each count needs a fresh interpreter).

    Publishes per-device-count scaling + the boundary-exchange bytes
    into MULTICHIP_r06.json and merges a ``multichip`` section into
    BENCH_OUT.json, both regression-gated by tools/metrics_diff.py.

    FAILS LOUDLY: a child that prints no result line, exits non-zero,
    or converges to a different document marks the run failed — the
    artifact records the actual rc and output tail, and the process
    exits non-zero. ``ok: true`` with an empty payload can no longer
    happen (the r05 harness recorded only ``n_devices`` with an empty
    tail and still passed)."""
    import subprocess

    # 100k replicas x 8 ops: >=100k replicas per the acceptance bar,
    # with enough ops per replica that the staged upload dominates
    # the SV handshake (the boundary wire scales with REPLICAS, the
    # staged bytes with OPS — at 1 op/replica the two are comparable
    # by construction and no sharding could make the exchange small)
    R = int(os.environ.get("BENCH_MULTICHIP_REPLICAS", 100_000))
    K = int(os.environ.get("BENCH_MULTICHIP_OPS", 8))
    nds = [int(x) for x in os.environ.get(
        "BENCH_MULTICHIP_DEVICES", "1,2,4,8"
    ).split(",")]
    if argv:
        nds = [int(x) for x in argv]
    here = os.path.dirname(os.path.abspath(__file__))
    per_device = {}
    failure = None
    tail = ""
    for nd in nds:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial a tunnel
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={nd}")
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": " ".join(flags),
            "CRDT_TPU_SHARDS": str(nd),
            # the scale union must take the sharded route on every
            # multi-device child regardless of the size gate
            "CRDT_TPU_SHARD_MIN_ROWS": "1",
        })
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--multichip-child", str(R), str(K)],
            env=env, capture_output=True, text=True, timeout=1800,
            cwd=here,
        )
        lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
        tail = (lines[-1] if lines else "")[:1500]
        if proc.returncode != 0 or not lines:
            failure = {
                "n_devices": nd,
                "rc": proc.returncode,
                "stdout_tail": proc.stdout[-500:],
                "stderr_tail": proc.stderr[-800:],
            }
            log(f"multichip child nd={nd} failed rc={proc.returncode}: "
                f"{proc.stderr[-300:]}")
            break
        leg = json.loads(lines[-1])
        per_device[str(nd)] = leg
        log(f"multichip nd={nd}: converge {leg['converge_s']}s "
            f"{leg['converge_runs_s']} e2e {leg['e2e_s']}s "
            f"boundary {leg['boundary_bytes']}B")

    payload = {
        "replicas": R,
        "ops_per_replica": K,
        "device_counts": nds,
        "per_device": per_device,
    }
    ok = failure is None and bool(per_device)
    if ok:
        digests = {leg["digest"] for leg in per_device.values()}
        if len(digests) != 1:
            ok = False
            failure = {"divergence": {
                nd: leg["digest"] for nd, leg in per_device.items()
            }}
        else:
            payload["byte_identical"] = True
    if ok and "1" in per_device:
        t1 = per_device["1"]["converge_s"]
        payload["scaling_efficiency"] = {
            nd: round(t1 / max(leg["converge_s"], 1e-9), 2)
            for nd, leg in per_device.items() if nd != "1"
        }
        big = per_device[str(max(
            int(nd) for nd in per_device if nd != "1"
        ))] if len(per_device) > 1 else None
        if big:
            payload["boundary_bytes"] = big["boundary_bytes"]
            payload["staged_bytes"] = big["staged_bytes"]
            payload["boundary_fraction"] = round(
                big["boundary_bytes"] / max(big["staged_bytes"], 1), 4
            )
    if failure is not None:
        payload["failure"] = failure
    # LOUD: an empty per_device payload is a failed run, full stop —
    # but scaling efficiency is only demanded when the run requested
    # BOTH the nd=1 baseline and a multi-device leg (a custom `1`- or
    # `2,4`-only run has no ratio to form and is still a success)
    ok = ok and bool(per_device)
    if 1 in nds and any(n != 1 for n in nds):
        ok = ok and bool(payload.get("scaling_efficiency"))
    artifact = {
        "n_devices": max(nds) if nds else 0,
        "rc": 0 if ok else 1,
        "ok": ok,
        "skipped": False,
        "tail": tail,
        "multichip": payload,
    }
    try:
        with open(MULTICHIP_OUT, "w") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
            f.write("\n")
    except OSError as exc:
        log(f"{MULTICHIP_OUT} not written: {exc}")
    # merge the gated section into the committed bench artifact
    if ok:
        try:
            with open(BENCH_OUT) as f:
                full = json.load(f)
        except (OSError, ValueError):
            full = {}
        full["multichip"] = payload
        try:
            with open(BENCH_OUT, "w") as f:
                json.dump(full, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError as exc:
            log(f"{BENCH_OUT} not written: {exc}")
    print(json.dumps({
        "metric": "multichip_scaling",
        "ok": ok,
        "scaling_efficiency": payload.get("scaling_efficiency"),
        "boundary_fraction": payload.get("boundary_fraction"),
        "full_results": os.path.basename(MULTICHIP_OUT),
    }))
    return 0 if ok else 1


def build_doc_trace(n_small: int, ops_small: int, n_big: int,
                    ops_big: int, seed: int = 17) -> dict:
    """Mixed-tenant trace (round 14): ``n_small`` single-writer docs
    of ``ops_small`` ops each (one map root + one list root + a few
    tombstones — the idle-tenant shape that dominates a production
    server's doc population) plus ``n_big`` multi-writer docs of
    ``ops_big`` ops (the shared build_trace shape). Returns
    ``{doc_id: [v1 update blobs]}``; doc ids sort small-docs-first."""
    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    docs = {}
    for i in range(n_small):
        rng = np.random.default_rng(seed + i)
        client = 1 + int(rng.integers(0, 1 << 20))
        recs = []
        chain: list = []
        n_map = ops_small // 3
        for k in range(n_map):
            recs.append(ItemRecord(
                client=client, clock=k, parent_root="m",
                key=f"k{int(rng.integers(0, 24))}",
                content=int(i * 31 + k),
            ))
        for k in range(n_map, ops_small):
            recs.append(ItemRecord(
                client=client, clock=k, parent_root="l",
                origin=chain[-1] if chain else None,
                content=int(i + k),
            ))
            chain.append((client, k))
        ds = DeleteSet()
        ds.add(client, n_map)
        docs[f"t{i:05d}"] = [v1.encode_update(recs, ds)]
    for j in range(n_big):
        docs[f"zbig{j}"] = build_trace(
            8, max(ops_big // 8, 1), seed=seed + 7000 + j
        )
    return docs


def multitenant_leg() -> dict:
    """The ``--multitenant`` evidence (round 14, ROADMAP item 2): a
    heavy mixed-tenant trace (many small docs + a few large) through
    :class:`crdt_tpu.models.multidoc.MultiDocServer` twice —

    - **baseline**: ``pack_docs=False`` — one dispatch per doc
      through the stock replay pipeline (the pre-round-14 serving
      shape, and the per-doc ORACLE: every packed digest is asserted
      against it);
    - **packed**: doc-packed dispatch batches + the vectorized
      unpack + the double-buffered async pipeline.

    Publishes ``docs_converged_per_s`` (both modes), ``speedup``,
    ``p99_per_doc_ms``, ``dispatches_per_tick``, and the flooding-
    tenant chaos digest (shed counters + untouched-neighbor check),
    all regression-gated in tools/metrics_diff.py. Decode/staging
    runs on the ingest side (``prepare()``) for BOTH modes, so the
    ratio isolates what the tentpole changes: dispatch amortization
    and the unpack."""
    from crdt_tpu.models import replay as _rp
    from crdt_tpu.models.multidoc import MultiDocServer

    D = int(os.environ.get("BENCH_MT_DOCS", 1000))
    K = int(os.environ.get("BENCH_MT_OPS", 64))
    n_big = int(os.environ.get("BENCH_MT_BIG", 4))
    big_ops = int(os.environ.get("BENCH_MT_BIG_OPS", 4096))
    max_rows = int(os.environ.get("BENCH_MT_MAX_ROWS", 1 << 14))
    docs = build_doc_trace(D, K, n_big, big_ops)
    n_docs = len(docs)

    def run(pack: bool):
        srv = MultiDocServer(pack_docs=pack,
                             max_rows_per_dispatch=max_rows)
        for d, bs in docs.items():
            srv.submit_many(d, bs)
        srv.prepare()  # ingest-side decode, untimed in both modes
        t0 = time.perf_counter()
        rep = srv.tick()
        while srv.dirty_docs():
            rep2 = srv.tick()
            rep = rep._replace(
                docs=rep.docs + rep2.docs,
                dispatches=rep.dispatches + rep2.dispatches,
            )
        return time.perf_counter() - t0, rep, srv

    run(True)   # warm (compile) — untimed, like every bench warmup
    run(False)
    t_packed, rep_p, packed_srv = run(True)
    t_base, rep_b, base_srv = run(False)

    mismatches = sum(
        packed_srv.digest(d) != base_srv.digest(d) for d in docs
    )
    # independent oracle spot-check: replay_trace of a sample
    sample = list(docs)[:3] + list(docs)[-1:]
    for d in sample:
        if docs[d] and packed_srv.cache(d) != _rp.replay_trace(
                docs[d]).cache:
            mismatches += 1

    def p99_ms(srv):
        lat = [srv.latency_s(d) for d in docs
               if srv.latency_s(d) is not None]
        return round(float(np.percentile(lat, 99)) * 1e3, 2) \
            if lat else None

    # flooding-tenant chaos: one tenant blows a tiny budget while
    # neighbors converge; the flooder is shed ALONE — every other
    # tenant's converged bytes match its unflooded baseline digest.
    # Neighbors are SMALL docs (each a single under-budget blob), so
    # the only tenant the tiny chaos budget can touch is the flooder
    flood_docs = {d: docs[d] for d in list(docs)[:min(32, D)]}
    # slo_ms=0: every SERVED blob trivially breaches, so the chaos
    # leg lights the slo.breaches registry deterministically AND the
    # per-tenant route mix + shed==breach attribution of the flooder
    # rides the committed evidence (shed blobs are breaches by
    # definition — they are never served)
    chaos = MultiDocServer(max_rows_per_dispatch=max_rows,
                           tenant_max_pending_bytes=2048,
                           tenant_max_pending_updates=4,
                           slo_ms=0.0)
    for d, bs in flood_docs.items():
        chaos.submit_many(d, bs)
    flooder = "flood!"
    for blob in build_doc_trace(24, K, 0, 0, seed=9090).values():
        chaos.submit_many(flooder, blob)
    chaos.prepare()
    chaos.tick()
    neighbors_ok = all(
        chaos.digest(d) == base_srv.digest(d) for d in flood_docs
    )
    chaos_slo = chaos.slo.report()
    flooder_slo = chaos_slo["tenants"].get(flooder, {})

    out = {
        "docs": n_docs,
        "small_docs": D,
        "ops_per_small_doc": K,
        "big_docs": n_big,
        "ops_per_big_doc": big_ops,
        "max_rows_per_dispatch": max_rows,
        "baseline_s": round(t_base, 3),
        "packed_s": round(t_packed, 3),
        "docs_converged_per_s": round(n_docs / t_packed, 1),
        "baseline_docs_per_s": round(n_docs / t_base, 1),
        "speedup": round(t_base / t_packed, 2),
        "p99_per_doc_ms": p99_ms(packed_srv),
        "baseline_p99_per_doc_ms": p99_ms(base_srv),
        "dispatches_per_tick": rep_p.dispatches,
        "baseline_dispatches": rep_b.dispatches,
        "digest_mismatches": mismatches,
        "oracle_identical": mismatches == 0,
        "flood": {
            "shed_updates": chaos.shed_count,
            "shed_bytes": chaos.shed_bytes,
            "bounded": chaos.shed_count > 0,
            "neighbors_unchanged": neighbors_ok,
            # round 18: the flooder's SLO ledger — shed folds into
            # breaches (a shed update misses any finite objective),
            # so breaches >= shed, attributed to the ONE tenant
            "slo_flooder": {
                "breaches": flooder_slo.get("breaches", 0),
                "burn_rate": flooder_slo.get("burn_rate", 0.0),
                "routes": flooder_slo.get("routes", {}),
                "shed_equals_route": (
                    flooder_slo.get("routes", {}).get("shed", 0)
                    == chaos.shed_count
                ),
            },
        },
        # round 18: the packed contender's per-tenant SLO digest —
        # the full per-tenant report is scrapeable live (/snapshot);
        # the artifact keeps the summary shape
        "slo": _slo_digest(packed_srv),
    }
    return out


def _slo_digest(srv) -> dict:
    rep = srv.slo.report()
    return {
        "slo_ms": rep["slo_ms"],
        "tenants": len(rep["tenants"]),
        "total_breaches": rep["total_breaches"],
        "worst_burn_rate": rep["worst_burn_rate"],
    }


class _SteadyStream:
    """Single-writer doc generator whose every delta continues the
    client's clock contiguously — the SV-admissible steady-state
    shape the round-15 delta ticks serve (one map root + one chained
    list root, the small-tenant mix of build_doc_trace)."""

    def __init__(self, i: int):
        self.client = 1 + i
        self.i = i
        self.k = 0
        self.chain = None
        self.map_tail: dict = {}

    def delta(self, n_ops: int) -> bytes:
        from crdt_tpu.codec import v1
        from crdt_tpu.core.ids import DeleteSet
        from crdt_tpu.core.records import ItemRecord

        recs = []
        for j in range(n_ops):
            k = self.k
            self.k += 1
            if j % 4 == 0:
                # a map set chains onto the key's previous value
                # (origin = prior item), the Yjs Y.Map wire shape —
                # and the O(1) tail-advance the incremental engine
                # serves it with
                key = f"k{(self.i + j) % 16}"
                recs.append(ItemRecord(
                    client=self.client, clock=k, parent_root="m",
                    key=key, origin=self.map_tail.get(key),
                    content=int(self.i * 31 + k),
                ))
                self.map_tail[key] = (self.client, k)
            else:
                recs.append(ItemRecord(
                    client=self.client, clock=k, parent_root="l",
                    origin=self.chain, content=int(self.i + k),
                ))
                self.chain = (self.client, k)
        return v1.encode_update(recs, DeleteSet())


def multitenant_steady_leg() -> dict:
    """The round-15 steady-state evidence: N ticks of SMALL deltas on
    LARGE resident docs through :class:`MultiDocServer` twice —

    - **full replay** (``delta_ticks=False``): the round-14 tick —
      every dirty doc re-decodes and re-converges its FULL history
      (the pre-round-15 serving shape, and the per-doc ORACLE: every
      steady digest is asserted against it);
    - **delta ticks**: per-doc resident incremental engines — a tick
      stages only the delta rows (history stays resident), so
      steady-state throughput is bounded by delta size, not doc
      size.

    The timed window covers submit + prepare + tick (the full
    per-tick serving cost, decode included — that is exactly what
    the full-replay baseline pays per tick and the delta route
    avoids). The cold ingest and the one-time promotion tick are
    warmup, like every bench warm phase. FAILS LOUDLY (RuntimeError)
    when the incremental route silently degrades every doc to cold
    replay — the gated speedup must never rot into measuring the
    fallback.

    The eviction sub-leg floods 10x more docs than the resident
    budget fits (``resident_max_bytes``) in rolling waves: committed
    resident bytes stay <= budget (the ledger's peak), evictions
    fire, and an evicted doc reconverges byte-identically on its
    next touch."""
    from crdt_tpu.models import replay as _rp
    from crdt_tpu.models.incremental import IncrementalReplay
    from crdt_tpu.models.multidoc import MultiDocServer

    D = int(os.environ.get("BENCH_MT_STEADY_DOCS", 32))
    K = int(os.environ.get("BENCH_MT_STEADY_OPS", 8192))
    delta_ops = int(os.environ.get("BENCH_MT_STEADY_DELTA", 4))
    ticks = int(os.environ.get("BENCH_MT_STEADY_TICKS", 4))

    # one shared trace: both contenders replay the SAME blobs
    streams = [_SteadyStream(i) for i in range(D)]
    ids = [f"s{i:04d}" for i in range(D)]
    history = [[s.delta(K)] for s in streams]
    warm = [[s.delta(delta_ops) for s in streams] for _ in range(2)]
    tick_deltas = [
        [s.delta(delta_ops) for s in streams] for _ in range(ticks)
    ]
    full = [
        history[i] + [w[i] for w in warm]
        + [td[i] for td in tick_deltas]
        for i in range(D)
    ]

    def run(delta_mode: bool):
        srv = MultiDocServer(delta_ticks=delta_mode)
        for i, d in enumerate(ids):
            srv.submit(d, history[i][0])
        srv.prepare()
        srv.tick()                      # cold ingest — untimed
        for w in warm:                  # two untimed warm ticks: the
            for i, d in enumerate(ids):  # promotion build, then the
                srv.submit(d, w[i])     # first delta (one-time chain
            srv.prepare()               # link build) — the timed
            srv.tick()                  # window is pure steady state
        delta_serves = 0
        t0 = time.perf_counter()
        for t in range(ticks):
            for i, d in enumerate(ids):
                srv.submit(d, tick_deltas[t][i])
            srv.prepare()
            rep = srv.tick()
            delta_serves += rep.delta_docs
        return time.perf_counter() - t0, delta_serves, srv

    run(True)                           # compile/calibration warmup
    t_steady, delta_serves, steady_srv = run(True)
    t_full, _, full_srv = run(False)

    if delta_serves == 0:
        # the loud-failure satellite: a silently degraded incremental
        # route would leave the "speedup" measuring cold replay twice
        raise RuntimeError(
            "steady leg: tenant.delta_docs == 0 — the incremental "
            "route degraded every doc to cold replay"
        )

    mismatches = sum(
        steady_srv.digest(d) != full_srv.digest(d) for d in ids
    )
    for i in (0, D // 2, D - 1):        # independent oracle spot-check
        if steady_srv.cache(ids[i]) != _rp.replay_trace(
                full[i]).cache:
            mismatches += 1
    # beacon twice: the digest cache must skip the clean population
    # (sentinel.doc_digest_skips — pinned by the smoke registry leg)
    steady_srv.doc_digests()
    steady_srv.doc_digests()

    # ---- eviction sub-leg: bounded under a 10x doc-count flood ----
    flood_D = int(os.environ.get("BENCH_MT_STEADY_FLOOD_DOCS", 40))
    flood_K = int(os.environ.get("BENCH_MT_STEADY_FLOOD_OPS", 256))
    fit = max(2, flood_D // 10)
    budget = IncrementalReplay.estimate_resident_bytes(
        flood_K + 4 * delta_ops
    ) * fit
    fstreams = [_SteadyStream(1000 + i) for i in range(flood_D)]
    fids = [f"f{i:04d}" for i in range(flood_D)]
    fhist = [[s.delta(flood_K)] for s in fstreams]
    fsrv = MultiDocServer(delta_ticks=True,
                          resident_max_bytes=budget)
    for i, d in enumerate(fids):
        fsrv.submit(d, fhist[i][0])
    fsrv.tick()                         # cold ingest
    for _pass in range(2):              # rolling promote waves: LRU
        for start in range(0, flood_D, fit):
            for i in range(start, min(start + fit, flood_D)):
                b = fstreams[i].delta(delta_ops)
                fhist[i].append(b)
                fsrv.submit(fids[i], b)
            fsrv.tick()
    peak = fsrv.resident_peak_bytes()
    evicted = [d for d in fids if not fsrv.is_resident(d)]
    reconverge_ok = False
    if evicted:
        d = evicted[0]
        i = fids.index(d)
        b = fstreams[i].delta(delta_ops)
        fhist[i].append(b)
        fsrv.submit(d, b)
        fsrv.tick()
        reconverge_ok = (
            fsrv.cache(d) == _rp.replay_trace(fhist[i]).cache
        )

    return {
        "docs": D,
        "ops_per_doc": K,
        "delta_ops_per_doc": delta_ops,
        "ticks": ticks,
        "steady_s": round(t_steady, 4),
        "full_replay_s": round(t_full, 4),
        "docs_per_s": round(D * ticks / t_steady, 1),
        "full_replay_docs_per_s": round(D * ticks / t_full, 1),
        "speedup": round(t_full / t_steady, 2),
        "delta_docs_per_tick": delta_serves / ticks,
        "delta_rows_per_tick": delta_ops * D,
        "digest_mismatches": mismatches,
        "oracle_identical": mismatches == 0,
        "slo": _slo_digest(steady_srv),
        "eviction": {
            "flood_docs": flood_D,
            "ops_per_doc": flood_K,
            "budget_bytes": int(budget),
            "peak_bytes": int(peak),
            "evictions": fsrv.eviction_count,
            "bounded": peak <= budget and fsrv.eviction_count > 0,
            "reconverge_identical": reconverge_ok,
        },
    }


def multitenant_pooled_leg() -> dict:
    """The round-20 pooled-resident-matrix evidence: N warm docs ALL
    above the device crossover (``CRDT_TPU_DEVICE_MIN=1`` for the
    leg), small deltas per tick — the pooled route batches every
    doc's device round into ONE splice+converge dispatch
    (:class:`crdt_tpu.ops.resident.ResidentPool`), the unpooled
    baseline pays one per doc. The leg measures the DISPATCH COUNT
    per steady tick (``packed.device_dispatch_count`` delta — a
    count, not a timing: the gate never rides the ms noise floor)
    and publishes the pool's own counters; digests are asserted
    byte-identical between the two routes."""
    from crdt_tpu.models.multidoc import MultiDocServer
    from crdt_tpu.ops import packed as pk

    D = int(os.environ.get("BENCH_MT_POOLED_DOCS", 8))
    K = int(os.environ.get("BENCH_MT_POOLED_OPS", 384))
    delta_ops = int(os.environ.get("BENCH_MT_POOLED_DELTA", 4))
    ticks = int(os.environ.get("BENCH_MT_POOLED_TICKS", 4))

    streams = [_SteadyStream(500 + i) for i in range(D)]
    ids = [f"p{i:04d}" for i in range(D)]
    history = [[s.delta(K)] for s in streams]
    warm = [[s.delta(delta_ops) for s in streams] for _ in range(2)]
    tick_deltas = [
        [s.delta(delta_ops) for s in streams] for _ in range(ticks)
    ]

    def run(pool: bool):
        srv = MultiDocServer(delta_ticks=True, pool=pool)
        for i, d in enumerate(ids):
            srv.submit(d, history[i][0])
        srv.prepare()
        srv.tick()                      # cold ingest — untimed
        for w in warm:                  # promotion + first delta
            for i, d in enumerate(ids):
                srv.submit(d, w[i])
            srv.prepare()
            srv.tick()
        d0 = pk.device_dispatch_count
        t0 = time.perf_counter()
        for t in range(ticks):
            for i, d in enumerate(ids):
                srv.submit(d, tick_deltas[t][i])
            srv.prepare()
            srv.tick()
        dt = time.perf_counter() - t0
        return (pk.device_dispatch_count - d0) / ticks, dt, srv

    # force every doc above the crossover: the evidence IS the
    # dispatch count, and below the crossover both routes host-route
    prev = os.environ.get("CRDT_TPU_DEVICE_MIN")
    os.environ["CRDT_TPU_DEVICE_MIN"] = "1"
    try:
        dp, t_pooled, srv_p = run(True)
        du, t_unpooled, srv_u = run(False)
    finally:
        if prev is None:
            os.environ.pop("CRDT_TPU_DEVICE_MIN", None)
        else:
            os.environ["CRDT_TPU_DEVICE_MIN"] = prev

    mismatches = sum(
        srv_p.digest(d) != srv_u.digest(d) for d in ids
    )
    pool = srv_p.pool
    return {
        "pooled_docs": D,
        "pooled_ops_per_doc": K,
        # the tentpole number: steady device dispatches per tick,
        # pooled (O(1)) vs per-doc (O(docs)) — gated lower-is-better
        # with count semantics in tools/metrics_diff.py
        "device_dispatches_per_tick": dp,
        "unpooled_dispatches_per_tick": du,
        "dispatch_reduction": round(du / dp, 2) if dp else None,
        "pooled_tick_s": round(t_pooled, 4),
        "unpooled_tick_s": round(t_unpooled, 4),
        "pool_dispatches": pool.dispatches if pool else 0,
        "pool_docs": pool.doc_count() if pool else 0,
        "pool_bytes": pool.device_bytes() if pool else 0,
        "pool_peak_bytes": pool.peak_bytes if pool else 0,
        "pool_compactions": pool.compactions if pool else 0,
        "pooled_oracle_identical": mismatches == 0,
    }


def multitenant(argv=None) -> int:
    """The ``--multitenant`` harness: run the round-14 packing leg
    AND the round-15 steady-state leg, merge the gated section into
    BENCH_OUT.json (like ``--multichip``), one summary line on
    stdout. Exits non-zero on a divergent, unshed, unbounded, or
    under-10x-steady run — a wrong document, an unbounded flood, or
    a rotten delta route must never publish as evidence."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)
    from crdt_tpu.obs import (
        TickTimeline, Tracer, set_timeline, set_tracer,
    )

    tracer = None
    timeline = None
    if os.environ.get("BENCH_TRACE", "1") != "0":
        tracer = set_tracer(Tracer(enabled=True))
        timeline = set_timeline(TickTimeline(enabled=True))
    leg = multitenant_leg()
    leg["steady"] = multitenant_steady_leg()
    # the round-20 pooled dispatch-floor keys publish at the steady
    # level: multitenant.steady.device_dispatches_per_tick (and the
    # pool counters) are what tools/metrics_diff.py gates
    leg["steady"].update(multitenant_pooled_leg())
    if tracer is not None:
        counters = tracer.counters()
        leg["docs_packed_counted"] = counters.get(
            "converge.docs_packed", 0)
        leg["tenant_shed_counted"] = counters.get("tenant.shed", 0)
        leg["steady"]["delta_docs_counted"] = counters.get(
            "tenant.delta_docs", 0)
        leg["steady"]["evictions_counted"] = counters.get(
            "tenant.resident_evictions", 0)
    if timeline is not None and len(timeline):
        # the Perfetto artifact (round 18): every tick of both legs
        # as a zoomable trace next to BENCH_OUT.json, its summary in
        # the gated evidence (open the file at ui.perfetto.dev)
        tl_path = os.environ.get(
            "BENCH_TIMELINE_OUT",
            os.path.join(os.path.dirname(BENCH_OUT),
                         "BENCH_TIMELINE.json"),
        )
        recs = timeline.records()
        effs = [r["overlap_efficiency"] for r in recs
                if len(r["dispatches"]) > 1]
        leg["timeline"] = {
            "ticks_recorded": timeline.recorded,
            "double_buffered_ticks": len(effs),
            "mean_overlap_efficiency": (
                round(sum(effs) / len(effs), 4) if effs else None
            ),
            "stall_ms_total": round(
                sum(r["stall_ms"] for r in recs), 3),
            "artifact": os.path.basename(tl_path),
        }
        try:
            timeline.perfetto_json(tl_path)
        except OSError as exc:
            log(f"{tl_path} not written: {exc}")
    ok = bool(leg.get("oracle_identical")) \
        and bool(leg["flood"]["bounded"]) \
        and bool(leg["flood"]["neighbors_unchanged"]) \
        and bool(leg["steady"]["oracle_identical"]) \
        and leg["steady"]["speedup"] >= 10 \
        and bool(leg["steady"]["eviction"]["bounded"]) \
        and bool(leg["steady"]["eviction"]["reconverge_identical"]) \
        and bool(leg["steady"]["pooled_oracle_identical"]) \
        and leg["steady"]["device_dispatches_per_tick"] <= 2 \
        and leg["steady"]["device_dispatches_per_tick"] \
        < leg["steady"]["unpooled_dispatches_per_tick"]
    if ok:
        try:
            with open(BENCH_OUT) as f:
                full = json.load(f)
        except (OSError, ValueError):
            full = {}
        full["multitenant"] = leg
        try:
            with open(BENCH_OUT, "w") as f:
                json.dump(full, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError as exc:
            log(f"{BENCH_OUT} not written: {exc}")
    print(json.dumps({
        "metric": "multitenant_packing",
        "ok": ok,
        "docs_converged_per_s": leg["docs_converged_per_s"],
        "baseline_docs_per_s": leg["baseline_docs_per_s"],
        "speedup": leg["speedup"],
        "p99_per_doc_ms": leg["p99_per_doc_ms"],
        "dispatches_per_tick": leg["dispatches_per_tick"],
        "steady_docs_per_s": leg["steady"]["docs_per_s"],
        "steady_speedup": leg["steady"]["speedup"],
        "steady_evictions": leg["steady"]["eviction"]["evictions"],
        "steady_device_dispatches_per_tick":
            leg["steady"]["device_dispatches_per_tick"],
        "full_results": os.path.basename(BENCH_OUT),
    }))
    return 0 if ok else 1


def coldstart_leg() -> dict:
    """The ``--coldstart`` evidence (round 21, ROADMAP item 4): a
    scale doc joins via device-layout snapshot + WAL tail vs the full
    cold replay every crash pays without one — digest-asserted, with
    the corruption rung exercised (bit-flipped snapshot must fall
    back to WAL byte-identically) — plus the whole-server
    checkpoint/restore round-trip of a warm resident set.

    Knobs: ``BENCH_COLD_OPS`` (scale-doc op count, default 120000),
    ``BENCH_COLD_DELTA`` (ops per WAL append, default 200),
    ``BENCH_COLD_TAIL`` (post-snapshot tail appends, default 8),
    ``BENCH_COLD_DOCS`` (server-leg warm docs, default 8)."""
    import shutil
    import tempfile

    from crdt_tpu.models.multidoc import MultiDocServer, cache_digest
    from crdt_tpu.models.replay import cold_start
    from crdt_tpu.obs import get_tracer
    from crdt_tpu.storage import snapshot as _sn
    from crdt_tpu.storage.persistence import LogPersistence

    n_ops = int(os.environ.get("BENCH_COLD_OPS", "120000"))
    per = int(os.environ.get("BENCH_COLD_DELTA", "200"))
    tail = int(os.environ.get("BENCH_COLD_TAIL", "8"))
    n_docs = int(os.environ.get("BENCH_COLD_DOCS", "8"))
    root = tempfile.mkdtemp(prefix="crdt_cold_")
    wal = None
    try:
        wal = LogPersistence(os.path.join(root, "wal.kvlog"))
        store = _sn.SnapshotStore(os.path.join(root, "snaps"))
        s = _SteadyStream(0)
        for _ in range(max(1, n_ops // per)):
            wal.store_update("scale", s.delta(per))
        # the snapshot rider compacts the WAL and writes the
        # device-layout snapshot at the same seq; then a short tail
        # of post-snapshot appends (the live-traffic window)
        eng, _ = cold_start("scale", wal, None)
        assert _sn.compact_with_snapshot(wal, "scale", eng, store)
        for _ in range(tail):
            wal.store_update("scale", s.delta(per))
        # baseline: the WAL-only rung (decode + full converge of the
        # compacted history) — what a restart pays without a snapshot
        t0 = time.perf_counter()
        eng_wal, path_wal = cold_start("scale", wal, None)
        replay_ms = (time.perf_counter() - t0) * 1e3
        assert path_wal == "wal"
        ref_digest = cache_digest(eng_wal.cache)
        # the join: snapshot load + tail replay only
        join_ms = None
        for _ in range(3):
            t0 = time.perf_counter()
            eng_snap, path_snap = cold_start("scale", wal, store)
            dt = (time.perf_counter() - t0) * 1e3
            join_ms = dt if join_ms is None else min(join_ms, dt)
        assert path_snap == "snapshot"
        identical = cache_digest(eng_snap.cache) == ref_digest
        # the corruption rung: a bit-flipped snapshot must reject and
        # fall back to WAL replay byte-identically (counted)
        snaps_dir = os.path.join(root, "snaps")
        snap_file = [n for n in os.listdir(snaps_dir)
                     if n.endswith(".snap")][0]
        p = os.path.join(snaps_dir, snap_file)
        with open(p, "rb") as f:
            pristine = f.read()
        damaged = bytearray(pristine)
        damaged[len(damaged) // 2] ^= 0x40
        with open(p, "wb") as f:
            f.write(bytes(damaged))
        fb0 = sum(v for k, v in get_tracer().counters().items()
                  if k.startswith("snap.fallbacks"))
        eng_fb, path_fb = cold_start("scale", wal, store)
        fb1 = sum(v for k, v in get_tracer().counters().items()
                  if k.startswith("snap.fallbacks"))
        fallback_recovered = (
            path_fb == "wal"
            and cache_digest(eng_fb.cache) == ref_digest
            and (not get_tracer().enabled or fb1 > fb0)
        )
        with open(p, "wb") as f:
            f.write(pristine)
        # the server leg: warm N docs, checkpoint the resident set,
        # restore it into a fresh server, digest-asserted per doc
        srv = MultiDocServer(snap_store=store)
        streams = {f"doc{i}": _SteadyStream(i + 1)
                   for i in range(n_docs)}
        for _ in range(4):
            for d, ds_ in streams.items():
                srv.submit_many(d, [ds_.delta(24) for _ in range(3)])
            srv.tick()
        warm = sum(1 for st in srv._docs.values()
                   if st.resident is not None)
        t0 = time.perf_counter()
        n_ckpt = srv.checkpoint()
        checkpoint_ms = (time.perf_counter() - t0) * 1e3
        srv2 = MultiDocServer(snap_store=store)
        t0 = time.perf_counter()
        n_restored = srv2.restore()
        restore_ms = (time.perf_counter() - t0) * 1e3
        server_identical = all(
            cache_digest(srv2._cache_of(srv2._docs[d]))
            == cache_digest(srv._cache_of(srv._docs[d]))
            for d in srv._docs
        )
        return {
            "n_ops": n_ops + tail * per,
            "replay_ms": round(replay_ms, 3),
            "join_ms": round(join_ms, 3),
            "speedup": round(replay_ms / join_ms, 2),
            "oracle_identical": bool(identical),
            "fallback_recovered": bool(fallback_recovered),
            "checkpoint_docs": n_ckpt,
            "restore_docs": n_restored,
            "warm_docs": warm,
            "checkpoint_ms": round(checkpoint_ms, 3),
            "restore_ms": round(restore_ms, 3),
            "server_identical": bool(server_identical),
        }
    finally:
        if wal is not None:
            wal.close()
        shutil.rmtree(root, ignore_errors=True)


def coldstart(argv=None) -> int:
    """The ``--coldstart`` harness: run the round-21 snapshot-join
    leg, merge the gated ``cold_start`` section into BENCH_OUT.json
    (like ``--multitenant``), one summary line on stdout. Exits
    non-zero on a divergent join, an unrecovered corruption, a lost
    checkpoint doc, or an under-5x speedup — a wrong or slow recovery
    path must never publish as evidence."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from crdt_tpu.obs import Tracer, set_tracer

    tracer = None
    if os.environ.get("BENCH_TRACE", "1") != "0":
        tracer = set_tracer(Tracer(enabled=True))
    leg = coldstart_leg()
    if tracer is not None:
        counters = tracer.counters()
        leg["snap_writes_counted"] = counters.get("snap.writes", 0)
        leg["snap_loads_counted"] = counters.get("snap.loads", 0)
        leg["snap_fallbacks_counted"] = sum(
            v for k, v in counters.items()
            if k.startswith("snap.fallbacks"))
    ok = bool(leg["oracle_identical"]) \
        and bool(leg["fallback_recovered"]) \
        and bool(leg["server_identical"]) \
        and leg["restore_docs"] == leg["checkpoint_docs"] \
        and leg["checkpoint_docs"] == leg["warm_docs"] \
        and leg["warm_docs"] > 0 \
        and leg["speedup"] >= 5
    if ok:
        try:
            with open(BENCH_OUT) as f:
                full = json.load(f)
        except (OSError, ValueError):
            full = {}
        full["cold_start"] = leg
        try:
            with open(BENCH_OUT, "w") as f:
                json.dump(full, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError as exc:
            log(f"{BENCH_OUT} not written: {exc}")
    print(json.dumps({
        "metric": "cold_start",
        "ok": ok,
        "n_ops": leg["n_ops"],
        "replay_ms": leg["replay_ms"],
        "join_ms": leg["join_ms"],
        "speedup": leg["speedup"],
        "checkpoint_docs": leg["checkpoint_docs"],
        "restore_ms": leg["restore_ms"],
        "full_results": os.path.basename(BENCH_OUT),
    }))
    return 0 if ok else 1


def build_subtree_trace(R: int, K: int, seed: int = 5):
    """The round-23 hard case the shared-anchor conflict trace only
    grazes: many writers grow one hot list as a BRANCHING tree (every
    op anchors a uniformly random earlier op — wide stars, bushy
    subtrees, caterpillar spines all occur, never a flat chain), half
    the anchors landing on client 1's shared heads, plus deep
    origin-chained LWW sets on a handful of hot map keys. Without the
    subtree split the doubling-rounds bound tracks the whole hot
    segment and the deepest key chain; with it, the split width."""
    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    rng = np.random.default_rng(seed)
    n_map = (K * 3) // 10
    blobs = []
    for r in range(R):
        client = r + 1
        recs = []
        last_set: dict = {}
        for k in range(n_map):
            key = int(rng.integers(0, 8))
            prev_set = last_set.get(key)
            recs.append(ItemRecord(
                client=client, clock=k, parent_root="m0",
                key=f"k{key}", content=k,
                origin=(client, prev_set)
                if prev_set is not None else None,
            ))
            last_set[key] = k
        own: list = []
        for k in range(n_map, K):
            if client == 1 and len(own) < 8:
                origin = None  # the shared heads everyone piles onto
            elif own and rng.random() < 0.5:
                origin = (client, own[int(rng.integers(0, len(own)))])
            elif rng.random() < 0.5:
                # pile onto a shared head -> R-wide sibling groups
                origin = (1, n_map + int(rng.integers(0, 8)))
            else:
                origin = None
            recs.append(ItemRecord(
                client=client, clock=k, parent_root="hot",
                origin=origin, content=k,
            ))
            own.append(k)
        ds = DeleteSet()
        for k in rng.choice(K - n_map, size=max(1, K // 25),
                            replace=False):
            ds.add(client, int(n_map + k))
        blobs.append(v1.encode_update(recs, ds))
    return blobs


def conflict_leg() -> dict:
    """The ``--conflict`` evidence (round 23): replay the branching
    hot-list + deep-map-chain trace with the subtree split DISABLED
    (the oracle), then at widths {1, odd, default} single-chip and
    2/4-way sharded — every leg digest-asserted byte-identical
    against the oracle (cache AND snapshot), with the staged
    ``converge.wyllie_rounds`` / ``converge.map_rounds`` bounds and
    the ``converge.subtree_cuts`` / ``converge.map_chain_cuts``
    counts read from the tracer at the gated width.

    Knobs: ``BENCH_CONFLICT_REPLICAS`` (writers, default 16),
    ``BENCH_CONFLICT_OPS`` (ops per writer, default 3000),
    ``BENCH_CONFLICT_WIDTH`` (the gated odd width, default 257)."""
    import hashlib

    from crdt_tpu.models import replay as rp
    from crdt_tpu.obs import get_tracer
    from crdt_tpu.ops import packed, shard

    R = int(os.environ.get("BENCH_CONFLICT_REPLICAS", "16"))
    K = int(os.environ.get("BENCH_CONFLICT_OPS", "3000"))
    W = int(os.environ.get("BENCH_CONFLICT_WIDTH", "257"))
    blobs = build_subtree_trace(R, K)
    gauge_names = ("converge.wyllie_rounds", "converge.map_rounds",
                   "converge.subtree_cuts", "converge.map_chain_cuts")

    def run(width, shards=None):
        if width is None:
            os.environ.pop(packed._CHAIN_SPLIT_ENV, None)
        else:
            os.environ[packed._CHAIN_SPLIT_ENV] = str(width)
        if shards is None:
            os.environ.pop(shard.SHARD_ENV, None)
            os.environ.pop(shard.MIN_ROWS_ENV, None)
        else:
            os.environ[shard.SHARD_ENV] = str(shards)
            os.environ[shard.MIN_ROWS_ENV] = "1"
        t0 = time.perf_counter()
        res = rp.replay_trace(blobs)
        e2e_s = round(time.perf_counter() - t0, 3)
        digest = hashlib.sha256(
            json.dumps(res.cache, sort_keys=True).encode()
            + hashlib.sha256(res.snapshot).digest()
        ).hexdigest()
        gauges = get_tracer().report()["gauges"]
        return digest, e2e_s, {g.split(".", 1)[1]: gauges[g]
                               for g in gauge_names if g in gauges}

    ref, oracle_s, oracle_g = run(0)
    legs: dict = {"oracle": {"e2e_s": oracle_s, **oracle_g}}
    identical = True
    for width in (1, W, None):
        d, s, g = run(width)
        name = "default" if width is None else str(width)
        legs[name] = {"e2e_s": s, "identical": d == ref, **g}
        identical = identical and d == ref
    gated = legs[str(W)]
    for shards in (2, 4):
        d, s, _ = run(None, shards=shards)
        legs[f"sharded_{shards}"] = {"e2e_s": s,
                                     "identical": d == ref}
        identical = identical and d == ref
    os.environ.pop(packed._CHAIN_SPLIT_ENV, None)
    os.environ.pop(shard.SHARD_ENV, None)
    os.environ.pop(shard.MIN_ROWS_ENV, None)
    return {
        "replicas": R,
        "ops_per_replica": K,
        "gated_width": W,
        "legs": legs,
        # the gated numbers: the staged rounds bounds at the gated
        # width (lower = better; the tentpole) and the cut counts
        # (the split engaging at all — 0 means the shapes regressed
        # to refused)
        "converge": {
            "wyllie_rounds": gated.get("wyllie_rounds"),
            "map_rounds": gated.get("map_rounds"),
            "subtree_cuts": gated.get("subtree_cuts", 0),
            "map_chain_cuts": gated.get("map_chain_cuts", 0),
        },
        "oracle_rounds": {
            "wyllie_rounds": oracle_g.get("wyllie_rounds"),
            "map_rounds": oracle_g.get("map_rounds"),
        },
        "identical": bool(identical),
    }


def conflict(argv=None) -> int:
    """The ``--conflict`` harness: run the round-23 subtree-split leg,
    merge the gated ``conflict`` section into BENCH_OUT.json (like
    ``--coldstart``), one summary line on stdout. Exits non-zero on
    any divergent digest or when either staged rounds bound fails to
    drop STRICTLY below the split-disabled oracle — a split that is
    wrong, or that stopped engaging, must never publish as
    evidence."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the 2/4-way sharded legs need virtual devices before jax wakes
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    from crdt_tpu.obs import Tracer, set_tracer

    set_tracer(Tracer(enabled=True))
    leg = conflict_leg()
    ok = bool(leg["identical"]) \
        and leg["converge"]["subtree_cuts"] > 0 \
        and leg["converge"]["map_chain_cuts"] > 0 \
        and leg["converge"]["wyllie_rounds"] \
        < leg["oracle_rounds"]["wyllie_rounds"] \
        and leg["converge"]["map_rounds"] \
        < leg["oracle_rounds"]["map_rounds"]
    if ok:
        try:
            with open(BENCH_OUT) as f:
                full = json.load(f)
        except (OSError, ValueError):
            full = {}
        full["conflict"] = leg
        try:
            with open(BENCH_OUT, "w") as f:
                json.dump(full, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError as exc:
            log(f"{BENCH_OUT} not written: {exc}")
    print(json.dumps({
        "metric": "conflict",
        "ok": ok,
        "identical": leg["identical"],
        "wyllie_rounds": leg["converge"]["wyllie_rounds"],
        "map_rounds": leg["converge"]["map_rounds"],
        "oracle_rounds": leg["oracle_rounds"],
        "subtree_cuts": leg["converge"]["subtree_cuts"],
        "map_chain_cuts": leg["converge"]["map_chain_cuts"],
        "full_results": os.path.basename(BENCH_OUT),
    }))
    return 0 if ok else 1


def autopilot_leg() -> dict:
    """The ``--autopilot`` evidence (round 22, ROADMAP item 2): the
    SLO-driven control plane A/B — one flooding tenant beside small
    steady neighbors through :class:`MultiDocServer` twice, identical
    submissions, identical STATIC per-tenant budgets:

    - **OFF** (oracle): no controller — the static budget is the only
      defense (the pre-round-22 serving shape);
    - **ON**: a :class:`crdt_tpu.obs.control.Controller` squeezes the
      breaching flooder's budget, shields its docs from the LRU
      sweep, and restores the static budget with hysteresis once the
      flood drains.

    Burn is driven by SHEDS only (``slo_ms`` is effectively infinite
    and the burn window is outcome-counted, never wall-clock), so the
    recovery evidence is deterministic: each flood blob overflows the
    squeezed byte budget, so under keep-the-newest the squeezed
    flooder sheds 7 of its 8 blobs every flood tick (burn pins at
    14/16), and ``recovery_ticks`` counts calm ticks until burn
    drains to the restore threshold ``burn_lo`` (0 = already there at
    flood end). Neighbor digests must be byte-identical across ON/OFF
    (the squeeze touches ONE tenant) and the ON ledger must replay
    byte-identically from its own sensor trace.
    ``tools/metrics_diff.py`` gates ``autopilot.recovery_ticks`` and
    ``autopilot.neighbor_p99_ms`` (both lower-is-better)."""
    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord
    from crdt_tpu.models.multidoc import MultiDocServer
    from crdt_tpu.obs.control import Controller
    from crdt_tpu.obs.slo import SLOLedger

    flood_ticks = int(os.environ.get("BENCH_AP_FLOOD_TICKS", 6))
    calm_ticks = int(os.environ.get("BENCH_AP_CALM_TICKS", 28))
    n_neighbors = int(os.environ.get("BENCH_AP_NEIGHBORS", 6))
    budget_bytes, budget_updates = 2048, 4
    burn_window = 16
    # "recovered" = burn drained to the controller's restore
    # threshold — the same bar the hysteresis rule applies
    recover_lo = 0.25
    flooder = "flood!"

    def flood_blob(i: int) -> bytes:
        # one independent single-record update (own client, no
        # origin: shedding any subset never orphans a survivor),
        # sized BETWEEN the squeezed byte budget and the static one —
        # the squeezed server sheds every flood blob (burn pins at
        # 1.0), the static server keeps a couple per tick
        return v1.encode_update([ItemRecord(
            client=10_000 + i, clock=0, parent_root="m",
            key=f"f{i}", content="f" * 700,
        )], DeleteSet())

    assert budget_bytes // 4 < len(flood_blob(0)) < budget_bytes, \
        "autopilot: flood blob out of the squeeze band"

    def run(on: bool):
        ctrl = (Controller(cooldown_ticks=4, restore_after=2)
                if on else None)
        srv = MultiDocServer(
            tenant_max_pending_bytes=budget_bytes,
            tenant_max_pending_updates=budget_updates,
            slo_ms=1e9,  # serves never breach: sheds drive burn
            control=ctrl,
        )
        # fast-flushing burn window (16 outcomes, not 128): the
        # restore hysteresis is observable within the calm phase
        srv.slo = SLOLedger(1e9, burn_window=burn_window)
        neighbors = [f"n{i}" for i in range(n_neighbors)]
        streams = {d: _SteadyStream(i)
                   for i, d in enumerate(neighbors)}
        fstream = _SteadyStream(500)
        lat: list = []
        recovery = None
        restore_tick = None
        burn_flood_end = None
        nblob = 0
        for t in range(flood_ticks + calm_ticks):
            if t < flood_ticks:
                for _ in range(8):
                    srv.submit(flooder, flood_blob(nblob))
                    nblob += 1
            else:
                # calm: tiny admissible deltas so the burn window
                # keeps flushing (no outcomes = frozen burn)
                srv.submit(flooder, fstream.delta(2))
            for d in neighbors:
                srv.submit(d, streams[d].delta(4))
            srv.tick()
            for d in neighbors:
                ls = srv.latency_s(d)
                if ls is not None:
                    lat.append(ls)
            burn = srv.slo.report()["tenants"].get(
                flooder, {}).get("burn_rate", 0.0)
            if t == flood_ticks - 1:
                burn_flood_end = burn
                if burn <= recover_lo:
                    recovery = 0
            elif (t >= flood_ticks and recovery is None
                    and burn <= recover_lo):
                recovery = t - flood_ticks + 1
            if (on and restore_tick is None and t >= flood_ticks
                    and not ctrl.overrides()):
                restore_tick = t
        p99 = (round(float(np.percentile(lat, 99)) * 1e3, 3)
               if lat else None)
        return {
            "srv": srv, "ctrl": ctrl, "recovery": recovery,
            "restore_tick": restore_tick, "p99_ms": p99,
            "burn_flood_end": burn_flood_end,
            "neighbors": neighbors,
        }

    run(True)   # warm (compile) — untimed, like every bench warmup
    run(False)
    on = run(True)
    off = run(False)

    neighbors_identical = all(
        on["srv"].digest(d) == off["srv"].digest(d)
        for d in on["neighbors"]
    )
    ctrl = on["ctrl"]
    replay = Controller.replay(list(ctrl.trace), **ctrl.config())
    rules = [r["rule"] for r in ctrl.ledger.rows()]
    return {
        "flood_ticks": flood_ticks,
        "calm_ticks": calm_ticks,
        "neighbors": len(on["neighbors"]),
        "recovery_ticks": on["recovery"],
        "recovery_ticks_off": off["recovery"],
        "recovery_budget_ticks": int(os.environ.get(
            "BENCH_AP_RECOVERY_BUDGET", burn_window)),
        "burn_flood_end": on["burn_flood_end"],
        "burn_flood_end_off": off["burn_flood_end"],
        "neighbor_p99_ms": on["p99_ms"],
        "neighbor_p99_ms_off": off["p99_ms"],
        "neighbors_identical": neighbors_identical,
        "squeezed": "budget_squeeze" in rules,
        "restored": "budget_restore" in rules,
        "restore_tick": on["restore_tick"],
        "decisions": ctrl.decisions,
        "cooldown_skips": ctrl.cooldown_skips,
        "ledger_rows": ctrl.ledger.total,
        "ledger_dropped": ctrl.ledger.dropped,
        "ledger_replay_identical": (
            replay.ledger.to_jsonl() == ctrl.ledger.to_jsonl()
        ),
        "shed_updates_on": on["srv"].shed_count,
        "shed_updates_off": off["srv"].shed_count,
    }


def autopilot(argv=None) -> int:
    """The ``--autopilot`` harness: run the round-22 control-plane
    A/B leg, merge the gated ``autopilot`` section into
    BENCH_OUT.json (like ``--multitenant``), one summary line on
    stdout. Exits non-zero when the controller failed to squeeze or
    restore, the flooder's burn did not recover within the budget, a
    neighbor diverged from the controller-OFF oracle, or the ledger
    replay was not byte-identical — a control plane that distorts
    documents or loses its audit trail must never publish as
    evidence."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)
    from crdt_tpu.obs import (
        TickTimeline, Tracer, set_timeline, set_tracer,
    )

    tracer = None
    if os.environ.get("BENCH_TRACE", "1") != "0":
        tracer = set_tracer(Tracer(enabled=True))
        set_timeline(TickTimeline(enabled=True))
    leg = autopilot_leg()
    if tracer is not None:
        counters = tracer.counters()
        leg["decisions_counted"] = counters.get(
            "control.decisions", 0)
    ok = bool(leg["neighbors_identical"]) \
        and bool(leg["ledger_replay_identical"]) \
        and bool(leg["squeezed"]) \
        and bool(leg["restored"]) \
        and leg["recovery_ticks"] is not None \
        and leg["recovery_ticks"] <= leg["recovery_budget_ticks"]
    if ok:
        try:
            with open(BENCH_OUT) as f:
                full = json.load(f)
        except (OSError, ValueError):
            full = {}
        full["autopilot"] = leg
        try:
            with open(BENCH_OUT, "w") as f:
                json.dump(full, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError as exc:
            log(f"{BENCH_OUT} not written: {exc}")
    print(json.dumps({
        "metric": "autopilot",
        "ok": ok,
        "recovery_ticks": leg["recovery_ticks"],
        "recovery_ticks_off": leg["recovery_ticks_off"],
        "neighbor_p99_ms": leg["neighbor_p99_ms"],
        "neighbor_p99_ms_off": leg["neighbor_p99_ms_off"],
        "decisions": leg["decisions"],
        "restore_tick": leg["restore_tick"],
        "full_results": os.path.basename(BENCH_OUT),
    }))
    return 0 if ok else 1


def rebalance_leg() -> dict:
    """The ``--rebalance`` evidence (round 24, ROADMAP item 2's
    cross-process arc): the fleet placement loop A/B under seeded
    chaos — three ``FleetNode`` processes on a ``MemFabric``, one
    flooding tenant beside small steady neighbors, identical
    submissions and identical STATIC per-tenant budgets twice:

    - **OFF** (oracle): the same three servers, the same in-process
      controllers (round 22 squeeze/restore) — but NO placement
      loop: the flooder stays where the ring put it, the squeeze is
      the only defense, and the sustain load keeps breaching the
      squeezed budget forever (burn pins high);
    - **ON**: a :class:`crdt_tpu.fleet.PlacementLoop` consumes the
      controllers' federated ``rebalance_away`` advice — mangled by
      a seeded :class:`DuplicateAdviceSchedule` (duplicates +
      stale-seq replays) — and live-migrates the flooder to a clean
      process, where the sustain load fits the static budget and the
      serving burn drains.

    Chaos riding both legs (`net/faults.HandoffFaultSchedule`): every
    migration ``ack`` on the c->a link is dropped (the epoch-fenced
    probe path must complete those handoffs: ``migration.recovery``)
    and every ``commit`` on a->c is duplicated (the dst's idempotent
    re-ack). The ON leg additionally live-migrates an untouched
    identity doc a->c mid-stream, then kills process "a" cold and
    revives it from its snapshot store. A per-tick digest sweep over
    every doc x process counts double-serves (must be zero — each
    refused serve bumps ``fleet.fence_rejects{op=serve}``) and the
    identity doc + steady neighbors must be byte-identical across
    ON/OFF: digest, state vector, state-as-update, and the round-13
    snapshot generation. ``tools/metrics_diff.py`` gates
    ``rebalance.recovery_ticks`` / ``fence_rejects`` / ``forks`` /
    ``double_serves`` / ``migration_recoveries``."""
    import tempfile

    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord
    from crdt_tpu.fleet import FleetNode, PlacementLoop
    from crdt_tpu.fleet.fabric import MemFabric
    from crdt_tpu.net.faults import (
        DuplicateAdviceSchedule,
        HandoffFaultSchedule,
    )
    from crdt_tpu.obs import Tracer, set_tracer
    from crdt_tpu.obs.control import Controller
    from crdt_tpu.obs.slo import SLOLedger
    from crdt_tpu.storage.snapshot import SnapshotStore, encode_engine

    seed = int(os.environ.get("BENCH_RB_SEED", 7))
    flood_ticks = int(os.environ.get("BENCH_RB_FLOOD_TICKS", 8))
    sustain_ticks = int(os.environ.get("BENCH_RB_SUSTAIN_TICKS", 28))
    settle_ticks = 8
    budget_bytes, budget_updates = 2048, 4
    burn_window = 16
    recover_lo = 0.25
    members = ["a", "b", "c"]
    flooder = "doc"        # ring arc: a (pinned by test_placement)
    ident = "y"            # ring arc: a — the migrated identity doc
    steady = ["w", "tenant-0"]   # ring arcs: b, c — never moved
    docs = [flooder, ident] + steady

    def flood_blob(i: int) -> bytes:
        # independent single-record update; the UPDATE-COUNT cap is
        # the working constraint: 4 of them fit the static byte
        # budget (so a handoff tail never squeezes the destination)
        # while the squeezed cap of 1 update/tick sheds one of every
        # sustain pair forever — the self-sustaining breach the
        # placement loop exists to break
        return v1.encode_update([ItemRecord(
            client=10_000 + i, clock=0, parent_root="m",
            key=f"f{i}", content="f" * 400,
        )], DeleteSet())

    blob_len = len(flood_blob(0))
    assert budget_updates * blob_len <= budget_bytes, \
        "rebalance: a full update-cap tick must fit the byte budget"
    assert 2 * blob_len > budget_bytes // 4, \
        "rebalance: a sustain pair must breach the squeezed budget"

    def run(on: bool) -> dict:
        tracer = set_tracer(Tracer(enabled=True))
        tmp = tempfile.TemporaryDirectory()
        chaos = HandoffFaultSchedule(seed, windows=[
            # every handoff ack on c->a dies: the src must fence-
            # probe the dst and complete from its reply
            {"src": "c", "dst": "a", "kinds": ("ack",),
             "mode": "drop"},
            # every commit on a->c arrives twice: the dst re-acks
            # idempotently (and the re-ack dies too)
            {"src": "a", "dst": "c", "kinds": ("commit",),
             "mode": "dup"},
        ])
        fab = MemFabric(faults=chaos)
        dead: set = set()
        nodes: dict = {}
        ctrls: dict = {}
        stores: dict = {}

        def make_hint(p):
            # fleet-layer wiring: never advise moving a tenant onto
            # a process that is squeezing it (its budget override
            # would keep breaching) or a dead one
            def hint(t):
                excl = [p] + [q for q in members
                              if q in dead
                              or str(t) in {str(k) for k in
                                            ctrls[q].overrides()}]
                loads = {q: nodes[q].load() for q in members
                         if q not in dead}
                return nodes[p].ring.least_loaded_successor(
                    str(t), exclude=excl, loads=loads)
            return hint

        def build_node(p):
            ctrl = Controller(cooldown_ticks=4, restore_after=2)
            node = FleetNode(
                p, members, fab, store=stores[p],
                timeout_ticks=3, beacon_every=4,
                server_kw=dict(
                    tenant_max_pending_bytes=budget_bytes,
                    tenant_max_pending_updates=budget_updates,
                    slo_ms=1e9,   # sheds drive burn, never clocks
                    control=ctrl,
                ))
            # fast-flushing burn window, the autopilot idiom
            node.server.slo = SLOLedger(1e9, burn_window=burn_window)
            ctrl.placement_hint = make_hint(p)
            nodes[p], ctrls[p] = node, ctrl
            return node

        for p in members:
            stores[p] = SnapshotStore(os.path.join(tmp.name, p))
            build_node(p)
        ring = nodes["a"].ring
        adv_chaos = DuplicateAdviceSchedule(
            seed, duplicate=0.5, replay=0.5)
        # placement hysteresis ~ a burn window: a flood spike
        # shorter than that is the squeeze's job — moving a tenant
        # mid-spike just ships the spike to the destination (the
        # tail rides the commit) and cascades squeezes around the
        # ring. Only SUSTAINED pressure pays for a migration.
        hysteresis = int(os.environ.get("BENCH_RB_HYSTERESIS", 10))
        loop = PlacementLoop(
            ring, lambda p: None if p in dead else nodes.get(p),
            hysteresis=hysteresis, budget_per_tick=1) if on else None

        streams = {d: _SteadyStream(1 + i)
                   for i, d in enumerate([ident] + steady)}
        lost = {d: 0 for d in docs}

        def submit(doc, blob):
            # redirect-chasing client: offer to each live process in
            # order; exactly one accepts (or the update is lost for
            # one tick — tolerated ONLY for the flooder, whose sheds
            # already differ by design)
            for p in members:
                if p in dead:
                    continue
                r, _info = nodes[p].submit(doc, blob)
                if r in ("ok", "buffered"):
                    return r
            lost[doc] += 1
            return "lost"

        def flood_owner():
            for p in members:
                if p not in dead and nodes[p].lease.holds(flooder) \
                        and not nodes[p].migrator.migrating(flooder):
                    return p
            return None

        total = flood_ticks + sustain_ticks
        t_ident = flood_ticks + 2
        t_kill = total - 6
        t_revive = t_kill + 2
        nblob = 0
        recovery = None
        burn_flood_end = None
        burn_last = None
        double_serves = 0
        tail_restores = 0
        for t in range(total + settle_ticks):
            settling = t >= total
            if not settling:
                if t < flood_ticks:
                    for _ in range(10):
                        submit(flooder, flood_blob(nblob))
                        nblob += 1
                else:
                    # sustain: a pair per tick — fits the static
                    # update budget, breaches the squeezed one
                    # (1 update/tick) every single tick: the load
                    # that makes "squeeze forever" the wrong answer
                    # and "move it" right
                    for _ in range(2):
                        submit(flooder, flood_blob(nblob))
                        nblob += 1
                for d in [ident] + steady:
                    assert submit(d, streams[d].delta(4)) != "lost", \
                        f"rebalance: steady update lost for {d}"
            if on:
                if t == t_ident:
                    assert nodes["a"].migrate(ident, "c"), \
                        "rebalance: identity migration refused"
                if t == t_kill - 1:
                    nodes["a"].checkpoint()
                if t == t_kill:
                    fab.kill("a")
                    dead.add("a")
                if t == t_revive:
                    dead.discard("a")
                    node = build_node("a")
                    fab.revive("a", node)
                    before = tracer.counters().get(
                        "migration.tail_restores", 0)
                    node.restore()
                    tail_restores += tracer.counters().get(
                        "migration.tail_restores", 0) - before
            for p in members:
                if p not in dead:
                    nodes[p].tick()
            if on:
                rows = [dict(r, proc=p)
                        for p in members if p not in dead
                        for r in ctrls[p].advice()]
                # PlacementLoop.observe takes advice rows, not a
                # metric name  # crdtlint: disable=CL203
                loop.observe(t, adv_chaos.mangle(t, rows))
            # the fork guard sweep: every refused serve counts
            # fleet.fence_rejects{op=serve}; >1 server is a fork
            for d in docs:
                n_serving = sum(
                    1 for p in members
                    if p not in dead
                    and nodes[p].digest(d) is not None)
                if n_serving > 1:
                    double_serves += 1
            owner = flood_owner()
            burn = None
            if owner is not None:
                burn = nodes[owner].server.slo.report()[
                    "tenants"].get(flooder, {}).get("burn_rate")
            if burn is not None:
                burn_last = burn
            if t == flood_ticks - 1:
                burn_flood_end = burn
            if (recovery is None and t >= flood_ticks
                    and burn is not None and burn <= recover_lo):
                recovery = t - flood_ticks + 1
        # re-warm the identity doc so the engine snapshot comparison
        # sees a resident matrix on both legs
        assert submit(ident, streams[ident].delta(2)) == "ok"
        for p in members:
            if p not in dead:
                nodes[p].tick()
        serving_ident = [p for p in members if p not in dead
                         and nodes[p].digest(ident) is not None]
        assert len(serving_ident) == 1, \
            f"rebalance: identity doc served by {serving_ident}"
        counters = dict(tracer.counters())
        out = {
            "nodes": nodes, "loop": loop, "fab": fab,
            "adv_chaos": adv_chaos, "counters": counters,
            "recovery": recovery, "burn_flood_end": burn_flood_end,
            "burn_last": burn_last, "double_serves": double_serves,
            "lost": lost, "tail_restores": tail_restores,
            "ident_proc": serving_ident[0], "tmp": tmp,
        }
        set_tracer(Tracer(enabled=False))
        return out

    run(True)   # warm (compile paths, page caches) — untimed
    on = run(True)
    off = run(False)

    def ident_state(leg):
        srv = leg["nodes"][leg["ident_proc"]].server
        eng = srv._docs[ident].resident
        assert eng is not None, "rebalance: identity doc went cold"
        return {
            "digest": srv.digest(ident),
            "sv": eng.state_vector(),
            "update": eng.encode_state_as_update(),
            "snapshot": encode_engine(eng, seq=0),
        }

    s_on, s_off = ident_state(on), ident_state(off)
    identical = {
        "ident_digest": s_on["digest"] == s_off["digest"],
        "ident_sv": s_on["sv"] == s_off["sv"],
        "ident_update": s_on["update"] == s_off["update"],
        "ident_snapshot": s_on["snapshot"] == s_off["snapshot"],
    }
    for d in steady:
        a = [on["nodes"][p].digest(d) for p in members
             if on["nodes"][p].digest(d) is not None]
        b = [off["nodes"][p].digest(d) for p in members
             if off["nodes"][p].digest(d) is not None]
        identical[f"steady_{d}"] = bool(a) and a == b
    on["tmp"].cleanup()
    off["tmp"].cleanup()

    c_on = on["counters"]

    def csum(name: str) -> int:
        # labeled counters live under name{label=...} keys only
        return sum(v for k, v in c_on.items()
                   if k == name or k.startswith(name + "{"))

    hops = [r for r in on["loop"].ledger.rows()
            if r.get("action") == "migrate"]
    return {
        "seed": seed,
        "flood_ticks": flood_ticks,
        "sustain_ticks": sustain_ticks,
        "recovery_ticks": on["recovery"],
        "recovery_ticks_off": off["recovery"],
        "recovery_budget_ticks": int(os.environ.get(
            "BENCH_RB_RECOVERY_BUDGET", 20)),
        "burn_flood_end": on["burn_flood_end"],
        "burn_end_off": off["burn_last"],
        "migrations": on["loop"].migrations,
        "hops": [{"src": r["src"], "dst": r["dst"],
                  "tick": r["tick"]} for r in hops],
        "migrations_completed": csum("migration.completed"),
        "migration_recoveries": csum("migration.recovery"),
        "recoveries_by_step": {
            k.split('step="', 1)[1].rstrip('"}'): v
            for k, v in c_on.items()
            if k.startswith('migration.recovery{')},
        "fence_rejects": csum("fleet.fence_rejects"),
        "fork_refused": csum("fleet.fork_refused"),
        "forks": on["double_serves"] + off["double_serves"],
        "double_serves": on["double_serves"],
        "tail_blobs": csum("migration.tail_blobs"),
        "tail_restores": on["tail_restores"],
        "advice_dups": on["loop"].dup_drops,
        "advice_injected": on["adv_chaos"].injected,
        "ledger_rows": on["loop"].ledger.total,
        "frames_sent": on["fab"].sent,
        "frames_dropped": on["fab"].dropped,
        "frames_duplicated": on["fab"].duplicated,
        "lost_flood_updates": on["lost"][flooder],
        "identical": identical,
        "all_identical": all(identical.values()),
    }


def rebalance_child(argv) -> int:
    """One subprocess fleet server of the ``--rebalance --smoke``
    leg: a real ``FleetNode`` whose fabric is the round-7 sealed
    ``UdpEndpoint`` (X25519 static identities derived from
    deterministic seeds — every child computes every peer's public
    key offline, no key exchange). Child "a" seeds the doc and
    live-migrates it to "c" mid-run; the parent asserts exactly one
    process serves afterwards and the losers' fences counted."""
    cfg = json.loads(argv[0])
    idx = int(cfg["idx"])
    names = list(cfg["names"])
    me = names[idx]
    ports = cfg["ports"]
    outdir = cfg["outdir"]
    ticks = int(cfg["ticks"])

    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord
    from crdt_tpu.fleet import FleetNode, UdpFabric
    from crdt_tpu.net.transport import SecureBox, UdpEndpoint, keypair
    from crdt_tpu.obs import Tracer, set_tracer
    from crdt_tpu.storage.snapshot import SnapshotStore

    tracer = set_tracer(Tracer(enabled=True))
    keys = {n: keypair(bytes([j + 1]) * 32)
            for j, n in enumerate(names)}
    _pub, sec = keys[me]
    peers = {n: ("127.0.0.1", int(ports[j]),
                 SecureBox(sec, keys[n][0]))
             for j, n in enumerate(names) if n != me}
    ep = UdpEndpoint("127.0.0.1", int(ports[idx]))
    fab = UdpFabric(me, ep, peers)
    store = SnapshotStore(os.path.join(outdir, me))
    node = FleetNode(me, names, fab, store=store,
                     timeout_ticks=25, beacon_every=8,
                     server_kw={"slo_ms": 1e9})

    # start barrier: frames to an unbound port are lost, so nobody
    # ticks until every endpoint is up
    with open(os.path.join(outdir, f"ready_{idx}.json"), "w") as f:
        json.dump({"port": ep.port}, f)
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        if all(os.path.exists(os.path.join(outdir, f"ready_{j}.json"))
               for j in range(len(names))):
            break
        time.sleep(0.01)

    def chain_blob(k0, n_ops=4):
        recs = []
        for j in range(n_ops):
            k = k0 + j
            recs.append(ItemRecord(
                client=7, clock=k, parent_root="l",
                origin=(7, k - 1) if k else None,
                content=7000 + k,
            ))
        return v1.encode_update(recs, DeleteSet())

    doc = "doc"           # ring arc: a
    d0 = None
    migrate_ok = None
    # children tick on their own wall clocks (child "a" pays the
    # first-submit compile inside its loop), so the run ends on a
    # BARRIER, not a tick count: once "a" marks the handoff
    # complete, everyone runs a grace window (covers a beacon
    # cadence — "b" must adopt the new owner) and only then
    # snapshots its done file
    handoff_path = os.path.join(outdir, "handoff.json")
    t = 0
    grace = None
    while True:
        if me == "a":
            if t < 4:
                r, _ = node.submit(doc, chain_blob(4 * t))
                assert r == "ok", f"seed submit: {r}"
            if t == 4:
                d0 = node.server.digest(doc)
            if t == 10:
                migrate_ok = node.migrate(doc, "c")
            if migrate_ok and node.migrator.completed >= 1 \
                    and not os.path.exists(handoff_path):
                with open(handoff_path + ".tmp", "w") as f:
                    json.dump({"tick": t}, f)
                os.replace(handoff_path + ".tmp", handoff_path)
        ep.poll()
        node.tick()
        time.sleep(0.02)
        t += 1
        if grace is None:
            if t >= ticks and os.path.exists(handoff_path):
                grace = 24
            elif t > 40 * ticks:   # runaway guard: fail loudly
                break
        else:
            grace -= 1
            if grace <= 0:
                break

    served = node.digest(doc)   # fence-refused (+counted) on losers
    counters = tracer.counters()

    def csum(name):
        return sum(v for k, v in counters.items()
                   if k == name or k.startswith(name + "{"))
    done = {
        "proc": me,
        "digest": served,
        "d0": d0,
        "lease": list(node.lease.lease(doc)),
        "migrate_ok": migrate_ok,
        "completed": node.migrator.completed,
        "fence_rejects": csum("fleet.fence_rejects"),
        "fork_refused": csum("fleet.fork_refused"),
        "udp_failed": ep.failed,
    }
    tmp_path = os.path.join(outdir, f"done_{idx}.json.tmp")
    with open(tmp_path, "w") as f:
        json.dump(done, f)
    os.replace(tmp_path, os.path.join(outdir, f"done_{idx}.json"))
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        if os.path.exists(os.path.join(outdir, "stop")):
            break
        ep.poll()
        node.drain_inbox()
        time.sleep(0.01)
    ep.close()
    return 0


def rebalance_smoke() -> int:
    """``bench.py --rebalance --smoke``: the subprocess half of the
    round-24 evidence — three fleet servers in separate OS processes
    over sealed loopback UDP, one crash-safe live migration between
    them, fencing asserted from the done files. CPU-only, stdlib +
    the package's net/fleet layers; the CI leg."""
    import subprocess
    import tempfile

    t_start = time.perf_counter()
    names = ["a", "b", "c"]
    ticks = int(os.environ.get("BENCH_RB_SMOKE_TICKS", 30))
    repo = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as outdir:
        ports = _free_ports(len(names), udp=True)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        procs = []
        for idx in range(len(names)):
            cfg = {"idx": idx, "names": names, "ports": ports,
                   "outdir": outdir, "ticks": ticks}
            procs.append(subprocess.Popen(
                [sys.executable, os.path.join(repo, "bench.py"),
                 "--rebalance-child", json.dumps(cfg)],
                env=env, cwd=repo,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            ))
        try:
            done_paths = [os.path.join(outdir, f"done_{i}.json")
                          for i in range(len(names))]
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                if all(os.path.exists(p) for p in done_paths):
                    break
                dead = [p for p in procs if p.poll() not in (None, 0)]
                if dead:
                    break
                time.sleep(0.05)
            missing = [p for p in done_paths
                       if not os.path.exists(p)]
            if missing:
                for p in procs:
                    p.kill()
                tails = [p.communicate()[1][-800:] for p in procs]
                raise RuntimeError(
                    f"rebalance children incomplete: {missing} "
                    f"stderr={tails}"
                )
            dones = {}
            for i, path in enumerate(done_paths):
                with open(path) as f:
                    dones[names[i]] = json.load(f)
        finally:
            with open(os.path.join(outdir, "stop"), "w") as f:
                f.write("done")
            for p in procs:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()

    servers = [n for n in names if dones[n]["digest"] is not None]
    leases = {n: dones[n]["lease"] for n in names}
    ok = (
        dones["a"]["migrate_ok"] is True
        and dones["a"]["completed"] == 1
        and servers == ["c"]
        and dones["c"]["digest"] == dones["a"]["d0"]
        and leases["a"] == [2, "c"]
        and leases["b"] == [2, "c"]   # adopted via ownership beacon
        and leases["c"] == [2, "c"]
        and dones["a"]["fence_rejects"] >= 1
        and sum(dones[n]["fork_refused"] for n in names) == 0
    )
    out = {
        "metric": "rebalance_smoke",
        "ok": ok,
        "servers": servers,
        "leases": leases,
        "completed": dones["a"]["completed"],
        "fence_rejects": {n: dones[n]["fence_rejects"]
                          for n in names},
        "udp_failed": {n: dones[n]["udp_failed"] for n in names},
        "elapsed_s": round(time.perf_counter() - t_start, 2),
    }
    artifact = os.environ.get("BENCH_REBALANCE_ARTIFACT")
    if artifact:
        try:
            with open(artifact, "w") as f:
                json.dump({"rebalance_smoke": out,
                           "dones": dones}, f, indent=1,
                          sort_keys=True)
                f.write("\n")
        except OSError as exc:
            log(f"{artifact} not written: {exc}")
    print(json.dumps(out))
    return 0 if ok else 1


def rebalance(argv=None) -> int:
    """The ``--rebalance`` harness: run the round-24 fleet chaos A/B
    leg, merge the gated ``rebalance`` section into BENCH_OUT.json,
    one summary line on stdout. Non-zero when any fork guard fired
    (a double-serve or a diverged doc), the fences never rejected
    anything (the chaos was not exercised), the flooder's serving
    burn failed to recover within budget under the placement loop,
    or the migration-free oracle recovered WITHOUT it — evidence
    that moves documents must prove it moved only the bytes it
    claimed. ``--smoke`` runs the subprocess UDP leg instead."""
    if "--smoke" in (argv or []) or "--smoke" in sys.argv[1:]:
        return rebalance_smoke()
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    leg = rebalance_leg()
    ok = bool(leg["all_identical"]) \
        and leg["double_serves"] == 0 \
        and leg["forks"] == 0 \
        and leg["fence_rejects"] > 0 \
        and leg["migrations"] >= 1 \
        and leg["migrations_completed"] >= 2 \
        and leg["migration_recoveries"] >= 1 \
        and leg["advice_dups"] > 0 \
        and leg["recovery_ticks"] is not None \
        and leg["recovery_ticks"] <= leg["recovery_budget_ticks"] \
        and leg["recovery_ticks_off"] is None \
        and (leg["burn_end_off"] or 0.0) > 0.25
    if ok:
        try:
            with open(BENCH_OUT) as f:
                full = json.load(f)
        except (OSError, ValueError):
            full = {}
        full["rebalance"] = leg
        try:
            with open(BENCH_OUT, "w") as f:
                json.dump(full, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError as exc:
            log(f"{BENCH_OUT} not written: {exc}")
    print(json.dumps({
        "metric": "rebalance",
        "ok": ok,
        "recovery_ticks": leg["recovery_ticks"],
        "recovery_ticks_off": leg["recovery_ticks_off"],
        "burn_end_off": leg["burn_end_off"],
        "migrations": leg["migrations"],
        "hops": len(leg["hops"]),
        "fence_rejects": leg["fence_rejects"],
        "migration_recoveries": leg["migration_recoveries"],
        "double_serves": leg["double_serves"],
        "all_identical": leg["all_identical"],
        "full_results": os.path.basename(BENCH_OUT),
    }))
    return 0 if ok else 1


def overload_leg(seed: int = 11) -> dict:
    """Seeded overload evidence (guard layer): flood one replica at 4x
    its inbox byte budget in a single delivery round, record the
    bounded peak + shed counters, then measure post-heal convergence
    through the re-probe path. The robustness analogue of the xfer.*
    legs — ``tools/metrics_diff.py`` gates ``overload.peak_inbox_bytes``
    and the shed counts so the guards can't silently regress."""
    from crdt_tpu.net.replica import Replica
    from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter
    from crdt_tpu.obs import Tracer, get_tracer, set_tracer

    budget = int(os.environ.get("BENCH_OVERLOAD_BUDGET", 4096))
    tracer = get_tracer()
    restore = None
    if not tracer.enabled:
        # the leg's evidence IS counter-based: under BENCH_TRACE=0 a
        # disabled tracer would report shed_count/shed_bytes as 0
        # while shedding really happened, poisoning the metrics_diff
        # gate — force a leg-local tracer instead
        restore = tracer
        tracer = set_tracer(Tracer(enabled=True))
    try:
        return _overload_leg_body(seed, budget, tracer)
    finally:
        if restore is not None:
            set_tracer(restore)


def _overload_leg_body(seed: int, budget: int, tracer) -> dict:
    from crdt_tpu.net.replica import Replica
    from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter

    shed0 = tracer.counters().get("guard.inbox_shed", 0)
    shed_b0 = tracer.counters().get("guard.inbox_shed_bytes", 0)
    net = LoopbackNetwork(seed=seed)
    a = Replica(
        LoopbackRouter(net, "a"), topic="bench-overload", client_id=1,
        batch_incoming=True, inbox_max_bytes=budget,
        # first repair probe deferred past the flood round: a mid-
        # flood repair diff (admitted whole under keep-the-newest)
        # would muddy the bounded-peak evidence
        resync_retry_s=0.5,
    )
    b = Replica(LoopbackRouter(net, "b"), topic="bench-overload",
                client_id=2)
    net.run()
    sizes = []
    orig = b.doc.on_update

    def hook(u, m):
        sizes.append(len(u))
        orig(u, m)

    b.doc.on_update = hook
    i = 0
    while sum(sizes) < 4 * budget:
        b.set("m", f"k{i}", "x" * 64)
        i += 1
    net.run()  # ONE delivery round carrying the whole 4x flood
    peak = a.inbox_peak_bytes
    t0 = time.perf_counter()
    deadline = t0 + 30.0
    while dict(a.c) != dict(b.c) or len(dict(a.c).get("m", {})) != i:
        if time.perf_counter() > deadline:
            raise TimeoutError("overload leg did not re-converge")
        a.tick()
        b.tick()
        net.run()
        time.sleep(0.002)
    heal_s = time.perf_counter() - t0
    counters = tracer.counters()
    return {
        "seed": seed,
        "inbox_budget_bytes": budget,
        "flood_bytes": sum(sizes),
        "flood_updates": len(sizes),
        "peak_inbox_bytes": peak,
        "bounded": peak <= budget,
        "shed_count": counters.get("guard.inbox_shed", 0) - shed0,
        "shed_bytes": counters.get("guard.inbox_shed_bytes", 0) - shed_b0,
        "heal_s": round(heal_s, 4),
        "converged": True,
    }


def kernel_ablation_leg(cols, b2b_ms, null_floor_ms) -> dict:
    """Per-primitive sort-diet ablation at the headline shape: the
    three primitives the round-12 Pallas kernels replaced, each timed
    on BOTH paths with the sweep's b2b methodology, net of the
    null-dispatch floor.

    - ``sort_ms``: document-order assembly. jnp = the two global
      argsorts the old dispatch ran (sibling key + (seg, rank) key at
      the seq bucket); pallas = the ``stream_scatter`` permutation
      kernel that replaced them.
    - ``map_winners_ms``: the LWW winner chain. jnp = the sort +
      run-edge + doubling chain of ``lww.map_winners`` at the padded
      kernel width; pallas = the segmented Lamport argmax scan +
      doubling at map-bucket width over the staged grouped layout.
    - ``rank_ms``: YATA ranking. jnp = the on-device sibling-table
      build (run edges, next/first-child scatters) + Wyllie ranking;
      pallas = the ranking alone over the tables staging now
      precomputes (the build fell out of the dispatch).

    ``pallas`` names the production kernel path: compiled Pallas on
    TPU, the kernels' jnp oracles (same sortless algorithms) on other
    backends — i.e. exactly what :func:`packed.kernel_mode_for`
    dispatches on this rig. ``sort_map_speedup`` is the acceptance
    number: (sort + map) jnp / (sort + map) pallas, net of floor.
    """
    import jax
    import jax.numpy as jnp
    from functools import partial as _partial
    from crdt_tpu.ops import packed as _pk
    from crdt_tpu.ops import pallas_kernels as _plk
    from crdt_tpu.ops.device import (
        _CLOCK_BITS,
        NULLI,
        bucket_grid,
        dfs_ranks,
        run_edge_lookup,
        scatter_perm,
    )
    from crdt_tpu.ops.lww import map_winners

    # ---- host mini-staging: the id-sorted dense columns the OLD
    # (round-11) fused dispatch consumed (mirrors packed._stage's
    # prefix — rig-local: values only shape the timing, the
    # differential suites own exactness)
    client = np.asarray(cols["client"], np.int64)
    clock = np.asarray(cols["clock"], np.int64)
    pir = np.asarray(cols["parent_is_root"], bool)
    pa = np.asarray(cols["parent_a"], np.int64)
    pb = np.asarray(cols["parent_b"], np.int64)
    kid = np.asarray(cols["key_id"], np.int64)
    oc = np.asarray(cols["origin_client"], np.int64)
    ock = np.asarray(cols["origin_clock"], np.int64)
    valid = np.asarray(cols["valid"], bool)
    n = len(client)
    uniq = np.unique(np.concatenate([client[valid],
                                     oc[valid & (oc >= 0)]]))
    cd = np.searchsorted(uniq, np.clip(client, uniq[0], None))
    porder = np.lexsort((pb, pa, pir))
    pir_s, pa_s, pb_s = pir[porder], pa[porder], pb[porder]
    runs = np.r_[True, (pir_s[1:] != pir_s[:-1])
                 | (pa_s[1:] != pa_s[:-1]) | (pb_s[1:] != pb_s[:-1])]
    pref = np.empty(n, np.int64)
    pref[porder] = np.cumsum(runs) - 1
    ikey = np.where(valid, (cd << _CLOCK_BITS) | clock,
                    np.int64(2 ** 62))
    order = np.argsort(ikey, kind="stable")
    ikey_s = ikey[order]
    kid_s = kid[order]
    valid_s = valid[order]
    dup = np.r_[False, ikey_s[1:] == ikey_s[:-1]]
    uv = valid_s & ~dup
    sk = _pk.segkey_of(pref[order], kid_s)
    _, seg_inv = np.unique(sk[uv], return_inverse=True)
    seg = np.full(n, -1, np.int64)
    seg[uv] = seg_inv
    okey = np.where(oc[order] >= 0,
                    (np.searchsorted(uniq, np.clip(oc[order], uniq[0],
                                                   None)) << _CLOCK_BITS)
                    | ock[order], np.int64(-1))
    pos = np.clip(np.searchsorted(ikey_s, okey), 0, n - 1)
    origin_row = np.where((okey >= 0) & (ikey_s[pos] == okey), pos, -1)

    kpad = bucket_grid(n, floor=6)

    def _pad(a, fill):
        return np.concatenate([a, np.full(kpad - n, fill, a.dtype)])

    is_map = uv & (kid_s >= 0)
    seg_map = np.where(is_map, seg, NULLI)
    plan = _pk.stage(cols)
    B, S, M = plan.seq_bucket, plan.num_segments, plan.map_bucket
    mode = _plk.converge_kernel_mode(M, B)
    secs = _pk._decode_sections(
        jnp.asarray(plan.mat), _pk._section_sizes(S, B, M), plan.encs
    )
    sseg, soff, cp, nxt, fc, mkey, cend, rend = [
        jax.device_put(s) for s in secs
    ]

    def net(ms):
        return round(max(ms - null_floor_ms, 0.01), 2)

    out = {"shape": n, "mode": mode,
           "seq_bucket": B, "map_bucket": M}

    # ---- map_winners: old sort chain at kpad vs segmented argmax at M
    fn_old_map = jax.jit(_partial(
        map_winners, num_segments=S, rows_id_ranked=True,
        chain_rounds=plan.map_rounds, client_bits=23,
    ))
    a_seg = jnp.asarray(_pad(seg_map.astype(np.int32), NULLI))
    a_cl = jnp.asarray(_pad(cd[order].astype(np.int32), 0))
    a_ck = jnp.asarray(_pad(clock[order], 0))
    a_or = jnp.asarray(_pad(origin_row.astype(np.int32), NULLI))
    a_im = jnp.asarray(_pad(is_map, False))

    # the new side times packed._map_block ITSELF (one shared
    # definition with the production dispatch, so these gated numbers
    # can never drift onto a stale copy of the algorithm)
    fn_new_map = jax.jit(_partial(
        _pk._map_block, map_rounds=plan.map_rounds, mode=mode,
    ))

    out["map_winners_ms"] = {
        "jnp": net(b2b_ms(
            lambda: fn_old_map(a_seg, a_cl, a_ck, a_or, a_im))),
        "pallas": net(b2b_ms(
            lambda: fn_new_map(mkey, cend, rend))),
    }

    # ---- rank: table build + Wyllie vs Wyllie over prebuilt tables
    parent = jnp.where(sseg >= 0, jnp.where(cp >= 0, cp,
                                            B + jnp.maximum(sseg, 0)),
                       B + S).astype(jnp.int32)
    c_ok = jax.device_put(sseg >= 0)
    rng = np.random.default_rng(12)
    sib_client = jnp.asarray(rng.integers(0, 1 << 14, B)
                             .astype(np.int64))
    pos_desc = jnp.asarray(np.arange(B - 1, -1, -1, dtype=np.int64))
    qbits = int(max(B - 1, 1)).bit_length()

    @jax.jit
    def fn_old_rank(p_s, sord2, parent, c_ok):
        # the table build _rank_compact ran on device every dispatch
        # (sibling run edges + next/first-child scatters), then the
        # shared Wyllie ranking — sorted inputs given, so the sibling
        # argsort itself is charged to the sort leg, not here
        same = jnp.concatenate([p_s[1:] == p_s[:-1],
                                jnp.zeros(1, bool)])
        nxt_sorted = jnp.where(same, jnp.roll(sord2, -1),
                               NULLI).astype(jnp.int32)
        next_sib = scatter_perm(sord2, nxt_sorted)
        first_pos, _ = run_edge_lookup(p_s, B + S, side="left")
        first_child = jnp.where(
            first_pos >= 0, sord2[jnp.clip(first_pos, 0, B - 1)], NULLI
        ).astype(jnp.int32)
        return dfs_ranks(parent, next_sib, first_child, c_ok, S,
                         rank_rounds=plan.rank_rounds)

    @jax.jit
    def fn_new_rank(parent, nxt, fc, c_ok):
        return dfs_ranks(parent, nxt.astype(jnp.int32),
                         fc.astype(jnp.int32), c_ok, S,
                         rank_rounds=plan.rank_rounds)

    sibkey = ((parent.astype(jnp.int64) << (23 + qbits))
              | (sib_client << qbits) | pos_desc)
    sord2 = jnp.argsort(sibkey, stable=True)
    p_s = parent[sord2]
    out["rank_ms"] = {
        "jnp": net(b2b_ms(lambda: fn_old_rank(p_s, sord2, parent,
                                              c_ok))),
        "pallas": net(b2b_ms(lambda: fn_new_rank(parent, nxt, fc,
                                                 c_ok))),
    }

    # ---- sort: the removed global argsorts vs the scatter kernel
    dist = fn_new_rank(parent, nxt, fc, c_ok)
    root_dist = dist[B + jnp.maximum(sseg, 0)]
    c_rank = jnp.where(c_ok, root_dist - dist[:B] - 1, NULLI)
    scat_pos = jnp.where(
        c_ok & (c_rank >= 0),
        soff[jnp.clip(sseg, 0, S - 1)] + c_rank, NULLI
    ).astype(jnp.int32)
    skey2 = jnp.where(c_ok, sseg.astype(jnp.int64) * B
                      + jnp.maximum(c_rank, 0), jnp.int64(2 ** 62))

    @jax.jit
    def fn_old_sort(sibkey, skey2):
        return jnp.argsort(sibkey, stable=True), \
            jnp.argsort(skey2, stable=True)

    @_partial(jax.jit, static_argnames=("kmode",))
    def fn_new_sort(scat_pos, kmode):
        return _plk.stream_scatter(scat_pos, B, mode=kmode)

    out["sort_ms"] = {
        "jnp": net(b2b_ms(lambda: fn_old_sort(sibkey, skey2))),
        "pallas": net(b2b_ms(lambda: fn_new_sort(scat_pos,
                                                 kmode=mode))),
    }

    old_share = out["sort_ms"]["jnp"] + out["map_winners_ms"]["jnp"]
    new_share = out["sort_ms"]["pallas"] \
        + out["map_winners_ms"]["pallas"]
    out["sort_map_speedup"] = round(old_share / max(new_share, 1e-3), 2)
    out["note"] = (
        "per-primitive b2b timings net of the null-dispatch floor; "
        "'pallas' is the production kernel path on this rig "
        f"(mode={mode}: compiled Pallas on TPU, the kernels' sortless "
        "jnp oracles elsewhere), 'jnp' the pre-round-12 sort-based "
        "primitives at their old widths. sort_map_speedup = "
        "(sort+map) jnp / pallas — the ROADMAP item-3 >=2x claim."
    )
    return out


def fleet_trace_child(argv) -> int:
    """One subprocess replica of the ``--fleet-trace`` leg: a real
    UdpRouter peer under the seeded round-7 fault schedule, tracing +
    recording enabled, serving its obs surfaces over HTTP while it
    edits and converges. Children 1 and 2 are PERMANENTLY partitioned
    from each other at the router seam, so their traffic crosses only
    through the rendezvous relay — the forced multi-hop path whose
    full reconstruction the parent asserts."""
    cfg = json.loads(argv[0])
    idx = int(cfg["idx"])
    ports = cfg["ports"]
    outdir = cfg["outdir"]
    K = int(cfg["ops"])
    val_bytes = int(cfg["val_bytes"])

    from crdt_tpu.net.faults import (
        FaultSchedule,
        Partition,
        install_faults,
    )
    from crdt_tpu.net.replica import Replica
    from crdt_tpu.net.udp_router import UdpRouter
    from crdt_tpu.obs import (
        FlightRecorder,
        ObsHTTPServer,
        PropagationLedger,
        TickTimeline,
        Tracer,
        get_propagation,
        get_timeline,
        set_propagation,
        set_recorder,
        set_timeline,
        set_tracer,
        state_digest,
    )

    tracer = set_tracer(Tracer(enabled=True))
    set_recorder(FlightRecorder(enabled=True, capacity=16384))
    set_propagation(PropagationLedger())
    set_timeline(TickTimeline(enabled=True))

    router = UdpRouter(
        port=int(ports[idx]),
        seed=bytes([int(cfg["seed"]) % 200 + 1 + idx]) * 32,
        rendezvous=(idx == 0),
        bootstrap=([] if idx == 0
                   else [("127.0.0.1", int(ports[0]))]),
        relay_after_s=0.25,
        dial_retry_s=0.1,
        dial_retry_max_s=0.5,
        # fast announce refresh: under the fault schedule a dropped
        # (one-shot, relay-routed) announce is repaired on the next
        # ttl/3 cadence instead of the 20s default
        announce_ttl=1.0,
    )
    part = None
    if idx in (1, 2):
        # the relay forcer: children 1<->2 never hear each other
        # directly (a never-healing partition at the router seam);
        # the introduction dial escalates to the rendezvous relay
        part = Partition({int(ports[1])}, {int(ports[2])})
    install_faults(router, FaultSchedule(
        int(cfg["seed"]), drop=float(cfg["drop"]), duplicate=0.02,
        delay=float(cfg["delay"]), delay_polls=(1, 3),
        partition=part,
    ))
    rep = Replica(router, topic="fleet", client_id=101 + idx,
                  anti_entropy_s=0.2, batch_incoming=True)
    # round 22: child 1 carries a live control plane — a seeded
    # synthetic flood drives a budget squeeze whose placement-advice
    # row the parent's collector must surface at /fleet within one
    # scrape (the other children stay control-less: the collector's
    # /control fetch must tolerate the 404)
    ctrl = None
    if idx == 1:
        from crdt_tpu.obs import Controller

        ctrl = Controller(cooldown_ticks=2)
        for ct in range(4):
            ctrl.observe({
                "tick": ct,
                "budget": {"max_bytes": 2048, "max_updates": 4},
                "tenants": {"flood!": {
                    "burn": 1.0, "shed": 8 * (ct + 1),
                    "pending_bytes": 4096,
                }},
            })
        assert ctrl.advice(), "fleet-trace child: no advice"
    obs = ObsHTTPServer(port=int(cfg["obs_ports"][idx]),
                        snapshot_extra=lambda: {
                            "propagation": get_propagation().report(),
                        },
                        control=ctrl).start()

    def pump_for(seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            router.poll()
            time.sleep(0.002)

    # phase 1: join — both other peers visible on the topic (child
    # 1/2 reach each other only via the relay) and synced. The
    # bootstrap hello is an app-level one-shot the fault schedule can
    # eat, so it is re-dialed on a coarse cadence until the router
    # hears ANYONE (the reference re-dials its bootstrap DHT too).
    deadline = time.monotonic() + 30.0
    next_redial = time.monotonic() + 1.0
    while time.monotonic() < deadline:
        router.poll()
        if len(router.peers_on("fleet")) >= 2 and rep.synced:
            break
        now = time.monotonic()
        if idx != 0 and not router.peers and now >= next_redial:
            router.add_peer("127.0.0.1", int(ports[0]))
            next_redial = now + 1.0
        time.sleep(0.002)
    else:
        print(json.dumps({"child": idx, "error": "join timeout",
                          "peers": router.peers_on("fleet")}),
              file=sys.stderr)
        return 3

    # phase 2: seeded edits, one tick-timeline record per op window
    # (the merged-Perfetto evidence: per-process op phases)
    rng = np.random.default_rng(int(cfg["seed"]) * 31 + idx)
    tl = get_timeline()
    for j in range(K):
        tl.tick_begin(j, label=f"ops[{idx}]")
        with tl.phase("edit"):
            payload = "".join(
                chr(97 + int(c)) for c in rng.integers(0, 26,
                                                       val_bytes)
            )
            rep.set("m", f"{idx}:{j}", payload)
        with tl.phase("pump"):
            pump_for(0.02)
        tl.tick_end()

    # phase 3: converge — every client's K ops visible everywhere
    # (drops + the partition are repaired by probe retries, the AE
    # cadence, and the relay path; bounded by the deadline)
    cids = [101, 102, 103]
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        router.poll()
        sv = rep.doc.state_vector()
        if all(sv.get(c) >= K for c in cids):
            break
        time.sleep(0.002)
    else:
        print(json.dumps({
            "child": idx, "error": "converge timeout",
            "sv": {c: rep.doc.state_vector().get(c) for c in cids},
        }), file=sys.stderr)
        return 4
    # settle: stop ORIGINATING repair frames (the AE cadence would
    # mint new traced frames forever, and the parent's scrape of this
    # process could then race a frame still in flight toward a peer
    # it scrapes later), then drain what is in flight — the fault
    # schedule's held delays release within a few polls
    rep._next_ae_at = None
    rep._resync_at = None
    pump_for(0.5)
    rep.flush_incoming()

    from crdt_tpu.obs.recorder import get_recorder

    get_recorder().dump_jsonl(
        os.path.join(outdir, f"dump_{idx}.jsonl")
    )
    led = get_propagation().report()
    done = {
        "idx": idx,
        "digest": state_digest(rep.doc),
        "ledger": led,
        "relay": {k: v for k, v in router.stats.items()
                  if k.startswith("relay")},
        "counters": tracer.report()["counters"],
    }
    done_path = os.path.join(outdir, f"done_{idx}.json")
    with open(done_path + ".tmp", "w") as f:
        json.dump(done, f)
    os.replace(done_path + ".tmp", done_path)

    # phase 4: stay scrapeable until the parent finishes its live
    # collector pass (stop file), then exit clean
    stop = os.path.join(outdir, "stop")
    deadline = time.monotonic() + 60.0
    while not os.path.exists(stop) and time.monotonic() < deadline:
        router.poll()
        time.sleep(0.01)
    obs.stop()
    router.close()
    return 0


def _free_ports(n: int, *, udp: bool) -> list:
    """Pre-allocate n distinct free ports (bind-then-release; the
    children re-bind them — the tiny race is acceptable for a bench
    leg on loopback)."""
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket(
            socket.AF_INET,
            socket.SOCK_DGRAM if udp else socket.SOCK_STREAM,
        )
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def fleet_trace(argv=None) -> int:
    """``bench.py --fleet-trace``: the seeded multi-process tracing
    leg. Three subprocess replicas gossip over real UDP routers under
    a round-7 fault schedule (drops, dups, delays) with children 1/2
    force-relayed through the rendezvous; a live FleetCollector
    scrapes their ObsHTTPServers mid-run and the acceptance numbers
    are asserted, not eyeballed:

    - every traced receive's FULL path reconstructs across processes
      (``pair_rate == 1.0``), with direct, relayed, sync_answer and
      anti_entropy legs all present;
    - all three documents converge to one digest despite the faults;
    - the trace-context wire overhead stays < 5% of traced update
      bytes (the gated ratio);
    - the collector-merged Perfetto timeline carries all three
      processes under distinct pids.

    One JSON line out; BENCH_FLEET_OUT= writes the full artifact
    (the CI-uploaded evidence). Stdlib + the package's net/obs layers
    only — the leg never touches a device."""
    import subprocess
    import tempfile

    from crdt_tpu.obs import FleetCollector, Tracer, set_tracer

    t_start = time.perf_counter()
    seed = int(os.environ.get("BENCH_FLEET_SEED", 7))
    ops = int(os.environ.get("BENCH_FLEET_OPS", 10))
    val_bytes = int(os.environ.get("BENCH_FLEET_VAL_BYTES", 1024))
    drop = float(os.environ.get("BENCH_FLEET_DROP", 0.04))
    delay = float(os.environ.get("BENCH_FLEET_DELAY", 0.08))
    n_procs = 3

    tracer = set_tracer(Tracer(enabled=True))
    repo = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as outdir:
        ports = _free_ports(n_procs, udp=True)
        obs_ports = _free_ports(n_procs, udp=False)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        procs = []
        for idx in range(n_procs):
            cfg = {
                "idx": idx, "seed": seed, "ports": ports,
                "obs_ports": obs_ports, "outdir": outdir,
                "ops": ops, "val_bytes": val_bytes,
                "drop": drop, "delay": delay,
            }
            procs.append(subprocess.Popen(
                [sys.executable, os.path.join(repo, "bench.py"),
                 "--fleet-trace-child", json.dumps(cfg)],
                env=env, cwd=repo,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            ))
        try:
            done_paths = [os.path.join(outdir, f"done_{i}.json")
                          for i in range(n_procs)]
            deadline = time.monotonic() + 150.0
            while time.monotonic() < deadline:
                if all(os.path.exists(p) for p in done_paths):
                    break
                dead = [p for p in procs if p.poll() not in (None, 0)]
                if dead:
                    break
                time.sleep(0.05)
            missing = [p for p in done_paths
                       if not os.path.exists(p)]
            if missing:
                for p in procs:
                    p.kill()
                tails = [p.communicate()[1][-800:] for p in procs]
                raise RuntimeError(
                    f"fleet-trace children incomplete: {missing} "
                    f"stderr={tails}"
                )

            # the LIVE half: children are still polling + serving;
            # scrape them mid-run through the collector
            col = FleetCollector(events_limit=16384)
            for idx in range(n_procs):
                col.add_proc(
                    f"p{idx}", f"http://127.0.0.1:{obs_ports[idx]}"
                )
            ok = col.scrape()
            assert all(ok.values()), f"live scrape failed: {ok}"
            report = col.fleet_report()
            merged = col.merged_perfetto()
        finally:
            with open(os.path.join(outdir, "stop"), "w") as f:
                f.write("done")
            for p in procs:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()

        dones = []
        for p in done_paths:
            with open(p) as f:
                dones.append(json.load(f))

    # -- acceptance ----------------------------------------------------
    digests = {d["digest"] for d in dones}
    assert len(digests) == 1, \
        f"fleet-trace: documents diverged under faults: {digests}"
    paths = report["paths"]
    assert paths["traced_recvs"] > 0, "fleet-trace: nothing traced"
    assert paths["pair_rate"] == 1.0, (
        f"fleet-trace: only {paths['complete']}/"
        f"{paths['traced_recvs']} paths reconstructed "
        f"(sample: {paths['incomplete_sample']})"
    )
    routes = set(paths["routes"])
    assert {"direct", "relayed", "sync_answer"} <= routes, \
        f"fleet-trace: route coverage incomplete: {routes}"
    assert sorted(paths["origin_procs"]) == ["p0", "p1", "p2"], \
        f"fleet-trace: origin procs {paths['origin_procs']}"
    ctx_bytes = sum(d["ledger"]["context_bytes"] for d in dones)
    upd_bytes = sum(d["ledger"]["traced_update_bytes"]
                    for d in dones)
    overhead = ctx_bytes / upd_bytes if upd_bytes else 0.0
    assert overhead < 0.05, \
        f"fleet-trace: context overhead {overhead:.3f} >= 5%"
    relay_forwards = sum(
        d["relay"].get("relay_frames_forwarded", 0) for d in dones
    )
    assert relay_forwards > 0, "fleet-trace: no frames were relayed"
    pids = {e.get("pid") for e in merged["traceEvents"]
            if isinstance(e, dict)}
    assert len(pids) >= n_procs, \
        f"fleet-trace: merged timeline pids collided: {pids}"
    # round 22: the flooded child's control plane federates — its
    # squeeze must surface as a proc-tagged advice row (and its
    # ledger tail under report["control"]) within the ONE live
    # scrape above
    advice = report.get("advice") or []
    assert any(a.get("proc") == "p1"
               and a.get("action") == "rebalance_away"
               for a in advice), \
        f"fleet-trace: control advice not federated: {advice}"
    assert report.get("control", {}).get("p1", {}).get("rows"), \
        "fleet-trace: control ledger tail missing from /fleet"

    out = {
        "metric": "fleet_trace",
        "fleet_trace": {
            "procs": len(report["procs"]),
            "pair_rate": paths["pair_rate"],
            "traced_recvs": paths["traced_recvs"],
            "wire_overhead_ratio": overhead,
            "routes": paths["routes"],
            "hops": report["latency"]["hops"],
            "relay_frames_forwarded": relay_forwards,
            "control_advice_rows": len(advice),
            "converged": True,
            "wall_s": round(time.perf_counter() - t_start, 2),
        },
        "tracer": tracer.report(),
        "ok": True,
    }
    fleet_out = os.environ.get("BENCH_FLEET_OUT")
    if fleet_out:
        with open(fleet_out, "w") as f:
            json.dump({
                **out,
                "latency": report["latency"],
                "fleet_metrics_sums": report["metrics"]["sums"],
                "perfetto_pids": sorted(
                    p for p in pids if isinstance(p, int)
                ),
            }, f, indent=1, sort_keys=True, default=str)
            f.write("\n")
    line = dict(out)
    line.pop("tracer", None)
    print(json.dumps(line, sort_keys=True, default=str))
    return 0


def smoke():
    """Fast pipeline-accounting smoke: a tiny trace through all three
    contenders (numpy, one-shot device pipeline, streaming executor)
    on the CPU backend, equality-asserted, one JSON line out. Run by
    a tier-1 test so a phase silently re-serializing (or the streamed
    path diverging) is caught without a full scale run. Target <30s.
    """
    # CPU-pinned BEFORE any backend init: drop the axon pool var so
    # the sitecustomize hook never dials the tunnel (a dead tunnel
    # hangs backend init even under JAX_PLATFORMS=cpu — the same
    # hazard _ensure_live_backend guards the full bench against)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    # the round-13 shard-registry leg needs >=2 devices: force a
    # 2-way virtual CPU mesh unless the env already forces a count
    # (backend init reads the flag once, so this must precede any
    # device use)
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=2"
        ).strip()
    import jax

    # env alone is too late when jax was already imported via the
    # package: pin the backend through the config knob as well
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # older jaxlib spelling; the env pin above covers it
    jax.config.update("jax_enable_x64", True)
    from crdt_tpu.models import stream_replay

    # tracing ON by default in smoke: a tier-1 test asserts the
    # hot-path spans exist (instrumentation cannot silently rot).
    # BENCH_TRACE=0 measures the off-path cost instead.
    from crdt_tpu.obs import TickTimeline, Tracer, set_timeline, set_tracer

    tracer = None
    if os.environ.get("BENCH_TRACE", "1") != "0":
        tracer = set_tracer(Tracer(enabled=True))
        # the round-18 tick timeline rides the same switch: the
        # multitenant legs below must light the timeline registry
        set_timeline(TickTimeline(enabled=True))

    R = int(os.environ.get("BENCH_SMOKE_REPLICAS", 48))
    K = int(os.environ.get("BENCH_SMOKE_OPS", 40))
    blobs = build_trace(R, K)

    p_n: dict = {}
    t0 = time.perf_counter()
    cache_np, snap_np = run_numpy(blobs, p_n)
    t_np = time.perf_counter() - t0

    p_d: dict = {}
    xfer_before = _xfer_counters()
    t0 = time.perf_counter()
    cache_dev, snap_dev, *_ = run_device(blobs, p_d)
    t_dev = time.perf_counter() - t0
    xfer_dev = _xfer_diff(xfer_before, _xfer_counters())

    # force the full pipeline shape on the tiny trace: several decode
    # chunks, a real multi-shard converge/materialize pipeline
    p_s: dict = {}
    t0 = time.perf_counter()
    res = stream_replay(
        blobs, chunk_blobs=max(1, R // 6), max_shards=3,
        min_shard_rows=1, phases=p_s,
    )
    t_stream = time.perf_counter() - t0

    assert cache_dev == cache_np, "smoke: device vs numpy diverge"
    assert snap_dev == snap_np, "smoke: snapshots diverge"
    assert res.cache == cache_dev, "smoke: streamed cache diverges"
    assert res.snapshot == snap_dev, "smoke: streamed snapshot diverges"
    # accounting sanity: the overlap fields must exist and the busy
    # sum must cover every pipeline lane (decode + converge + the
    # incremental materialize all ran)
    for key in ("decode", "converge", "materialize",
                "busy_sum_s", "wall_s", "overlap_efficiency",
                "wall_vs_phases"):
        assert key in p_s, f"smoke: missing phase {key}"
    assert p_s["busy_sum_s"] > 0
    out = {
        "metric": "smoke_trace_replay",
        "ops": R * K,
        "platform": jax.devices()[0].platform,
        "numpy_s": round(t_np, 3),
        "device_s": round(t_dev, 3),
        "stream_s": round(t_stream, 3),
        "stream_phases_s": p_s,
        "phases_device_s": p_d,
        "phases_numpy_s": p_n,
        "xfer": xfer_dev,
        "ok": True,
    }
    report = None
    if tracer is not None:
        # the persistence leg (WAL append/compact spans), then the
        # hot-path span contract: these names are the documented
        # registry (README "Observability") and tier-1 pins them
        import tempfile

        from crdt_tpu.storage.persistence import LogPersistence

        with tempfile.TemporaryDirectory() as td:
            lp = LogPersistence(os.path.join(td, "smoke.kvlog"))
            for blob in blobs[:8]:
                lp.store_update("smoke", blob)
            lp.compact("smoke", snap_dev)
            lp.close()
        # guard-layer registry leg: fire each degradation ladder once
        # so the robustness counters the regression gate reads can't
        # rot (README "Overload & failure policy" registry)
        from crdt_tpu.core.engine import Engine
        from crdt_tpu.core.records import ItemRecord
        from crdt_tpu.guard.device import dispatch_guarded
        from crdt_tpu.guard.faults import (
            DeviceFaultPlan,
            DiskFaultSchedule,
            FaultyKv,
        )
        from crdt_tpu.net.replica import Replica
        from crdt_tpu.net.router import LoopbackNetwork, LoopbackRouter

        with DeviceFaultPlan(fail_attempts=2):  # retry -> host route
            dispatch_guarded("smoke.guard", lambda: 0, host=lambda: 0)
        eng = Engine(1)
        eng.pending_limit = 2  # cap -> evictions
        eng.apply_records([
            ItemRecord(client=9, clock=k, parent_root="s",
                       origin=(9, k - 1), content=k)
            for k in range(1, 7)
        ])
        with tempfile.TemporaryDirectory() as td:
            lp = LogPersistence(  # retry -> degrade -> write-back
                os.path.join(td, "guard.kvlog"),
                kv_wrapper=lambda kv: FaultyKv(
                    kv, DiskFaultSchedule(fail_writes={0, 1, 2})
                ),
                retries=2, retry_backoff_s=0.001,
            )
            lp.store_update("g", blobs[0])   # degrades
            lp.store_update("g", blobs[1])   # drains + syncs
            assert lp.get_all_updates("g") == blobs[:2]
            lp.close()
        net = LoopbackNetwork()
        ra = Replica(LoopbackRouter(net, "a"), topic="g", client_id=1,
                     batch_incoming=True, inbox_max_bytes=128)
        rb = Replica(LoopbackRouter(net, "b"), topic="g", client_id=2)
        net.run()
        for i in range(4):  # one round >> budget -> sheds
            rb.set("m", f"k{i}", "x" * 48)
        net.run()
        report = tracer.report()
        for cname in ("guard.inbox_shed", "guard.inbox_shed_bytes",
                      "engine.pending_evictions", "persist.retries",
                      "persist.degraded_writes",
                      "persist.recovered_updates",
                      "device.retries", "device.fallback"):
            assert report["counters"].get(cname, 0) > 0, \
                f"smoke: {cname} missing from guard registry"
        assert report["gauges"].get("persist.degraded") == 0, \
            "smoke: degraded gauge did not clear after write-back"
        out["guard_registry_ok"] = True
        for name in ("decode", "pack", "converge.dispatch",
                     "converge.fetch", "materialize", "gather",
                     "compact", "persist", "persist.compact"):
            sp = report["spans"].get(name)
            assert sp and sp["count"] > 0, \
                f"smoke: hot-path span {name!r} missing from tracer"
            assert "p50_s" in sp and "p99_s" in sp, name
        # the byte-accounting seam (transfer diet): every staged
        # upload and result fetch must land in the xfer.* registry
        # with its matching latency histogram, or the diet's
        # regression gate (tools/metrics_diff.py) reads nothing
        for cname in ("xfer.h2d_bytes", "xfer.h2d_puts",
                      "xfer.d2h_bytes", "xfer.d2h_fetches"):
            assert report["counters"].get(cname, 0) > 0, \
                f"smoke: {cname} missing from counter registry"
        for sname in ("xfer.h2d", "xfer.d2h"):
            sp = report["spans"].get(sname)
            assert sp and sp["count"] > 0, \
                f"smoke: {sname} histogram missing"
        assert "xfer.narrowed_ratio" in report["gauges"], \
            "smoke: xfer.narrowed_ratio gauge missing"
        assert xfer_dev.get("h2d_bytes", 0) > 0, \
            "smoke: device leg recorded no h2d bytes"
        # the round-12 kernel-dispatch registry: every fused converge
        # counts its static kernel-mode decision, so the sort-diet
        # evidence (and the metrics_diff gates reading it) can't rot
        assert any(k.startswith('converge.pallas{mode=')
                   for k in report["counters"]), \
            "smoke: converge.pallas mode counter missing"
        out["kernel_registry_ok"] = True
        # the round-13 sharded-converge registry: a 2-way sharded
        # converge of the smoke trace must be byte-identical to the
        # single-chip result AND light up every shard.* counter the
        # multichip regression gate reads (README "Multi-chip
        # sharding" registry)
        if len(jax.devices()) >= 2:
            from crdt_tpu.models import replay as _rp
            from crdt_tpu.ops import shard as _shard

            dec_s = decode_stage(blobs)
            cols_s, ds_s = column_stage(dec_s)
            splan = _shard.stage(cols_s, n_shards=2)
            assert splan is not None, "smoke: sharded staging refused"
            res_sh = _shard.converge(splan)
            w_s, v_s, o_s = _rp.gather(dec_s, ds_s, ("packed", res_sh))
            cache_sh = _rp.materialize(dec_s, ds_s, w_s, v_s, o_s)
            assert cache_sh == cache_dev, \
                "smoke: sharded converge diverges from single-chip"
            report = tracer.report()
            for cname in ("shard.dispatches", "shard.boundary_bytes"):
                assert report["counters"].get(cname, 0) > 0, \
                    f"smoke: {cname} missing from shard registry"
            assert "shard.shards" in report["gauges"], \
                "smoke: shard.shards gauge missing"
            assert "converge.wyllie_rounds" in report["gauges"], \
                "smoke: converge.wyllie_rounds gauge missing"
            out["shard_registry_ok"] = True
        # the round-23 subtree-split registry: a small branching-tree
        # doc (a shape the round-13 chain split refused outright)
        # plus a deep origin-chained map key chain, re-cut at a tiny
        # width — byte-identical to the split-disabled plan, and the
        # cut/rounds gauges the --conflict regression gate reads
        # must fire
        from crdt_tpu.codec import v1 as _v1
        from crdt_tpu.core.ids import DeleteSet as _DS
        from crdt_tpu.models import replay as _rp23
        from crdt_tpu.ops import packed as _packed

        recs23 = []
        for k in range(96):  # bushy tree: op k anchors op k // 3
            recs23.append(ItemRecord(
                client=1, clock=k, parent_root="t23",
                origin=(1, k // 3) if k else None, content=k))
        prev23 = None
        for k in range(40):  # deep origin-chained hot-key sets
            recs23.append(ItemRecord(
                client=1, clock=96 + k, parent_root="m23", key="hot",
                origin=(1, prev23) if prev23 is not None else None,
                content=k))
            prev23 = 96 + k
        blobs23 = [_v1.encode_update(recs23, _DS())]
        prior23 = os.environ.get(_packed._CHAIN_SPLIT_ENV)
        try:
            os.environ[_packed._CHAIN_SPLIT_ENV] = "0"
            want23 = _rp23.replay_trace(blobs23)
            os.environ[_packed._CHAIN_SPLIT_ENV] = "16"
            got23 = _rp23.replay_trace(blobs23)
        finally:
            if prior23 is None:
                os.environ.pop(_packed._CHAIN_SPLIT_ENV, None)
            else:
                os.environ[_packed._CHAIN_SPLIT_ENV] = prior23
        assert got23.cache == want23.cache \
            and got23.snapshot == want23.snapshot, \
            "smoke: subtree split diverges on the branching doc"
        g23 = tracer.report()["gauges"]
        for gname in ("converge.subtree_cuts",
                      "converge.map_chain_cuts"):
            assert g23.get(gname, 0) > 0, \
                f"smoke: {gname} did not fire on the branching doc"
        assert "converge.map_rounds" in g23, \
            "smoke: converge.map_rounds gauge missing"
        out["subtree_split_ok"] = True
        # the round-14 multi-tenant registry: a tiny mixed-tenant
        # batch through MultiDocServer, digest-identical to the
        # per-doc baseline, lighting up the tenant.* counters and
        # publishing the gated keys so the packing evidence (and the
        # metrics_diff gates reading it) can't rot between full runs
        os.environ.setdefault("BENCH_MT_DOCS", "8")
        os.environ.setdefault("BENCH_MT_OPS", "18")
        os.environ.setdefault("BENCH_MT_BIG", "1")
        os.environ.setdefault("BENCH_MT_BIG_OPS", "128")
        mt = multitenant_leg()
        assert mt["oracle_identical"], "smoke: multitenant diverges"
        assert mt["flood"]["bounded"], "smoke: flood tenant not shed"
        assert mt["flood"]["neighbors_unchanged"], \
            "smoke: flood changed a neighbor tenant"
        for key in ("docs_converged_per_s", "p99_per_doc_ms",
                    "dispatches_per_tick", "speedup"):
            assert mt.get(key) is not None, f"smoke: multitenant {key}"
        out["multitenant"] = {
            k: mt[k] for k in ("docs_converged_per_s",
                               "p99_per_doc_ms",
                               "dispatches_per_tick", "speedup",
                               "oracle_identical")
        }
        report = tracer.report()
        for cname in ("converge.docs_packed", "tenant.submitted",
                      "tenant.docs_converged", "tenant.shed",
                      "tenant.shed_bytes"):
            assert report["counters"].get(cname, 0) > 0, \
                f"smoke: {cname} missing from tenant registry"
        assert "tenant.pending_bytes" in report["gauges"], \
            "smoke: tenant.pending_bytes gauge missing"
        assert "tenant.dispatch_docs" in report["gauges"], \
            "smoke: tenant.dispatch_docs gauge missing"
        out["multitenant_registry_ok"] = True
        # the round-15 delta-tick registry: a tiny steady-state leg
        # (small deltas on resident docs + a rolling eviction flood),
        # digest-identical to the full-replay oracle, lighting the
        # tenant.delta_* / resident ledger / digest-skip evidence the
        # steady regression gates read
        os.environ.setdefault("BENCH_MT_STEADY_DOCS", "6")
        os.environ.setdefault("BENCH_MT_STEADY_OPS", "96")
        os.environ.setdefault("BENCH_MT_STEADY_DELTA", "3")
        os.environ.setdefault("BENCH_MT_STEADY_TICKS", "2")
        os.environ.setdefault("BENCH_MT_STEADY_FLOOD_DOCS", "20")
        os.environ.setdefault("BENCH_MT_STEADY_FLOOD_OPS", "48")
        mts = multitenant_steady_leg()
        assert mts["oracle_identical"], \
            "smoke: steady delta ticks diverge from full replay"
        assert mts["eviction"]["bounded"], \
            "smoke: resident budget unbounded or evictions missing"
        assert mts["eviction"]["reconverge_identical"], \
            "smoke: evicted doc did not reconverge"
        out["multitenant"]["steady"] = {
            k: mts[k] for k in ("docs_per_s", "speedup",
                                "delta_docs_per_tick",
                                "oracle_identical")
        }
        # one scalar on the line (the 1500-byte stdout budget); the
        # full per-tenant digest rides the BENCH_SMOKE_OUT artifact
        out["multitenant"]["steady"]["slo_ms"] = \
            mts["slo"]["slo_ms"]
        assert mts["slo"]["slo_ms"] > 0, "smoke: steady slo_ms"
        report = tracer.report()
        for cname in ("tenant.delta_docs", "tenant.delta_rows",
                      "tenant.promotions",
                      "tenant.resident_evictions",
                      "sentinel.doc_digest_skips"):
            assert report["counters"].get(cname, 0) > 0, \
                f"smoke: {cname} missing from delta-tick registry"
        for gname in ("tenant.resident_bytes",
                      "tenant.resident_docs"):
            assert gname in report["gauges"], \
                f"smoke: {gname} gauge missing"
        out["mt_incremental_registry_ok"] = True
        # the round-20 pooled-resident registry: a tiny all-warm
        # device-forced leg must batch every doc's device round into
        # ONE pooled dispatch per tick, byte-identical to the
        # unpooled route, lighting the tenant.pool_* counters/gauges
        # the dispatch-floor gates read
        os.environ.setdefault("BENCH_MT_POOLED_DOCS", "4")
        os.environ.setdefault("BENCH_MT_POOLED_OPS", "48")
        os.environ.setdefault("BENCH_MT_POOLED_DELTA", "3")
        os.environ.setdefault("BENCH_MT_POOLED_TICKS", "2")
        mtp = multitenant_pooled_leg()
        assert mtp["pooled_oracle_identical"], \
            "smoke: pooled route diverges from unpooled"
        assert mtp["device_dispatches_per_tick"] <= 2, \
            "smoke: pooled steady ticks above the dispatch floor"
        assert mtp["device_dispatches_per_tick"] \
            < mtp["unpooled_dispatches_per_tick"], \
            "smoke: pooling did not reduce dispatches"
        assert mtp["pool_dispatches"] > 0, \
            "smoke: pooled flush never dispatched"
        out["multitenant"]["steady"]["device_dispatches_per_tick"] = \
            mtp["device_dispatches_per_tick"]
        out["multitenant"]["steady"]["pool_peak_bytes"] = \
            mtp["pool_peak_bytes"]
        report = tracer.report()
        for cname in ("tenant.pool_dispatches",):
            assert report["counters"].get(cname, 0) > 0, \
                f"smoke: {cname} missing from pooled registry"
        for gname in ("tenant.pool_bytes", "tenant.pool_docs"):
            assert gname in report["gauges"], \
                f"smoke: {gname} gauge missing"
        out["mt_pooled_registry_ok"] = True
        # the round-21 snapshot registry: a tiny coldstart leg (scale
        # doc snapshot join + corruption fallback + server
        # checkpoint/restore), digest-asserted, lighting the snap.*
        # counters/gauges the recovery regression gates read
        os.environ.setdefault("BENCH_COLD_OPS", "600")
        os.environ.setdefault("BENCH_COLD_DELTA", "50")
        os.environ.setdefault("BENCH_COLD_DOCS", "3")
        cs = coldstart_leg()
        assert cs["oracle_identical"], \
            "smoke: snapshot join diverges from WAL replay"
        assert cs["fallback_recovered"], \
            "smoke: corrupted snapshot did not fall back to WAL"
        assert cs["server_identical"], \
            "smoke: checkpoint/restore diverges"
        assert cs["restore_docs"] == cs["checkpoint_docs"] > 0, \
            "smoke: checkpoint/restore lost docs"
        report = tracer.report()
        for cname in ("snap.writes", "snap.loads", "snap.bytes",
                      "tenant.checkpoint_docs"):
            assert report["counters"].get(cname, 0) > 0, \
                f"smoke: {cname} missing from snapshot registry"
        assert any(k.startswith("snap.fallbacks{")
                   for k in report["counters"]), \
            "smoke: snap.fallbacks{reason=} counter missing"
        for gname in ("snap.write_ms", "snap.load_ms"):
            assert gname in report["gauges"], \
                f"smoke: {gname} gauge missing"
        out["snap_registry_ok"] = True
        cs_art = os.environ.get("BENCH_COLDSTART_ARTIFACT")
        if cs_art:
            # CI points this at the workspace so the coldstart leg
            # the tier-1 smoke ALREADY ran uploads as the recovery
            # evidence artifact — same run-what-you-already-ran
            # pattern as BENCH_SMOKE_OUT (the committed full-scale
            # numbers live in BENCH_OUT.json's cold_start section)
            with open(cs_art, "w") as f:
                json.dump({
                    "cold_start": cs,
                    "snap_counters": {
                        k: v for k, v in report["counters"].items()
                        if k.startswith("snap.")
                        or k == "tenant.checkpoint_docs"
                    },
                }, f, indent=1, sort_keys=True)
                f.write("\n")
        # the round-18 SLO registry: the chaos flood leg above ran
        # with slo_ms=0, so breaches / burn rate / route mix must be
        # live (shed==breach for the flooder is asserted in the leg
        # itself via slo_flooder.shed_equals_route)
        assert report["counters"].get("slo.breaches", 0) > 0, \
            "smoke: slo.breaches missing from SLO registry"
        assert "slo.burn_rate" in report["gauges"], \
            "smoke: slo.burn_rate gauge missing"
        assert any(k.startswith("slo.route_cold{")
                   for k in report["counters"]), \
            "smoke: slo.route_cold{tenant=} counter missing"
        assert any(k.startswith("slo.route_shed{")
                   for k in report["counters"]), \
            "smoke: slo.route_shed{tenant=} counter missing"
        for sname in ("slo.ingest_to_converged",
                      "slo.ingest_to_served"):
            sp = report["spans"].get(sname)
            assert sp and sp["count"] > 0, \
                f"smoke: {sname} histogram missing"
        assert mt["flood"]["slo_flooder"]["shed_equals_route"], \
            "smoke: flooder shed count not mirrored in SLO route mix"
        out["slo_registry_ok"] = True
        # the round-18 timeline registry: the multitenant ticks above
        # recorded into the tick timeline; the per-tick overlap/stall
        # gauges must be live and the Perfetto export schema-valid
        from crdt_tpu.obs import get_timeline

        tl = get_timeline()
        assert report["counters"].get("timeline.ticks", 0) > 0, \
            "smoke: timeline.ticks counter missing"
        assert "timeline.overlap_efficiency" in report["gauges"], \
            "smoke: timeline.overlap_efficiency gauge missing"
        assert "timeline.stall_ms" in report["gauges"], \
            "smoke: timeline.stall_ms gauge missing"
        assert len(tl) > 0, "smoke: timeline ring empty"
        pf = tl.to_perfetto()
        assert pf["traceEvents"], "smoke: empty Perfetto export"
        for ev in pf["traceEvents"]:
            for k in ("name", "ph", "ts", "pid", "tid"):
                assert k in ev, f"smoke: Perfetto event missing {k}"
            if ev["ph"] == "X":
                assert ev["dur"] >= 0, "smoke: negative duration"
        tl_art = os.environ.get("BENCH_SMOKE_TIMELINE")
        if tl_art:
            # the schema-validated export doubles as CI's uploaded
            # timeline artifact (open at ui.perfetto.dev) — same
            # run-what-you-already-ran pattern as BENCH_SMOKE_OUT
            with open(tl_art, "w") as f:
                json.dump(pf, f)
        out["timeline_registry_ok"] = True
        # the round-19 propagation registry: a tiny traced loopback
        # swarm (broadcast + late-join sync answer + one forced AE
        # round) must light the wire-trace-context evidence — the
        # per-route hop-lag histograms, the birth-to-visibility
        # span, the context byte accounting and overhead gauge —
        # and a hostile context must degrade (counted) without
        # touching the update it rode on
        from crdt_tpu.obs import (
            FleetCollector,
            FlightRecorder,
            ObsHTTPServer,
            PropagationLedger,
            get_propagation,
            set_propagation,
            set_recorder,
        )

        set_recorder(FlightRecorder(enabled=True))
        set_propagation(PropagationLedger())
        pnet = LoopbackNetwork()
        pa = Replica(LoopbackRouter(pnet, "pa"), topic="ptrace",
                     client_id=11)
        pb = Replica(LoopbackRouter(pnet, "pb"), topic="ptrace",
                     client_id=12)
        pa.set("m", "k0", "v" * 256)
        pnet.run()
        pc = Replica(LoopbackRouter(pnet, "pc"), topic="ptrace",
                     client_id=13)  # late joiner: sync_answer route
        pnet.run()
        pb.set("m", "k1", "w" * 256)
        pnet.run()
        # force one anti-entropy round with a REAL deficit: blank
        # pa's recorded SV for pb so the delta actually ships (route
        # anti_entropy; redelivery is idempotent)
        from crdt_tpu.core.ids import StateVector as _SV

        pa.peer_state_vectors["pb"] = _SV()
        pa.anti_entropy_s = 0.5
        pa._ae_interval = 0.5
        pa._next_ae_at = time.monotonic() - 1
        pa.tick()
        pnet.run()
        # hostile context on a valid update: the update applies, the
        # context rejects (counted), the poll loop survives
        before_applied = tracer.report()["counters"].get(
            "replica.updates_applied", 0)
        pb_update = pa.doc.encode_state_as_update()
        pc._on_data({"update": pb_update, "tid": [11, 999, 0.0],
                     "hop": 0, "tc": b"\xff\x01hostile"}, "pa")
        pc.flush_incoming()
        report = tracer.report()
        assert report["counters"].get(
            "replica.updates_applied", 0) > before_applied, \
            "smoke: update with hostile context did not apply"
        assert report["counters"].get(
            "propagation.malformed_contexts", 0) > 0, \
            "smoke: hostile context not counted"
        for cname in ("propagation.contexts_sent",
                      "propagation.contexts_received",
                      "propagation.context_bytes",
                      "propagation.traced_update_bytes"):
            assert report["counters"].get(cname, 0) > 0, \
                f"smoke: {cname} missing from propagation registry"
        assert "propagation.wire_overhead_ratio" in \
            report["gauges"], "smoke: overhead gauge missing"
        for sname in ('replica.hop_lag{route="direct"}',
                      'replica.hop_lag{route="sync_answer"}',
                      'replica.hop_lag{route="anti_entropy"}',
                      "replica.birth_to_visibility"):
            sp = report["spans"].get(sname)
            assert sp and sp["count"] > 0, \
                f"smoke: {sname} histogram missing"
        led = get_propagation().report()
        assert led["hop_lag_by_route"].get("direct", {}).get(
            "count", 0) > 0, "smoke: ledger route histogram empty"
        out["propagation_registry_ok"] = True
        # the round-19 collector registry: scrape THIS process's own
        # obs endpoint through a FleetCollector, serve the /fleet
        # surfaces, and require full path reconstruction over the
        # traced loopback swarm above
        obs_self = ObsHTTPServer(port=0).start()
        col = FleetCollector()
        col.add_proc("self", obs_self.url)
        ok_scrape = col.scrape()
        assert ok_scrape.get("self"), "smoke: self-scrape failed"
        fleet = col.fleet_report()
        assert fleet["paths"]["traced_recvs"] > 0, \
            "smoke: collector saw no traced receives"
        assert fleet["paths"]["pair_rate"] == 1.0, \
            f"smoke: collector pair_rate {fleet['paths']}"
        assert any(k.endswith('{proc="self"}') for k in
                   fleet["metrics"]["counters"]), \
            "smoke: proc= labels missing from fleet registries"
        obs_fleet = ObsHTTPServer(port=0, collector=col).start()
        import urllib.request as _rq

        body = json.loads(_rq.urlopen(
            obs_fleet.url + "/fleet?scrape=0").read())
        assert body["procs"] == ["self"], "smoke: /fleet endpoint"
        mt_body = json.loads(_rq.urlopen(
            obs_fleet.url + "/fleet/timeline").read())
        assert "traceEvents" in mt_body, "smoke: /fleet/timeline"
        obs_fleet.stop()
        obs_self.stop()
        report = tracer.report()
        for cname in ("collector.scrapes",):
            assert report["counters"].get(cname, 0) > 0, \
                f"smoke: {cname} missing from collector registry"
        assert report["gauges"].get("collector.procs") == 1, \
            "smoke: collector.procs gauge missing"
        assert report["gauges"].get("collector.pair_rate") == 1.0, \
            "smoke: collector.pair_rate gauge missing"
        out["collector_registry_ok"] = True
        # the round-22 control-plane registry: a deterministic
        # synthetic sensor trace through a tiny-ledger Controller
        # (squeeze, cooldown-blocked oscillation, restore) must light
        # every control.* counter/gauge the regression gates read,
        # replay to a byte-identical ledger, and a cadence-configured
        # server over a real snapshot store must count
        # snap.cadence_writes (README "Control plane" registry)
        from crdt_tpu.obs import Controller
        from crdt_tpu.storage.snapshot import SnapshotStore

        sctrl = Controller(cooldown_ticks=3, restore_after=2,
                           ledger_capacity=2)
        for st in range(14):
            # flood -> clean (restore blocked by cooldown, counted)
            # -> restore -> re-flood (squeeze blocked, counted) ->
            # squeeze -> clean -> restore: both rules fire twice and
            # the cooldown gate blocks both directions
            burn = 1.0 if st in (0, 4, 5, 6) else 0.0
            sctrl.observe({
                "tick": st,
                "budget": {"max_bytes": 2048, "max_updates": 4},
                "tenants": {"flood!": {
                    "burn": burn, "shed": 4 * st,
                    "pending_bytes": 4096 if st < 6 else 0,
                }},
            })
        srules = [r["rule"] for r in sctrl.ledger.rows()]
        assert "budget_restore" in srules, \
            "smoke: controller never restored"
        assert sctrl.decisions >= 2 and sctrl.ledger.dropped > 0, \
            "smoke: control ledger drop accounting missing"
        assert sctrl.cooldown_skips > 0, \
            "smoke: cooldown never blocked an oscillating sensor"
        sreplay = Controller.replay(list(sctrl.trace),
                                    **sctrl.config())
        assert sreplay.ledger.to_jsonl() == sctrl.ledger.to_jsonl(), \
            "smoke: control ledger replay not byte-identical"
        # cadence actuation through a REAL server + snapshot store
        from crdt_tpu.models.multidoc import MultiDocServer as _MDS

        with tempfile.TemporaryDirectory() as td:
            csrv = _MDS(snap_store=SnapshotStore(td),
                        checkpoint_every_ticks=2)
            cstream = _SteadyStream(700)
            for ct in range(5):
                csrv.submit("cadence", cstream.delta(4))
                csrv.tick()
            assert csrv.cadence_checkpoints > 0, \
                "smoke: cadence checkpoint never fired"
        report = tracer.report()
        for cname in ("control.decisions", "control.cooldown_skips",
                      "control.ledger_dropped",
                      "snap.cadence_writes"):
            assert report["counters"].get(cname, 0) > 0, \
                f"smoke: {cname} missing from control registry"
        assert any(k.startswith("control.decisions{rule=")
                   for k in report["counters"]), \
            "smoke: control.decisions{rule=} counter missing"
        assert any(k.startswith("control.setpoint{knob=")
                   for k in report["gauges"]), \
            "smoke: control.setpoint{knob=} gauge missing"
        ctl_art = os.environ.get("BENCH_SMOKE_CONTROL")
        if ctl_art:
            # the smoke controller's decision ledger doubles as CI's
            # uploaded control-plane artifact (audit it offline with
            # ``tools/obsq.py control``) — same run-what-you-
            # already-ran pattern as BENCH_SMOKE_OUT
            sctrl.ledger.dump_jsonl(ctl_art)
        out["control_registry_ok"] = True
        out["tracer_spans_ok"] = True
    # obs-off overhead pin (round 18 satellite): a DISABLED tracer's
    # span hook must stay one attribute check + one shared no-op
    # context manager — no per-call allocation, sub-5us per span even
    # on a loaded CI box (the hot paths run it millions of times)
    from crdt_tpu.obs import Tracer as _Tracer

    _off = _Tracer(enabled=False)
    assert _off.span("a") is _off.span("b"), \
        "smoke: disabled span allocated a fresh context manager"
    reps = 50_000
    t0 = time.perf_counter()
    for _ in range(reps):
        with _off.span("converge.dispatch"):
            pass
    per_ns = (time.perf_counter() - t0) / reps * 1e9
    assert per_ns < 5000, \
        f"smoke: disabled span costs {per_ns:.0f}ns/call (>5us)"
    assert not _off.report()["spans"], \
        "smoke: disabled tracer recorded spans"
    out["obs_disabled_span_ns"] = int(per_ns)
    smoke_out = os.environ.get("BENCH_SMOKE_OUT")
    if smoke_out and report is not None:
        # the BENCH_OUT-shaped artifact WITH the embedded report, at
        # a caller-chosen path (never the committed BENCH_OUT.json:
        # smoke must not overwrite real run evidence with toy numbers)
        with open(smoke_out, "w") as f:
            json.dump({**out, "tracer": report}, f, indent=1,
                      sort_keys=True)
            f.write("\n")
    # the numpy contender's phase dict (and the round-20 pooled
    # steady keys) stay in the artifact above; on stdout they would
    # push the one-line JSON past emit_result's 1500-byte tail
    # budget (nothing downstream reads them from the line — the
    # gated keys ride the artifact, where metrics_diff looks)
    out.pop("phases_numpy_s", None)
    # the contender wall-clock scalars also ride the artifact only:
    # the round-21 snap_registry_ok flag pushed the line past the
    # 1500-byte budget, and nothing downstream reads timings from it
    for k in ("numpy_s", "device_s", "stream_s"):
        out.pop(k, None)
    # the round-23 subtree-split flag rides the artifact only, for
    # the same budget reason (tier-1 reads it from the artifact)
    out.pop("subtree_split_ok", None)
    if isinstance(out.get("multitenant", {}).get("steady"), dict):
        out["multitenant"]["steady"].pop(
            "device_dispatches_per_tick", None)
        out["multitenant"]["steady"].pop("pool_peak_bytes", None)
    emit_result(out, path=None)  # smoke never overwrites run evidence


def main():
    _ensure_live_backend()
    import jax

    global enable_x64
    from crdt_tpu.compat import enable_x64

    jax.config.update("jax_enable_x64", True)

    # phase evidence rides the artifact: the full tracer report
    # (p50/p99 histograms for decode/pack/converge.dispatch/
    # converge.fetch/materialize/persist + counters) is embedded in
    # BENCH_OUT.json at the end, so every committed bench run carries
    # its own per-phase breakdown. BENCH_TRACE=0 disables (hooks cost
    # one attribute check when off).
    from crdt_tpu.obs import Tracer, set_tracer

    bench_tracer = None
    if os.environ.get("BENCH_TRACE", "1") != "0":
        bench_tracer = set_tracer(Tracer(enabled=True))
    # the persistent compile cache is configured by the package itself
    # (crdt_tpu/ops/device.py, per-user path): the untimed warmup
    # costs real compile only on a cold machine

    R = int(os.environ.get("BENCH_REPLICAS", 1000))
    K = int(os.environ.get("BENCH_OPS", 100))
    iters = int(os.environ.get("BENCH_ITERS", 3))
    total = R * K
    platform = jax.devices()[0].platform
    log(f"workload: {R} replicas x {K} ops = {total} ops, platform={platform}")

    lazy_probe = force_sync_mode()
    costs = platform_costs()

    t0 = time.perf_counter()
    blobs = build_trace(R, K)
    log(f"trace: {len(blobs)} blobs, {sum(map(len, blobs)):,} bytes "
        f"(built in {time.perf_counter() - t0:.1f}s, untimed)")

    # ---- warm both paths (compilation; persistent cache) -------------
    t0 = time.perf_counter()
    run_device(blobs, {})
    log(f"device warmup (compile): {time.perf_counter() - t0:.1f}s (untimed)")

    # ---- kernel-only N-scaling sweep (forced-sync, honest) -----------
    # Methodology: per-dispatch time is the best of three 8-deep
    # back-to-back batches (one block at the end of each batch). The
    # tunnel pipelines queued dispatches, so batching amortizes its
    # per-dispatch LATENCY jitter (25-115ms, session weather) while
    # still charging the real per-dispatch THROUGHPUT cost; a null
    # dispatch measured with the identical methodology pins that
    # residual floor, and `net` = sweep - floor is the device compute.
    from crdt_tpu.ops import packed as _pk
    import jax.numpy as jnp

    def _b2b_ms(fn, reps=8, outer=3):
        jax.block_until_ready(fn())  # warm / compile
        best = float("inf")
        for _ in range(outer):
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn()
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / reps)
        return best * 1e3

    dec_w = decode_stage(blobs)
    cols_w, _ = column_stage(dec_w)
    sweep = {}
    null_floor_ms = None
    for frac in (4, 2, 1):
        nsub = len(cols_w["client"]) // frac
        plan = _pk.stage({k: v[:nsub] for k, v in cols_w.items()})
        with enable_x64(True):
            # undonated repeat-dispatch probe: the production converge
            # entries donate their staged buffers (one plan, one
            # dispatch), so the sweep needs its own entry to re-time
            # the same device matrix
            dev, sweep_fn = _pk.make_repeat_dispatch(plan)
            jax.block_until_ready(dev)
            sweep[nsub] = _b2b_ms(lambda: sweep_fn(dev)) / 1e3
            if frac == 1:
                # the staged upload is one flat section array (round
                # 12); the null program touches a single element of it
                null = jax.jit(lambda m: m[:1].astype(jnp.int32) + 1)
                null_floor_ms = _b2b_ms(lambda: null(dev))
    ns = sorted(sweep)
    log("fused-kernel dispatch sweep (8-deep b2b, sync mode): " + ", ".join(
        f"{n}: {sweep[n]*1e3:.1f}ms" for n in ns)
        + f"; null-dispatch floor {null_floor_ms:.1f}ms")
    kernel_ops_s = round(ns[-1] / sweep[ns[-1]])

    # ---- per-primitive sort-diet ablation (round 12) -----------------
    try:
        with enable_x64(True):
            ablation = kernel_ablation_leg(cols_w, _b2b_ms,
                                           null_floor_ms)
        log("kernel ablation (net ms, jnp -> pallas): "
            + ", ".join(
                f"{k.split('_ms')[0]} {v['jnp']:.2f} -> "
                f"{v['pallas']:.2f}"
                for k, v in ablation.items()
                if isinstance(v, dict) and "jnp" in v)
            + f"; sort+map speedup {ablation['sort_map_speedup']}x")
    except Exception as exc:
        log(f"kernel ablation failed: {exc!r}")
        ablation = {"error": repr(exc)}

    # ---- timed end-to-end runs ---------------------------------------
    t_dev = None
    xfer_headline = None
    for _ in range(iters):
        phases_dev = {}
        xfer_before = _xfer_counters()
        t0 = time.perf_counter()
        cache_dev, snap_dev, dec, ds, win_rows, win_vis, seq_orders = (
            run_device(blobs, phases_dev)
        )
        dt = time.perf_counter() - t0
        xfer_after = _xfer_counters()
        if t_dev is None or dt < t_dev:
            t_dev, best_phases_dev = dt, phases_dev
            xfer_headline = _xfer_diff(xfer_before, xfer_after)
    log(f"device e2e: {t_dev:.3f}s ({total / t_dev:,.0f} ops/s) "
        f"phases={best_phases_dev} xfer={xfer_headline}")

    t_np = None
    for _ in range(iters):
        phases_np = {}
        t0 = time.perf_counter()
        cache_np, snap_np = run_numpy(blobs, phases_np)
        dt = time.perf_counter() - t0
        if t_np is None or dt < t_np:
            t_np, best_phases_np = dt, phases_np
    log(f"numpy-scalar e2e: {t_np:.3f}s ({total / t_np:,.0f} ops/s) "
        f"phases={best_phases_np}")

    # the two contenders must agree before any ratio is meaningful
    assert cache_dev == cache_np, "device and numpy contenders diverge"
    assert snap_dev == snap_np

    # ---- WAL evidence (untimed): run the persistence layer so the
    # embedded tracer report carries real persist append/compact spans
    if bench_tracer is not None:
        import tempfile

        from crdt_tpu.storage.persistence import LogPersistence

        with tempfile.TemporaryDirectory() as td:
            lp = LogPersistence(os.path.join(td, "bench.kvlog"))
            for blob in blobs[: min(64, len(blobs))]:
                lp.store_update("bench", blob)
            lp.compact("bench", snap_dev)
            lp.close()

    # ---- python oracle (BASELINE.md's named baseline) ----------------
    skip_oracle = os.environ.get("BENCH_SKIP_ORACLE", "0") == "1"
    oracle_x = None
    if not skip_oracle:
        eng, t_oracle = run_oracle(blobs)
        oracle_x = round(t_oracle / t_dev, 1)
        log(f"python oracle e2e: {t_oracle:.2f}s "
            f"({total / t_oracle:,.0f} ops/s) -> device is {oracle_x}x")
        # correctness: winners + sequence orders match the faithful engine
        wt = {
            (p[1], k): (rec_id, vis)
            for (p, k), (rec_id, vis) in eng.map_winner_table().items()
            if p[0] == "root"
        }
        roots_d, keys_d = dec["roots"], dec["keys"]
        got = {}
        for row, vis in zip(win_rows, win_vis):
            got[(roots_d[dec["parent_root"][row]],
                 keys_d[dec["key_id"][row]])] = (
                (int(dec["client"][row]), int(dec["clock"][row])), vis)
        mismatch = sum(1 for kk, vv in wt.items() if got.get(kk) != vv)
        assert mismatch == 0, f"{mismatch}/{len(wt)} winners diverge"
        want_orders = eng.seq_order_table()
        got_orders = {
            spec: [(int(dec["client"][r]), int(dec["clock"][r]))
                   for r in rows]
            for spec, rows in seq_orders.items()
        }
        assert got_orders == want_orders, "sequence order diverges"
        log(f"correctness vs oracle: {len(wt)} map keys, "
            f"{len(want_orders)} sequences, 0 divergent")

    # ---- conflict-heavy YATA run (BENCH_CONFLICT=0 to skip) ----------
    # The hard case the append-only trace never triggers (VERDICT r1):
    # R-wide same-origin sibling groups on shared anchors. Exactness is
    # asserted against the scalar oracle at this size.
    conflict_result = None
    try:
      if os.environ.get("BENCH_CONFLICT", "1") != "0":
        R_c = min(R, 200)
        blobs_c = build_conflict_trace(R_c, K)
        run_device(blobs_c, {})  # warm shapes
        # min-of-2 on EVERY contender (one shared idiom: min_time), so
        # no ratio ever divides differently-treated quantities
        t_dev_c, _, dev_out = min_time(
            lambda: run_device(blobs_c, {}), 2
        )
        cache_c = dev_out[0]
        t_np_c, _, np_out = min_time(lambda: run_numpy(blobs_c, {}), 2)
        cache_cn = np_out[0]
        assert cache_c == cache_cn, "conflict run: contenders diverge"
        # the PRODUCT route (auto: session crossover — at this size
        # the local-backend fused kernel), min-of-3, same headline
        # treatment as text_run's routes
        from crdt_tpu.models import replay_trace as _rt_c

        t_auto_c, _, res_ac = min_time(
            lambda: _rt_c(blobs_c, route="auto"), 3
        )
        assert res_ac.cache == cache_c, "conflict auto route diverges"
        conflict_result = {
            "ops": R_c * K,
            "device_s": round(t_dev_c, 3),
            "numpy_s": round(t_np_c, 3),
            "auto_s": round(t_auto_c, 3),
            "auto_path": res_ac.path,
            "vs_baseline": round(t_np_c / t_dev_c, 2),
            "vs_python_oracle": None,
        }
        oracle_note = "oracle skipped"
        if not skip_oracle:
            # min-of-2 oracle: the ratio's numerator gets the same
            # noise treatment as its min-of-N denominator
            eng_c, t_oracle_c = run_oracle(blobs_c)
            _, t_oracle_c2 = run_oracle(blobs_c)
            t_oracle_c = min(t_oracle_c, t_oracle_c2)
            assert cache_c == eng_c.to_json(), \
                "conflict run diverges from oracle"
            conflict_result["vs_python_oracle"] = round(
                t_oracle_c / t_auto_c, 1
            )
            conflict_result["vs_python_oracle_device"] = round(
                t_oracle_c / t_dev_c, 1
            )
            oracle_note = f"oracle {t_oracle_c:.2f}s; exact"
        log(f"conflict e2e ({R_c * K} ops, shared-anchor siblings): "
            f"auto {t_auto_c:.3f}s ({res_ac.path}), device "
            f"{t_dev_c:.3f}s vs numpy {t_np_c:.3f}s; {oracle_note}")

    except AssertionError:
        raise  # a correctness divergence must FAIL the bench
    except Exception as exc:  # transient tunnel/compile failures
        log(f"conflict run failed: {exc!r}")
        conflict_result = conflict_result or {}
        conflict_result["error"] = repr(exc)

    # ---- right-bearing text run (BENCH_TEXT=0 to skip) ---------------
    # Mid-inserts carry right origins, which the device sibling model
    # cannot express; ordering for affected parents runs through the
    # exact host machinery. Referenced against the oracle (the numpy
    # contender does not model rights).
    text_result = None
    try:
      if os.environ.get("BENCH_TEXT", "1") != "0":
        R_t = min(R, 200)
        blobs_t = build_text_trace(R_t, K)
        from crdt_tpu.models import replay_trace as _replay

        _replay(blobs_t)  # warm shapes (device route)
        # ALL FOUR routes recorded, min-of-3 each; the HEADLINE ratio
        # is the auto route — the product's real behavior (VERDICT r4
        # item 4). "host" is the identical fused kernel on the local
        # CPU backend (zero tunnel interactions); "replica" is the
        # resident replica's own ingest machinery.
        routes = {}
        res_t = None
        for route in ("device", "host", "auto", "replica"):
            # min-of-3 (shared min_time idiom): the box's CPU
            # contention moves host-side spans ~2x between sessions,
            # and the headline ratio hangs off this minimum
            best, runs, res_r = min_time(
                lambda route=route: _replay(blobs_t, route=route), 3
            )
            if route == "device":
                res_t = res_r
            else:
                assert res_r.cache == res_t.cache, \
                    f"text route {route} diverges"
            routes[route] = {
                "s": round(best, 3), "runs_s": runs, "path": res_r.path,
            }
        t_dev_t = routes["device"]["s"]
        t_auto_t = routes["auto"]["s"]
        log("text routes: " + "  ".join(
            f"{r}={routes[r]['s']}s({routes[r]['path']})"
            for r in routes))
        text_result = {
            "ops": R_t * K,
            "device_s": t_dev_t,
            "auto_s": t_auto_t,
            "routes": routes,
            "vs_python_oracle": None,
        }

        # steady-state text rounds: a live replica consuming
        # mid-insert (right-bearing) deltas on a GROWING document.
        # Per-round cost must track the DELTA, not the document —
        # the linked-chain incremental integrate's claim (VERDICT r3
        # item 5; the r3 design re-ordered the whole segment per
        # touch, so this number grew with the doc).
        from crdt_tpu.codec import v1 as _v1t
        from crdt_tpu.core.ids import DeleteSet as _DS
        from crdt_tpu.core.records import ItemRecord as _IR
        from crdt_tpu.models.incremental import IncrementalReplay as _Inc

        rng_t = np.random.default_rng(11)
        inc_t = _Inc(capacity=1 << 16)
        inc_t.device_min_rows = 1 << 62  # the keystroke regime: host
        chain_t: list = []
        clk = [0]

        def text_round(n_ops):
            recs = []
            for _ in range(n_ops):
                if chain_t and rng_t.random() < 0.5:
                    j = int(rng_t.integers(0, len(chain_t)))
                    recs.append(_IR(
                        client=1, clock=clk[0], parent_root="text",
                        origin=chain_t[j - 1] if j > 0 else None,
                        right=chain_t[j], content=clk[0]))
                    chain_t.insert(j, (1, clk[0]))
                else:
                    recs.append(_IR(
                        client=1, clock=clk[0], parent_root="text",
                        origin=chain_t[-1] if chain_t else None,
                        content=clk[0]))
                    chain_t.append((1, clk[0]))
                clk[0] += 1
            blob = _v1t.encode_update(recs, _DS())
            t0 = time.perf_counter()
            inc_t.apply([blob])
            return time.perf_counter() - t0

        steady = {}
        for _ in range(4):
            for _ in range(40):
                text_round(100)
            t_round = min(text_round(100) for _ in range(3))
            steady[str(inc_t.cols.n)] = round(t_round * 1e3, 2)
        ks = sorted(steady, key=int)
        text_result["steady_round_ms_by_doc_rows"] = steady
        text_result["steady_flat_ratio"] = round(
            steady[ks[-1]] / max(steady[ks[0]], 1e-9), 2
        )

        # keystroke regime: LOCAL mid-document inserts on a growing
        # resident doc. Anchor resolution is cursor-local (epoch-
        # validated), so per-insert cost must stay flat in doc size
        # (VERDICT r4 item 8; previously O(index) per insert).
        from crdt_tpu.api.resident_doc import ResidentCrdt as _RC

        kdoc = _RC(91)
        kdoc.array("kt")
        kdoc.push("kt", 0)
        keys_tbl = {}
        for _ in range(4):
            for i in range(4000):
                kdoc.push("kt", i)
            nvis = len(kdoc.c["kt"])
            mid = nvis // 2
            kdoc.insert("kt", mid, "w")  # seed the cursor (amortized)
            best = float("inf")  # min-of-2 batches: ~50us/op numbers
            for b in range(2):   # are easily doubled by box noise
                t0 = time.perf_counter()
                for j in range(100):
                    kdoc.insert("kt", mid + (j % 7) - 3, f"m{b}-{j}")
                best = min(best, time.perf_counter() - t0)
            keys_tbl[str(nvis)] = round(best / 100 * 1e6, 1)
        kk = sorted(keys_tbl, key=int)
        text_result["keystroke_insert_us_by_doc_rows"] = keys_tbl
        text_result["keystroke_flat_ratio"] = round(
            keys_tbl[kk[-1]] / max(keys_tbl[kk[0]], 1e-9), 2
        )
        log("keystroke mid-inserts (us/op by doc rows): "
            + ", ".join(f"{k}: {keys_tbl[k]}" for k in kk)
            + f" (last/first {text_result['keystroke_flat_ratio']})")
        log("text steady-state rounds (100 mid-inserts each): "
            + ", ".join(f"{k} rows: {steady[k]}ms" for k in ks)
            + f" (last/first {text_result['steady_flat_ratio']})")
        oracle_note = "oracle skipped"
        if not skip_oracle:
            # min-of-2 on the oracle too: the headline ratio is a
            # quotient of two host-side timings — both sides get the
            # same noise treatment
            eng_t, t_oracle_t = run_oracle(blobs_t)
            _, t_oracle_t2 = run_oracle(blobs_t)
            t_oracle_t = min(t_oracle_t, t_oracle_t2)
            assert res_t.cache == eng_t.to_json(), \
                "text run diverges from oracle"
            # the HEADLINE is the auto route — what the product does
            text_result["vs_python_oracle"] = round(
                t_oracle_t / t_auto_t, 1
            )
            text_result["vs_python_oracle_by_route"] = {
                r: round(t_oracle_t / routes[r]["s"], 1) for r in routes
            }
            oracle_note = f"oracle {t_oracle_t:.2f}s; exact"
        log(f"text e2e ({R_t * K} ops, 20% right-bearing mid-inserts): "
            f"auto {t_auto_t:.3f}s, device {t_dev_t:.3f}s; {oracle_note}")

    except AssertionError:
        raise
    except Exception as exc:
        log(f"text run failed: {exc!r}")
        text_result = text_result or {}
        text_result["error"] = repr(exc)

    # ---- PRODUCT swarm run (BENCH_SWARM=0 to skip) -------------------
    # The replica-level gate, not the firehose models: a loopback
    # swarm converges through the live sync protocol in each merge
    # mode. Scalar/resident pay host merges; "device" routes every
    # buffered round through the engine's TPU gate — its overhead
    # through this tunnel is a published number here, not a private
    # one (VERDICT r2 item 8).
    swarm_result = None
    try:
      if os.environ.get("BENCH_SWARM", "1") != "0":
        from crdt_tpu.net import LoopbackNetwork, LoopbackRouter, ypear_crdt

        def swarm_round(mode, n_reps, n_ops, mixed=False):
            net = LoopbackNetwork()
            reps = [
                ypear_crdt(LoopbackRouter(net, f"pk{i}"), topic="b",
                           client_id=i + 1, merge_mode=mode,
                           batch_incoming=True)
                for i in range(n_reps)
            ]
            net.run()
            t0 = time.perf_counter()
            for i, r in enumerate(reps):
                for j in range(n_ops):
                    if not mixed:
                        if j % 2:
                            r.set("m", f"k{i}-{j}", j)
                        else:
                            r.push("l", f"v{i}-{j}")
                        continue
                    k = j % 5
                    if k == 0:
                        r.set("m", f"k{i % 16}-{j % 32}", [i, j])
                    elif k == 1:
                        r.push("l", f"v{i}-{j}")
                    elif k == 2:  # nested array-in-map
                        r.set("nest", f"arr{i % 8}", value=f"n{i}-{j}",
                              array_method="push")
                    elif k == 3:  # mid-insert at a live index
                        cur = r.get("l") or []
                        r.insert("l", (i * 7 + j) % (len(cur) + 1),
                                 f"ins{i}-{j}")
                    else:
                        r.set("m", f"solo{i}", j)
                if mixed and i % 8 == 7:
                    net.run()  # interleaved delivery mid-stream
            net.run()
            dt = time.perf_counter() - t0
            first = dict(reps[0].c)
            assert all(dict(r.c) == first for r in reps[1:]), mode
            return dt

        # single-run swarm numbers flip on session weather (the r4
        # artifact recorded a resident loss its own commit could not
        # reproduce) — every published number is a min-of-N with the
        # runs recorded
        n_reps, n_ops = 12, 25
        swarm_result = {
            "replicas": n_reps,
            "ops": n_reps * n_ops,
            # the engine device gate pays a tunnel round-trip per
            # buffered round; it is kept as a differential oracle
            # (merge_mode="device"), NOT a product default — resident
            # is the device-resident product mode (VERDICT r3 item 4)
            "note": "device = explicit differential-oracle mode; "
                    "min-of-N, runs recorded",
        }
        for mode in ("scalar", "resident", "device"):
            if mode == "device":
                swarm_round(mode, n_reps, n_ops)  # warm compiled shapes
            runs = [
                round(swarm_round(mode, n_reps, n_ops), 3)
                for _ in range(2 if mode == "device" else 3)
            ]
            swarm_result[f"{mode}_s"] = min(runs)
            swarm_result[f"{mode}_runs_s"] = runs
        log(f"product swarm ({n_reps} replicas x {n_ops} ops, "
            f"buffered rounds): "
            + "  ".join(f"{m}={swarm_result[f'{m}_s']}s"
                        for m in ("scalar", "resident", "device")))

        # the non-toy shape (BASELINE configs 3/4): 64 replicas x 200
        # mixed ops each — maps, list appends, live-index mid-inserts,
        # nested array-in-map — with interleaved delivery. The scalar
        # engine pays every peer's re-merge per buffered round; the
        # resident replica's linked-chain integrate is the product
        # claim under test at this size (VERDICT r4 item 2).
        n_big_reps = int(os.environ.get("BENCH_SWARM_BIG_REPS", 64))
        n_big_ops = int(os.environ.get("BENCH_SWARM_BIG_OPS", 200))
        if n_big_reps > 0:
            big = {"replicas": n_big_reps, "ops": n_big_reps * n_big_ops,
                   "workload": "mixed map/array + nested + mid-inserts, "
                               "interleaved delivery"}
            for mode in ("scalar", "resident"):
                runs = [
                    round(swarm_round(mode, n_big_reps, n_big_ops,
                                      mixed=True), 2)
                    for _ in range(2)
                ]
                big[f"{mode}_s"] = min(runs)
                big[f"{mode}_runs_s"] = runs
                log(f"big swarm {mode}: {min(runs)}s {runs}")
            big["resident_vs_scalar"] = round(
                big["scalar_s"] / max(big["resident_s"], 1e-9), 2
            )
            swarm_result["big"] = big
    except AssertionError:
        raise
    except Exception as exc:
        log(f"swarm run failed: {exc!r}")
        swarm_result = {"error": repr(exc)}

    # ---- fleet run (BENCH_FLEET=0 to skip) ---------------------------
    # The mesh axis as a MEASURED product capability (VERDICT r4 item
    # 1): real per-replica v1 broadcast blobs staged into the sharded
    # gossip model, one collective round converging the whole swarm.
    # Three records: single-chip scaling vs replica count (the replica
    # axis batched on one device), a differential check against the
    # scalar engine, and a subprocess weak-scaling table on the
    # virtual 8-device CPU mesh (the driver's multichip rig).
    fleet_result = None
    try:
      if os.environ.get("BENCH_FLEET", "1") != "0":
        from crdt_tpu.models.fleet import (
            fleet_for_trace,
            fleet_replay,
            load_trace,
        )
        from crdt_tpu.parallel.gossip import make_mesh

        from crdt_tpu.models.fleet import SegmentedFleet, shard_trace

        K_f = 64
        fleet_result = {"ops_per_replica": K_f, "single_chip": {}}
        mesh1 = make_mesh(1)
        for R_f in (64, 256, 1024):
            blobs_f = build_trace(R_f, K_f, seed=9)
            tr = load_trace(blobs_f, replicas_multiple=1)
            fleet = fleet_for_trace(tr, mesh=mesh1)
            fleet.step(tr.cols, tr.dels)  # compile (untimed)
            times = []
            for _ in range(3):
                t0 = time.perf_counter()
                fleet.step(tr.cols, tr.dels)
                times.append(round(time.perf_counter() - t0, 3))
            t_round = min(times)
            # the segmented mapping on the same chip: converge +
            # sharded deficit on device, SV build on host at staging
            sh = shard_trace(tr, 1)
            sf = SegmentedFleet(sh, mesh=mesh1)
            sf.step(sh)  # compile (untimed)
            times_seg = []
            for _ in range(3):
                t0 = time.perf_counter()
                sf.step(sh)
                times_seg.append(round(time.perf_counter() - t0, 3))
            t_seg = min(times_seg)
            fleet_result["single_chip"][str(R_f)] = {
                "ops": R_f * K_f,
                "round_s": t_round,
                "ops_per_s": round(R_f * K_f / t_round),
                "runs_s": times,
                "segmented_round_s": t_seg,
                "segmented_ops_per_s": round(R_f * K_f / t_seg),
                "segmented_runs_s": times_seg,
            }
            log(f"fleet round ({R_f} replicas x {K_f} ops, 1 chip): "
                f"replicated {t_round:.3f}s "
                f"({R_f * K_f / t_round:,.0f} ops/s), "
                f"segmented {t_seg:.3f}s "
                f"({R_f * K_f / t_seg:,.0f} ops/s)")

        # differential: the fleet PRODUCT route must reproduce the
        # scalar engine's document on the same broadcasts, in BOTH
        # mesh mappings
        R_d = 64
        blobs_d = build_trace(R_d, K_f, seed=9)
        res_fleet = fleet_replay(blobs_d, mesh=mesh1)
        res_seg = fleet_replay(blobs_d, mesh=mesh1, shard="segments")
        assert res_seg.cache == res_fleet.cache, \
            "fleet shard modes diverge"
        if not skip_oracle:
            eng_f, t_eng_f = run_oracle(blobs_d)
            assert res_fleet.cache == eng_f.to_json(), \
                "fleet diverges from engine"
            fleet_result["differential_ok"] = True
            # one engine applyUpdate pass over the round = ONE peer's
            # merge work in the reference's full-mesh swarm; every
            # peer repeats it, so a host swarm of R replicas pays
            # ~R x this per round, while one fleet round serves every
            # replica's converged state + SV handshake at once
            fleet_result["engine_one_peer_apply_s"] = round(t_eng_f, 3)
            r64 = fleet_result["single_chip"][str(R_d)]
            # the reference's full-mesh swarm repeats that merge at
            # EVERY peer: R x one-peer apply is the swarm's total
            # merge work for the round the fleet serves in one shot.
            # Per-mode ratios: the segmented step's SV build happens
            # at host STAGING, outside the timed step, so its ratio
            # reads merge-only and is not directly comparable to the
            # replicated round (which times the handshake on device).
            t_swarm = R_d * t_eng_f
            fleet_result["swarm_equiv_total_merge_s"] = round(t_swarm, 2)
            fleet_result["fleet_vs_swarm_equiv"] = {
                "replicated": round(t_swarm / r64["round_s"], 1),
                "segmented_merge_only": round(
                    t_swarm / r64["segmented_round_s"], 1
                ),
                # VERDICT r5 Next #6: this is an EXTRAPOLATION, not a
                # measured swarm — one engine applyUpdate pass over
                # the round, times R, on the model that the
                # reference's full-mesh swarm repeats the same merge
                # at every peer. No R-peer swarm was actually run.
                "estimated": True,
                "formula": (
                    f"swarm_equiv_total_merge_s = R({R_d}) x "
                    "engine_one_peer_apply_s (one measured apply, "
                    "extrapolated); ratio = that / fleet round_s"
                ),
            }
            ratios = fleet_result["fleet_vs_swarm_equiv"]
            log(f"fleet differential: exact; engine one-peer apply "
                f"{t_eng_f:.3f}s -> {R_d}-peer swarm-equivalent "
                f"{t_swarm:.2f}s of merge work vs one fleet round: "
                f"replicated x{ratios['replicated']}, "
                f"segmented (merge-only) "
                f"x{ratios['segmented_merge_only']}")
        else:
            from crdt_tpu.models import replay_trace as _rt_f

            res_h_f = _rt_f(blobs_d, route="host")
            assert res_fleet.cache == res_h_f.cache
            fleet_result["differential_ok"] = True

        # virtual-mesh weak scaling (subprocess: the TPU tunnel env
        # must not leak into the CPU mesh child)
        import subprocess
        import sys as _sys

        child_env = dict(os.environ)
        child_env.pop("PALLAS_AXON_POOL_IPS", None)
        child_env["JAX_PLATFORMS"] = "cpu"
        child_env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=8"
        )
        proc = subprocess.run(
            [_sys.executable, os.path.abspath(__file__),
             "--fleet-mesh-child", "128", "64", "1", "2", "4", "8"],
            env=child_env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode == 0 and proc.stdout.strip():
            fleet_result["virtual_mesh"] = json.loads(
                proc.stdout.strip().splitlines()[-1]
            )
            ss = fleet_result["virtual_mesh"]["strong_scaling"]
            log("fleet virtual-mesh strong scaling (128-replica union; "
                "1-core rig, so flat = work truly divides): "
                + ", ".join(
                    f"{nd}d: seg {ss[nd]['segmented_round_s']}s vs "
                    f"repl {ss[nd]['replicated_round_s']}s"
                    for nd in sorted(ss, key=int)))
        else:
            fleet_result["virtual_mesh"] = {
                "error": (proc.stderr or "no output")[-500:]
            }
            log(f"fleet mesh child failed: {proc.stderr[-300:]}")
    except AssertionError:
        raise
    except Exception as exc:
        log(f"fleet run failed: {exc!r}")
        fleet_result = fleet_result or {}
        fleet_result["error"] = repr(exc)

    # ---- larger-scale crossover run (BENCH_SCALE=0 to skip) ----------
    scale_result = None
    scale = int(os.environ.get("BENCH_SCALE", 16))
    try:
      if scale > 1:
        log(f"scale run: {R * scale} replicas x {K} ops")
        blobs_l = build_trace(R * scale, K, seed=1)
        run_device(blobs_l, {})  # warm one-shot shapes (the oracle)
        run_stream(blobs_l, {})  # warm the streaming shard shapes
        # the DEVICE PATH of the scale replay is the overlapped
        # streaming executor (on by default; crdt_tpu.models.
        # streaming); the serial one-shot pipeline stays as the
        # reference oracle — equality asserted below — and its wall
        # clock is recorded so the overlap win is itself a published,
        # reproducible number. Two recorded runs per contender,
        # interleaved: the judge's bar is a ratio STABLE across runs,
        # not one lucky session (VERDICT r3 item 1).
        runs_s, runs_n = [], []
        p_s, p_n = {}, {}
        xfer_stream = None
        res_s = None
        for _ in range(2):
            ps = {}
            xb = _xfer_counters()
            t0 = time.perf_counter()
            res_s = run_stream(blobs_l, ps)
            runs_s.append(round(time.perf_counter() - t0, 2))
            if not p_s or runs_s[-1] <= min(runs_s[:-1]):
                p_s = ps
                xfer_stream = _xfer_diff(xb, _xfer_counters())
            pn = {}
            t0 = time.perf_counter()
            cache_ln, _ = run_numpy(blobs_l, pn)
            runs_n.append(round(time.perf_counter() - t0, 2))
            if not p_n or runs_n[-1] <= min(runs_n[:-1]):
                p_n = pn
        # one-shot oracle: min-of-2 like the streamed side, so the
        # published overlap win never divides a single noisy run
        runs_one = []
        p_d = {}
        xfer_oneshot = None
        for _ in range(2):
            pd = {}
            xb = _xfer_counters()
            t0 = time.perf_counter()
            cache_l, snap_l, *_ = run_device(blobs_l, pd)
            runs_one.append(round(time.perf_counter() - t0, 2))
            if not p_d or runs_one[-1] <= min(runs_one[:-1]):
                p_d = pd
                xfer_oneshot = _xfer_diff(xb, _xfer_counters())
        t_oneshot = min(runs_one)
        t_dev_l, t_np_l = min(runs_s), min(runs_n)
        # the streamed path must be BIT-IDENTICAL to the one-shot
        # oracle (and both to the numpy contender's shared assembly)
        assert cache_l == cache_ln
        assert res_s.cache == cache_l, "streamed cache diverges"
        assert res_s.snapshot == snap_l, "streamed snapshot diverges"
        scale_result = {
            "ops": R * scale * K,
            "device_s": t_dev_l,           # streaming executor wall
            "numpy_s": t_np_l,
            "vs_baseline": round(t_np_l / t_dev_l, 2),
            "runs_device_s": runs_s,
            "runs_numpy_s": runs_n,
            "vs_baseline_per_run": [
                round(n / d, 2) for n, d in zip(runs_n, runs_s)
            ],
            "phases_device_s": p_s,        # incl. overlap accounting
            "phases_numpy_s": p_n,
            "oneshot_device_s": t_oneshot,
            "oneshot_runs_s": runs_one,
            "oneshot_phases_s": p_d,
            "stream_vs_oneshot": round(t_oneshot / t_dev_l, 2),
            "overlap_efficiency": p_s.get("overlap_efficiency"),
            "wall_vs_phases": p_s.get("wall_vs_phases"),
            # bytes-on-link per leg (the transfer-diet evidence; best
            # run's xfer.* counter growth)
            "xfer_stream": xfer_stream,
            "xfer_oneshot": xfer_oneshot,
        }
        # the SERIAL pipeline's structural ceiling, kept for the
        # r05-comparable record: with every phase serialized,
        # decode/columns/materialize/compact bound the ratio no
        # matter how fast the merge is. The streaming executor exists
        # to break exactly this bound — its wall vs busy-sum above is
        # the measured overlap.
        shared_d = sum(
            p_d.get(k, 0.0)
            for k in ("decode", "columns", "materialize", "compact")
        )
        span_n = p_n.get("merge", 0.0) + p_n.get("gather", 0.0)
        span_d = (
            p_d.get("pack", 0.0) + p_d.get("converge", 0.0)
            + p_d.get("gather", 0.0)
        )
        scale_result["merge_span_ratio"] = round(span_n / span_d, 2)
        scale_result["amdahl_ceiling"] = round(t_np_l / shared_d, 2)
        log(f"scale e2e: stream {runs_s} (one-shot {t_oneshot}s -> "
            f"x{scale_result['stream_vs_oneshot']} from overlap, "
            f"efficiency {p_s.get('overlap_efficiency')}, wall/phases "
            f"{p_s.get('wall_vs_phases')}) vs numpy {runs_n} "
            f"-> {scale_result['vs_baseline']}x "
            f"(per-run {scale_result['vs_baseline_per_run']}; "
            f"merge-span {scale_result['merge_span_ratio']}x; "
            f"serial shared-stage ceiling "
            f"{scale_result['amdahl_ceiling']}x)")
        log(f"  stream phases {p_s}")
        log(f"  one-shot phases {p_d}")
        log(f"  numpy phases {p_n}")

        # ---- steady-state rounds on the big doc (BENCH_ROUNDS=0 off)
        # The product's long-lived shape: a replica holding the doc in
        # HBM consumes small update batches forever. IncrementalReplay
        # re-converges only the touched segments per round; the cold
        # path re-stages the whole union. Per-round cost must stay
        # FLAT in doc size — that is the resident-state claim.
        if os.environ.get("BENCH_ROUNDS", "1") != "0":
            from crdt_tpu.models.incremental import IncrementalReplay
            from crdt_tpu.ops.device import bucket_pow2 as _b2

            # crossover table: the same steady-state round through the
            # exact HOST path (against the resident columns) and the
            # forced DEVICE path (one upload + one dispatch + one
            # fetch), per delta size, plus the scalar engine reference.
            # The product's auto rule (device_min_rows) picks per
            # round; this table IS the measured basis for its default.
            K_d = 50
            sizes = sorted(int(s) for s in os.environ.get(
                "BENCH_ROUND_SIZES", "250,1000,4000,16000,64000"
            ).split(","))
            # six deltas per size: warm, 2x host-timed, backlog
            # flush, 2x device-timed
            total_delta = 6 * sum(sizes)
            cap = _b2(R * scale * K + 2 * total_delta)
            inc = IncrementalReplay(capacity=cap)
            t0 = time.perf_counter()
            inc.apply(blobs_l)
            t_ingest = time.perf_counter() - t0
            # second fresh ingest, same shapes: the run-to-run delta
            # isolates one-off compile/cache cost from steady ingest
            # (VERDICT r4 item 5 — the r3->r4 ingest doubling was the
            # new 64k rounds legs growing the capacity bucket 2M->4M,
            # whose giant-bucket kernels compile fresh on a cold
            # cache; warm runs do not pay it)
            inc2 = IncrementalReplay(capacity=cap)
            t0 = time.perf_counter()
            inc2.apply(blobs_l)
            t_ingest2 = time.perf_counter() - t0
            del inc2
            ingest_runs = [round(t_ingest, 2), round(t_ingest2, 2)]
            all_blobs = list(blobs_l)
            table = {}
            crossover = None
            cbase = R * scale + 1000
            default_min = inc.device_min_rows
            from crdt_tpu.codec import v1 as _v1r

            for d_ops in sizes:
                R_d = max(1, d_ops // K_d)
                mk = lambda i: build_trace(  # noqa: E731
                    R_d, K_d, seed=500 + cbase + i,
                    client_base=cbase + i * R_d, map_frac=1.0)
                # six deltas per size: device warm, 2x host timed,
                # backlog flush, 2x device timed. Each timed leg takes
                # the MIN of two rounds: deltas keep growing the
                # touched segments, so a size-bucket boundary (and its
                # one-off XLA compile) can land inside any single
                # round — the min keeps compiles out of the number
                ds = [mk(i) for i in range(6)]
                cbase += 6 * R_d
                for d in ds:
                    all_blobs += d
                inc.device_min_rows = 0
                inc.apply(ds[0])               # warm
                inc.device_min_rows = 1 << 62  # force host
                t_host = float("inf")
                for d in ds[1:3]:
                    t0 = time.perf_counter()
                    inc.apply(d)
                    t_host = min(t_host, time.perf_counter() - t0)
                inc.device_min_rows = 0        # force device
                inc.apply(ds[3])               # flush host backlog
                t_dev_r = float("inf")
                xb = _xfer_counters()
                for d in ds[4:6]:
                    t0 = time.perf_counter()
                    inc.apply(d)
                    t_dev_r = min(t_dev_r, time.perf_counter() - t0)
                # per-round bytes-on-link: steady-state rounds must
                # ship ~delta-sized uploads against the donated
                # resident matrix, never the full doc
                xd = _xfer_diff(xb, _xfer_counters())
                h2d_round = xd.get("h2d_bytes", 0) // 2
                inc.device_min_rows = default_min  # restore auto rule
                scalar_s = None
                if not skip_oracle:
                    rr_d = []
                    for blob in ds[1]:
                        rr, _dd = _v1r.decode_update(blob)
                        rr_d.extend(rr)
                    t0 = time.perf_counter()
                    eng.apply_records(rr_d)
                    scalar_s = round(time.perf_counter() - t0, 3)
                table[str(R_d * K_d)] = {
                    "host_round_s": round(t_host, 3),
                    "device_round_s": round(t_dev_r, 3),
                    "scalar_round_s": scalar_s,
                    "device_round_h2d_bytes": h2d_round,
                }
                if crossover is None and t_dev_r < t_host:
                    crossover = R_d * K_d
                log(f"  round {R_d * K_d:>6} ops: host {t_host:.3f}s  "
                    f"device {t_dev_r:.3f}s"
                    + (f"  scalar {scalar_s:.3f}s" if scalar_s else ""))

            # exactness net across every round + mode, and the cold
            # reference the steady state is measured against
            from crdt_tpu.models import replay_trace as _rt

            cold_runs = []
            for _ in range(2):
                t0 = time.perf_counter()
                res_full = _rt(all_blobs)
                cold_runs.append(round(time.perf_counter() - t0, 2))
            t_cold_round = min(cold_runs)
            assert inc.cache == res_full.cache, \
                "incremental diverges from cold replay"
            ref = table.get("1000") or table[next(iter(table))]
            med = min(ref["host_round_s"], ref["device_round_s"])
            rounds_result = {
                "doc_ops": R * scale * K,
                "per_delta": table,
                "crossover_delta_ops": crossover,
                "incremental_round_s": med,
                "cold_replay_round_s": t_cold_round,
                "cold_replay_runs_s": cold_runs,
                "vs_cold_replay": round(t_cold_round / max(med, 1e-9), 1),
                "ingest_s": min(ingest_runs),
                "ingest_runs_s": ingest_runs,
                "ingest_note": (
                    "run1-run2 delta = one-off compile/cache cost; the "
                    "r3->r4 ingest doubling was the 64k rounds legs "
                    "growing the capacity bucket 2M->4M (fresh "
                    "giant-bucket compiles on a cold cache), not the "
                    "eager-staging change"
                ),
                # the product default is measured-per-session, not a
                # static number: this is the probe + threshold the auto
                # rule (device_min_rows=None) uses in THIS session
                # (VERDICT r3 item 2)
                "auto_calibration": IncrementalReplay.calibration_info(),
            }
            scale_result["rounds"] = rounds_result
            xmsg = (
                f"host/device crossover at {crossover} delta ops"
                if crossover else
                "host wins at every measured size"
            )
            log(f"steady-state rounds on the {R * scale * K}-op doc: "
                f"best small round {med:.3f}s vs cold replay "
                f"{t_cold_round:.2f}s; {xmsg}")

    except AssertionError:
        raise
    except Exception as exc:
        log(f"scale/rounds run failed: {exc!r}")
        scale_result = scale_result or {}
        scale_result["error"] = repr(exc)

    out = {
        "metric": "e2e_trace_replay_lww_yata",
        "value": round(total / t_dev),
        "unit": "ops/s",
        "vs_baseline": round(t_np / t_dev, 2),
        "vs_python_oracle": oracle_x,
        "kernel_dispatch_ops_per_s": kernel_ops_s,
        "kernel_sweep_ms": {str(n): round(sweep[n] * 1e3, 1) for n in ns},
        "kernel_sweep_net_ms": {
            str(n): round(max(sweep[n] * 1e3 - null_floor_ms, 0.0), 1)
            for n in ns
        },
        "kernel_ablation": ablation,
        "dispatch_floor_ms": round(null_floor_ms, 1),
        "phases_device_s": best_phases_dev,
        "phases_numpy_s": best_phases_np,
        # headline bytes-on-link (best device run's xfer.* counter
        # growth: h2d_bytes/d2h_bytes/h2d_bytes_saved; the transfer
        # diet's regression-gated number — tools/metrics_diff.py)
        "xfer": xfer_headline,
        "platform": platform,
        "platform_costs_ms": costs,
        "lazy_exec_probe_ms": lazy_probe,
        "note": (
            "vs_baseline compares against a tuned numpy CPU merge "
            "sharing the same pipeline; through this tunnelled "
            "single-chip platform the device path's e2e floor is "
            "~0.2-0.3s of fixed transfer/dispatch latency (see "
            "platform_costs_ms), which dominates at 100k ops. "
            "kernel_sweep_net_ms (sweep minus the same-methodology "
            "null-dispatch floor) is the device COMPUTE: at 100k it "
            "is at/under phases_numpy_s.merge — see ROOFLINE.md for "
            "the floor derivation. vs_python_oracle is the "
            "BASELINE.md scalar-loop baseline. scale_run rides the "
            "same tunnel (its 37MB staging upload alone costs "
            "0.6-1.2s here); rounds.per_delta is the measured "
            "host/device crossover table for the steady state, where "
            "sub-threshold rounds never touch the link at all."
        ),
    }
    if conflict_result:
        out["conflict_run"] = conflict_result
    if text_result:
        out["text_run"] = text_result
    if swarm_result:
        out["swarm_run"] = swarm_result
    if fleet_result:
        out["fleet_run"] = fleet_result
    if scale_result:
        out["scale_run"] = scale_result
    if os.environ.get("BENCH_OVERLOAD", "1") != "0":
        # robustness evidence: seeded 4x-budget flood, bounded peak,
        # shed counts, post-heal convergence (regression-gated)
        try:
            out["overload"] = overload_leg()
        except Exception as exc:
            out["overload"] = {"error": repr(exc)}
    if bench_tracer is not None:
        # the full observability report (shared Tracer.report schema):
        # per-span p50/p90/p99/max histograms + counters + gauges —
        # committed phase evidence, not session-log folklore
        out["tracer"] = bench_tracer.report()
    emit_result(out)


if __name__ == "__main__":
    import sys as _sys_main

    if len(_sys_main.argv) > 1 and _sys_main.argv[1] == "--fleet-mesh-child":
        fleet_mesh_child(_sys_main.argv[2:])
    elif (
        len(_sys_main.argv) > 1
        and _sys_main.argv[1] == "--fleet-trace-child"
    ):
        _sys_main.exit(fleet_trace_child(_sys_main.argv[2:]))
    elif "--fleet-trace" in _sys_main.argv[1:]:
        _sys_main.exit(fleet_trace(_sys_main.argv[2:]))
    elif (
        len(_sys_main.argv) > 1
        and _sys_main.argv[1] == "--multichip-child"
    ):
        multichip_child(_sys_main.argv[2:])
    elif "--multichip" in _sys_main.argv[1:]:
        _sys_main.exit(multichip(
            [a for a in _sys_main.argv[2:] if not a.startswith("-")]
        ))
    elif "--multitenant" in _sys_main.argv[1:]:
        _sys_main.exit(multitenant())
    elif "--coldstart" in _sys_main.argv[1:]:
        _sys_main.exit(coldstart())
    elif "--autopilot" in _sys_main.argv[1:]:
        _sys_main.exit(autopilot())
    elif "--conflict" in _sys_main.argv[1:]:
        _sys_main.exit(conflict())
    elif (
        len(_sys_main.argv) > 1
        and _sys_main.argv[1] == "--rebalance-child"
    ):
        _sys_main.exit(rebalance_child(_sys_main.argv[2:]))
    elif "--rebalance" in _sys_main.argv[1:]:
        _sys_main.exit(rebalance(_sys_main.argv[2:]))
    elif (
        "--smoke" in _sys_main.argv[1:]
        or os.environ.get("BENCH_SMOKE") == "1"
    ):
        smoke()
    else:
        main()
