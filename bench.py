#!/usr/bin/env python
"""North-star benchmark: replica fan-in convergence, device vs scalar.

Two workloads, the reference's two merge hot paths (crdt.js:294):

1. Map LWW — R replicas concurrently write K map ops each (the
   1k-replica fan-in config), 5% tombstones; device path is the
   batched ``converge_maps`` kernel (segmented argmax + delete masks).
2. Sequence YATA — R replicas concurrently append K items to shared
   lists (own-chain origins, the concurrent-append shape); device
   path is the ``tree_order_ranks`` kernel (lexsort + pointer
   doubling + Wyllie ranking).

Baseline for both is the stock-Yjs-semantics scalar integrate loop
(crdt_tpu.core.engine — the faithful port of the reference's
``Y.applyUpdate``), and both timed device outputs are checked against
that oracle (checks run AFTER the timed loops: on this platform one
large device->host transfer permanently degrades later dispatches,
so materializing anything before timing would corrupt the numbers).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
where value is combined device convergence throughput over both
workloads (total ops / total device time) and vs_baseline is the
speedup over the scalar loop on the identical op sets.

Env knobs: BENCH_REPLICAS (default 1000), BENCH_OPS (ops per replica
per workload, default 100 — defaults match the north-star "1k
replicas, 100k ops" fan-in config), BENCH_ITERS (timed reps, 5).
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_workload(R: int, K: int, seed: int = 0):
    """Concurrent map-set records from R replicas + a delete set."""
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    rng = np.random.default_rng(seed)
    num_maps = 8
    keys_per_map = max(64, (R * K) // 64)
    maps = rng.integers(0, num_maps, (R, K))
    keys = rng.integers(0, keys_per_map, (R, K))
    records = []
    for r in range(R):
        client = r + 1
        for k in range(K):
            records.append(
                ItemRecord(
                    client=client,
                    clock=k,
                    parent_root=f"m{maps[r, k]}",
                    key=f"k{keys[r, k]}",
                    content=int(r * K + k),
                )
            )
    ds = DeleteSet()
    n_del = (R * K) // 20  # 5% tombstones
    for i in rng.choice(R * K, size=n_del, replace=False):
        ds.add(int(i // K) + 1, int(i % K))
    return records, ds


def build_seq_workload(R: int, K: int, seed: int = 1, num_lists: int = 8):
    """Concurrent appends: each replica chains K items onto shared
    lists, each item's origin = that replica's previous item in the
    list (what Yjs produces when isolated replicas append locally and
    then sync). Returns (records, seg, parent_idx, key1, key2) — the
    columnar form ``tree_order_ranks`` consumes."""
    from crdt_tpu.core.records import ItemRecord

    rng = np.random.default_rng(seed)
    lists = rng.integers(0, num_lists, (R, K))
    records = []
    n = R * K
    seg = np.empty(n, np.int32)
    parent_idx = np.full(n, -1, np.int32)
    key1 = np.empty(n, np.int64)
    key2 = np.empty(n, np.int64)
    last_row: dict = {}
    row = 0
    for r in range(R):
        client = r + 1
        for k in range(K):
            lst = int(lists[r, k])
            prev = last_row.get((r, lst))
            records.append(
                ItemRecord(
                    client=client,
                    clock=k,
                    parent_root=f"l{lst}",
                    origin=records[prev].id if prev is not None else None,
                    content=row,
                )
            )
            seg[row] = lst
            parent_idx[row] = -1 if prev is None else prev
            key1[row] = client
            key2[row] = k
            last_row[(r, lst)] = row
            row += 1
    return records, seg, parent_idx, key1, key2


def main():
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from crdt_tpu.core.engine import Engine
    from crdt_tpu.ops import deleteset as ds_ops
    from crdt_tpu.ops.merge import Interner, converge_maps, records_to_columns

    R = int(os.environ.get("BENCH_REPLICAS", 1000))
    K = int(os.environ.get("BENCH_OPS", 100))
    iters = int(os.environ.get("BENCH_ITERS", 5))
    total = R * K
    log(f"workload: {R} replicas x {K} ops = {total} ops on {jax.devices()[0].platform}")

    records, ds = build_workload(R, K)

    # ---- scalar baseline: the reference's one-at-a-time merge loop ----
    eng = Engine(0)
    t0 = time.perf_counter()
    eng.apply_records(records, ds)
    t_scalar = time.perf_counter() - t0
    oracle = eng.map_winner_table()
    log(f"scalar integrate: {t_scalar:.3f}s ({total / t_scalar:,.0f} ops/s)")

    # ---- device path: one batched convergence dispatch ---------------
    interner = Interner()
    pad = 1 << max(9, (total - 1).bit_length())
    cols = records_to_columns(records, interner, pad=pad)
    d_client, d_start, d_end = ds_ops.ranges_to_device(ds)
    dpad = 1 << max(6, (len(d_client) - 1).bit_length())
    d_client = np.asarray(list(d_client) + [-1] * (dpad - len(d_client)), np.int32)
    d_start = np.asarray(list(d_start) + [-1] * (dpad - len(d_start)), np.int64)
    d_end = np.asarray(list(d_end) + [-1] * (dpad - len(d_end)), np.int64)

    args = (
        jnp.asarray(cols["client"]),
        jnp.asarray(cols["clock"]),
        jnp.asarray(cols["parent_is_root"]),
        jnp.asarray(cols["parent_a"]),
        jnp.asarray(cols["parent_b"]),
        jnp.asarray(cols["key_id"]),
        jnp.asarray(cols["origin_client"]),
        jnp.asarray(cols["origin_clock"]),
        jnp.asarray(cols["valid"]),
        jnp.asarray(d_client),
        jnp.asarray(d_start),
        jnp.asarray(d_end),
    )
    fn = partial(converge_maps, num_segments=pad)

    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    log(f"compile+first run: {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    t_device = (time.perf_counter() - t0) / iters
    log(f"device converge: {t_device * 1e3:.2f}ms ({total / t_device:,.0f} ops/s)")

    # =========== workload 2: sequence YATA ordering ====================
    # IMPORTANT: all device TIMING happens before any device->host
    # transfer — on this platform one large D2H permanently degrades
    # every later dispatch (~0.03ms -> 5-70ms), which would bill
    # transport stalls to the kernels. Correctness checks (which need
    # D2H) run at the end.
    from crdt_tpu.ops.yata import order_sequences, tree_order_ranks

    seq_records, seg_col, parent_col, k1_col, k2_col = build_seq_workload(R, K)
    s_total = len(seq_records)

    eng2 = Engine(0)
    t0 = time.perf_counter()
    eng2.apply_records(seq_records)
    t_scalar_seq = time.perf_counter() - t0
    seq_oracle = eng2.seq_order_table()
    log(f"scalar seq integrate: {t_scalar_seq:.3f}s "
        f"({s_total / t_scalar_seq:,.0f} ops/s)")

    # timed: the ordering kernel on the prepared columns
    spad = 1 << max(9, (s_total - 1).bit_length())
    num_seq = 1 << max(3, int(seg_col.max()).bit_length())
    from crdt_tpu.ops.merge import _pad_to

    sargs = (
        jnp.asarray(_pad_to(seg_col, spad, -1)),
        jnp.asarray(_pad_to(parent_col, spad, -1)),
        jnp.asarray(_pad_to(k1_col, spad, 0)),
        jnp.asarray(_pad_to(k2_col, spad, 0)),
        jnp.asarray(np.arange(spad) < s_total),
    )
    sfn = partial(tree_order_ranks, num_segments=num_seq)
    t0 = time.perf_counter()
    sout = sfn(*sargs)
    jax.block_until_ready(sout)
    log(f"seq compile+first run: {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    for _ in range(iters):
        sout = sfn(*sargs)
    jax.block_until_ready(sout)
    t_device_seq = (time.perf_counter() - t0) / iters
    log(f"device seq order: {t_device_seq * 1e3:.2f}ms "
        f"({s_total / t_device_seq:,.0f} ops/s)")

    # ---- correctness: device outputs == scalar oracles (D2H below) ---
    order, seg, winners, visible, _, _ = (np.asarray(x) for x in out)
    got = {}
    for w, vis in zip(winners, visible):
        if w < 0:
            continue
        rec = records[order[w]] if order[w] < total else None
        if rec is None:
            continue
        got[(("root", rec.parent_root), rec.key)] = (rec.id, bool(vis))
    mismatch = sum(1 for k, v in oracle.items() if got.get(k) != v)
    assert mismatch == 0, f"{mismatch}/{len(oracle)} winners diverge from oracle"
    log(f"correctness: {len(oracle)} map keys, 0 divergent")

    # (a) the TIMED dispatch's own output: ranks over the hand-built
    # columns must reproduce the oracle's document order per list
    rank = np.asarray(sout[0])[:s_total]
    got_timed = {}
    for row in range(s_total):
        got_timed.setdefault(int(seg_col[row]), []).append(
            (int(rank[row]), seq_records[row].id)
        )
    for lst, pairs in got_timed.items():
        pairs.sort()
        want_ids = seq_oracle[("root", f"l{lst}")]
        assert [i for _, i in pairs] == want_ids, f"timed order diverges (l{lst})"
    # (b) the full device-path wrapper (its own column prep + host
    # attachment handling) against the same oracle
    got_seq = order_sequences(seq_records)
    assert got_seq == seq_oracle, "sequence order diverges from oracle"
    log(f"correctness: {len(seq_oracle)} sequences, 0 divergent "
        "(timed kernel + wrapper)")

    # =========== combined headline ====================================
    all_ops = total + s_total
    t_dev_all = t_device + t_device_seq
    t_scalar_all = t_scalar + t_scalar_seq
    print(
        json.dumps(
            {
                "metric": "converge_throughput_lww_yata",
                "value": round(all_ops / t_dev_all),
                "unit": "ops/s",
                "vs_baseline": round(t_scalar_all / t_dev_all, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
