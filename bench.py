#!/usr/bin/env python
"""North-star benchmark: 1k-replica fan-in trace replay, end to end.

BASELINE.json config #5 — "1k-replica fan-in: 100k-op trace replay +
snapshot compaction" — measured HONESTLY (VERDICT r1 item #3):

- The timed region is ingest-to-visible-state, the same span as the
  reference's hot loop (crdt.js:294): v1 wire decode -> columnar
  staging -> merge -> winner gather -> cache materialization ->
  compacted snapshot encode. Nothing is pre-staged outside the timer.
- The headline ``vs_baseline`` compares the DEVICE path against an
  OPTIMIZED SCALAR baseline: the same end-to-end pipeline with the
  merge done by vectorized numpy ports of the kernels on the host CPU
  (a fair stand-in for a tuned native CPU implementation). The pure
  Python integrate loop — the faithful Yjs-semantics oracle — is
  reported separately, NOT used as the headline denominator
  (r1 printed 583,098x against it; that number was meaningless).
- The raw kernel timer is validated three ways: an N-scaling sweep
  (quarter/half/full union), per-phase wall-clock breakdowns, and an
  XProf device trace written to BENCH_TRACE_DIR (default
  /tmp/crdt_tpu_bench_trace).
- The r1 methodology claim that one large D2H permanently degrades
  later dispatches on this platform is DEMONSTRATED, not asserted:
  the kernel is re-timed after the correctness materialization and
  the before/after ratio is reported.

Prints ONE JSON line:
  {"metric": "e2e_trace_replay_lww_yata", "value": <ops/s end-to-end
   device path>, "unit": "ops/s", "vs_baseline": <device e2e /
   numpy-scalar e2e>, ...extra keys: kernel-only throughput, python
   oracle ratio, phase breakdown}

Env knobs: BENCH_REPLICAS (1000), BENCH_OPS (per replica, 100),
BENCH_ITERS (5), BENCH_TRACE_DIR, BENCH_SKIP_ORACLE=1 (skip the slow
pure-Python baseline).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# trace generation (not timed: this manufactures the wire input)
# ---------------------------------------------------------------------------


def build_trace(R: int, K: int, seed: int = 0):
    """Per-replica v1 update blobs: 60% map sets over 8 maps, 40%
    concurrent list appends over 8 lists (own-chain origins), 5% of
    each replica's ops tombstoned in its final blob's delete set."""
    from crdt_tpu.codec import v1
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    rng = np.random.default_rng(seed)
    num_maps, num_lists = 8, 8
    keys_per_map = max(64, (R * K) // 64)
    n_map = (K * 6) // 10
    blobs = []
    for r in range(R):
        client = r + 1
        recs = []
        maps = rng.integers(0, num_maps, n_map)
        keys = rng.integers(0, keys_per_map, n_map)
        last_set: dict = {}
        for k in range(n_map):
            mk = (int(maps[k]), int(keys[k]))
            prev = last_set.get(mk)
            recs.append(ItemRecord(
                client=client, clock=k, parent_root=f"m{maps[k]}",
                key=f"k{keys[k]}", content=int(r * K + k),
                # chained like real Yjs map sets: origin = this
                # replica's previous entry for the key
                origin=(client, prev) if prev is not None else None,
            ))
            last_set[mk] = k
        lists = rng.integers(0, num_lists, K - n_map)
        last: dict = {}
        for j, k in enumerate(range(n_map, K)):
            lst = int(lists[j])
            prev = last.get(lst)
            recs.append(ItemRecord(
                client=client, clock=k, parent_root=f"l{lst}",
                origin=(client, prev) if prev is not None else None,
                content=int(r * K + k),
            ))
            last[lst] = k
        ds = DeleteSet()
        for k in rng.choice(K, size=max(1, K // 20), replace=False):
            ds.add(client, int(k))
        blobs.append(v1.encode_update(recs, ds))
    return blobs


# ---------------------------------------------------------------------------
# shared pipeline stages (identical host work for both contenders)
# ---------------------------------------------------------------------------


# The pipeline stages ARE the product's replay API: bench times
# crdt_tpu.models.replay, not a private copy (see that module's doc).
from crdt_tpu.models import replay as rp

decode_stage = rp.decode
column_stage = rp.stage
materialize_stage = rp.materialize
compact_stage = rp.compact
visible_mask = rp.visible_mask


# ---------------------------------------------------------------------------
# optimized scalar baseline: numpy ports of both kernels (host CPU)
# ---------------------------------------------------------------------------


def numpy_converge(cols):
    """Vectorized host merge, exact for this workload (per-replica
    chained map sets -> segmented (client, clock) argmax; append-only
    lists -> DFS ranks via the same pointer-doubling scheme as the
    device kernel). Checked against the Python oracle below."""
    client = cols["client"]
    clock = cols["clock"]
    pa = cols["parent_a"]
    kid = cols["key_id"]
    oc = cols["origin_client"]
    ok = cols["origin_clock"]
    n = len(client)

    # --- map winners -----------------------------------------------
    # with per-replica chained sets (origin = own previous entry), the
    # Yjs tail for a key is the largest client's latest set: group by
    # (parent, key), take max (client, clock)
    is_map = kid >= 0
    order = np.lexsort((clock, client, kid, pa))
    order = order[is_map[order]]
    pa_s, kid_s = pa[order], kid[order]
    last = np.r_[pa_s[1:] != pa_s[:-1], True] | np.r_[
        kid_s[1:] != kid_s[:-1], True
    ]
    win_rows = order[last]

    # --- sequence DFS ranks (numpy pointer doubling) -------------------
    is_seq = ~is_map
    pack = (client.astype(np.int64) << 40) | clock
    sorder = np.argsort(pack)
    opack = np.where(oc >= 0, (oc.astype(np.int64) << 40) | ok, -1)
    pos = np.searchsorted(pack[sorder], opack)
    posc = np.clip(pos, 0, n - 1)
    found = (opack >= 0) & (pack[sorder[posc]] == opack)
    origin_idx = np.where(found, sorder[posc], -1)

    seq_roots = (
        np.unique(pa[is_seq]) if is_seq.any() else np.empty(0, np.int64)
    )
    S = len(seq_roots)
    seg = np.where(
        is_seq,
        np.searchsorted(
            seq_roots, np.where(is_seq, pa, seq_roots[0] if S else 0)
        ),
        -1,
    )
    m = n + S
    parent = np.where(is_seq & (origin_idx >= 0), origin_idx,
                      n + np.maximum(seg, 0))
    parent = np.where(is_seq, parent, m)

    skey = np.lexsort((-clock, client, parent))
    p_s = parent[skey]
    same = np.r_[p_s[1:] == p_s[:-1], False]
    nxt = np.where(same, np.roll(skey, -1), -1)
    next_sib = np.full(n, -1, np.int64)
    next_sib[skey] = nxt
    first = np.r_[True, p_s[1:] != p_s[:-1]] & is_seq[skey]
    first_child = np.full(m + 1, -1, np.int64)
    first_child[np.where(first, p_s, m)] = np.where(first, skey, -1)
    first_child = first_child[:m]

    idx_m = np.arange(m)
    pad_next = np.r_[next_sib, np.full(S, -1)]
    pad_parent = np.r_[parent, np.zeros(S, np.int64)]
    pad_isseq = np.r_[is_seq, np.zeros(S, bool)]
    is_last = (idx_m < n) & (pad_next == -1) & pad_isseq
    g = np.where(is_last, pad_parent, idx_m)
    for _ in range(max(1, (max(m, 2) - 1).bit_length() + 1)):
        g = g[g]
    y_next = pad_next[np.clip(g, 0, m - 1)]
    succ = np.where((g >= n) | (y_next < 0), idx_m, y_next)
    succ = np.where(first_child >= 0, np.clip(first_child, 0, m - 1), succ)
    succ = np.where(pad_isseq | (idx_m >= n), succ, idx_m)
    dist = np.where(succ != idx_m, 1, 0)
    for _ in range(max(1, (max(m, 2) - 1).bit_length() + 1)):
        dist = dist + dist[succ]
        succ = succ[succ]
    root_dist = dist[n + np.maximum(seg, 0)]
    rank = np.where(is_seq, root_dist - dist[:n] - 1, -1)
    return win_rows, seg, rank


def seq_orders_from_ranks(seg, rank, root_of_seg):
    out = {}
    for i in np.flatnonzero(seg >= 0):
        out.setdefault(root_of_seg[int(seg[i])], []).append(
            (int(rank[i]), int(i))
        )
    return {
        root: [r for _, r in sorted(pairs)] for root, pairs in out.items()
    }


# ---------------------------------------------------------------------------


def main():
    import jax

    jax.config.update("jax_enable_x64", True)
    # persistent compile cache: the untimed warmup costs real compile
    # only on a cold machine
    jax.config.update("jax_compilation_cache_dir", "/tmp/crdt_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp

    from crdt_tpu.ops.resident import ResidentColumns

    R = int(os.environ.get("BENCH_REPLICAS", 1000))
    K = int(os.environ.get("BENCH_OPS", 100))
    iters = int(os.environ.get("BENCH_ITERS", 5))
    total = R * K
    platform = jax.devices()[0].platform
    log(f"workload: {R} replicas x {K} ops = {total} ops, platform={platform}")

    t0 = time.perf_counter()
    blobs = build_trace(R, K)
    log(f"trace: {len(blobs)} blobs, {sum(map(len, blobs)):,} bytes "
        f"(built in {time.perf_counter() - t0:.1f}s, untimed)")

    phases_dev: dict = {}
    phases_np: dict = {}

    def timed(phases, name, fn, *a):
        t = time.perf_counter()
        out = fn(*a)
        phases[name] = round(time.perf_counter() - t, 4)
        return out

    # ================= PRISTINE KERNEL VALIDATION ======================
    # BEFORE any device->host transfer: on this platform the first D2H
    # permanently degrades later dispatches (demonstrated below), so the
    # clean kernel numbers and the N-scaling sweep run first.
    dec_w = decode_stage(blobs)
    cols_w, ds_w = column_stage(dec_w)

    sweep = {}
    for frac in (4, 2, 1):
        nsub = len(cols_w["client"]) // frac
        rcs = ResidentColumns(capacity=max(512, nsub),
                              clients=range(1, R + 1))
        rcs.append({k: v[:nsub] for k, v in cols_w.items()})
        jax.block_until_ready(rcs.converge())  # compile + warm, fully
        t = time.perf_counter()
        for _ in range(iters):
            out = rcs.converge()
        jax.block_until_ready(out)
        sweep[nsub] = (time.perf_counter() - t) / iters
    ns = sorted(sweep)
    log("kernel N-sweep (pristine): " + ", ".join(
        f"{n}: {sweep[n] * 1e3:.2f}ms" for n in ns))
    kernel_ops_s = round(ns[-1] / sweep[ns[-1]])
    log(f"kernel-only (maps+seqs, N={ns[-1]}): "
        f"{sweep[ns[-1]] * 1e3:.2f}ms ({kernel_ops_s:,} ops/s)")

    # XProf device trace around one dispatch (best-effort diagnostics)
    trace_dir = os.environ.get("BENCH_TRACE_DIR", "/tmp/crdt_tpu_bench_trace")
    try:
        from crdt_tpu.utils.trace import jax_profile

        with jax_profile(trace_dir):
            out = rcs.converge()
            jax.block_until_ready(out)
        files = [
            os.path.join(dp, f)
            for dp, _, fs in os.walk(trace_dir) for f in fs
        ]
        log(f"profiler trace: {len(files)} files, "
            f"{sum(os.path.getsize(f) for f in files):,} bytes in {trace_dir}")
    except Exception as exc:
        log(f"profiler trace unavailable: {exc}")

    # ================= DEVICE PATH (end to end) ========================
    def device_merge(cols):
        return rp.converge(cols, clients=range(1, R + 1))

    device_gather = rp.gather

    # warmup pass: compiles every e2e shape bucket AND performs the
    # first device->host transfer (a one-time channel-setup cost on
    # this platform, ~9s, after which transfers run ~0.7s — both are
    # demonstrated by the pristine-vs-steady numbers reported). The
    # timed pass below therefore measures the SUSTAINED state,
    # degraded dispatches included.
    t = time.perf_counter()
    _, w_maps, w_seq = device_merge(cols_w)
    device_gather(dec_w, ds_w, w_maps, w_seq)
    del dec_w, cols_w, ds_w, w_maps, w_seq
    log(f"warmup pass (compile + first D2H): {time.perf_counter() - t:.1f}s "
        "(untimed, one-time; jit cache persists across runs)")

    t_dev0 = time.perf_counter()
    dec = timed(phases_dev, "decode", decode_stage, blobs)
    cols, ds = timed(phases_dev, "columns", column_stage, dec)
    rc, maps_out, seq_out = timed(phases_dev, "merge", device_merge, cols)
    win_rows, win_vis, seq_orders = timed(
        phases_dev, "gather", device_gather, dec, ds, maps_out, seq_out
    )
    cache_dev = timed(phases_dev, "materialize", materialize_stage,
                      dec, ds, win_rows, win_vis, seq_orders)
    snapshot_dev = timed(phases_dev, "compact", compact_stage, dec, ds)
    t_dev = time.perf_counter() - t_dev0
    log(f"device e2e (steady state): {t_dev:.2f}s "
        f"({total / t_dev:,.0f} ops/s) phases={phases_dev}")

    # ================= OPTIMIZED SCALAR BASELINE =======================
    t_np0 = time.perf_counter()
    dec2 = timed(phases_np, "decode", decode_stage, blobs)
    cols2, ds2 = timed(phases_np, "columns", column_stage, dec2)
    np_win, np_seg, np_rank = timed(
        phases_np, "merge", numpy_converge, cols2
    )

    def np_gather():
        spec_of_seg = {}
        for i in np.flatnonzero(np_seg >= 0):
            spec_of_seg.setdefault(int(np_seg[i]),
                                   rp.parent_spec(dec2, int(i)))
        orders = seq_orders_from_ranks(np_seg, np_rank, spec_of_seg)
        vis = visible_mask(dec2, list(np_win), ds2)
        return orders, vis

    np_seq_orders, np_vis = timed(phases_np, "gather", np_gather)
    cache_np = timed(phases_np, "materialize", materialize_stage,
                     dec2, ds2, list(np_win), np_vis, np_seq_orders)
    snapshot_np = timed(phases_np, "compact", compact_stage, dec2, ds2)
    t_np = time.perf_counter() - t_np0
    log(f"numpy-scalar e2e: {t_np:.2f}s ({total / t_np:,.0f} ops/s) "
        f"phases={phases_np}")

    # the two contenders must agree before any ratio is meaningful
    # (the snapshot check is codec determinism only: compaction depends
    # on the decode, not on either merge result)
    assert cache_dev == cache_np, "device and numpy baselines diverge"
    assert snapshot_dev == snapshot_np

    # ================= PYTHON ORACLE (reported, not headline) =========
    oracle_x = None
    if os.environ.get("BENCH_SKIP_ORACLE", "0") != "1":
        from crdt_tpu.core.engine import Engine

        from crdt_tpu.codec import v1 as _v1
        from crdt_tpu.core.ids import DeleteSet as _DS

        t = time.perf_counter()
        eng = Engine(0)
        recs3, ds3 = [], _DS()
        for blob in blobs:
            rr, dd = _v1.decode_update(blob)
            recs3.extend(rr)
            for c, k, length in dd.iter_all():
                ds3.add(c, k, length)
        eng.apply_records(recs3, ds3)
        t_oracle = time.perf_counter() - t
        oracle_x = round(t_oracle / t_dev, 1)
        log(f"python oracle e2e: {t_oracle:.2f}s "
            f"({total / t_oracle:,.0f} ops/s) -> device is {oracle_x}x")
        # correctness: winners match the faithful engine
        wt = {
            (p[1], k): (rec_id, vis)
            for (p, k), (rec_id, vis) in eng.map_winner_table().items()
            if p[0] == "root"
        }
        roots_d, keys_d = dec["roots"], dec["keys"]
        got = {}
        for row, vis in zip(win_rows, win_vis):
            got[(roots_d[dec["parent_root"][row]],
                 keys_d[dec["key_id"][row]])] = (
                (int(dec["client"][row]), int(dec["clock"][row])), vis)
        mismatch = sum(1 for kk, vv in wt.items() if got.get(kk) != vv)
        assert mismatch == 0, f"{mismatch}/{len(wt)} winners diverge"
        want_orders = eng.seq_order_table()  # keyed by parent spec
        got_orders = {
            spec: [(int(dec["client"][r]), int(dec["clock"][r]))
                   for r in rows]
            for spec, rows in seq_orders.items()
        }
        assert got_orders == want_orders, "sequence order diverges"
        log(f"correctness vs oracle: {len(wt)} map keys, "
            f"{len(want_orders)} sequences, 0 divergent")

    # demonstrate the D2H-degradation methodology note: the same full
    # kernel, re-timed in the post-D2H state, vs the pristine sweep
    t = time.perf_counter()
    for _ in range(iters):
        out = rc.converge()
    jax.block_until_ready(out)
    post_d2h = (time.perf_counter() - t) / iters
    log(f"post-D2H kernel re-time: {post_d2h * 1e3:.2f}ms "
        f"({post_d2h / sweep[ns[-1]]:.1f}x pristine; >1 demonstrates the "
        "platform's D2H dispatch penalty)")

    print(json.dumps({
        "metric": "e2e_trace_replay_lww_yata",
        "value": round(total / t_dev),
        "unit": "ops/s",
        "vs_baseline": round(t_np / t_dev, 2),
        "kernel_ops_per_s": kernel_ops_s,
        "kernel_post_d2h_ops_per_s": round(ns[-1] / post_d2h),
        "kernel_vs_numpy_merge": round(
            phases_np["merge"] / sweep[ns[-1]], 2
        ),
        "vs_python_oracle": oracle_x,
        "phases_device_s": phases_dev,
        "phases_numpy_s": phases_np,
    }))


if __name__ == "__main__":
    main()
