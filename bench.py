#!/usr/bin/env python
"""North-star benchmark: replica fan-in convergence, device vs scalar.

Workload (BASELINE.json north star, scaled by env): R replicas
concurrently write K map ops each (same shape as the 1k-replica fan-in
config); a fraction are deletes. Baseline is the stock-Yjs-semantics
scalar integrate loop (crdt_tpu.core.engine — the faithful port of the
reference's ``Y.applyUpdate`` hot loop, crdt.js:294). Device path is
the batched ``converge_maps`` kernel: the whole union merged in one
dispatch.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
where value is device convergence throughput (ops/s) and vs_baseline
is the speedup over the scalar loop on the identical op set.

Env knobs: BENCH_REPLICAS (default 1000), BENCH_OPS (ops per replica,
default 100 — defaults match the north-star "1k replicas, 100k ops"
fan-in config), BENCH_ITERS (timed kernel reps, default 5).
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_workload(R: int, K: int, seed: int = 0):
    """Concurrent map-set records from R replicas + a delete set."""
    from crdt_tpu.core.ids import DeleteSet
    from crdt_tpu.core.records import ItemRecord

    rng = np.random.default_rng(seed)
    num_maps = 8
    keys_per_map = max(64, (R * K) // 64)
    maps = rng.integers(0, num_maps, (R, K))
    keys = rng.integers(0, keys_per_map, (R, K))
    records = []
    for r in range(R):
        client = r + 1
        for k in range(K):
            records.append(
                ItemRecord(
                    client=client,
                    clock=k,
                    parent_root=f"m{maps[r, k]}",
                    key=f"k{keys[r, k]}",
                    content=int(r * K + k),
                )
            )
    ds = DeleteSet()
    n_del = (R * K) // 20  # 5% tombstones
    for i in rng.choice(R * K, size=n_del, replace=False):
        ds.add(int(i // K) + 1, int(i % K))
    return records, ds


def main():
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from crdt_tpu.core.engine import Engine
    from crdt_tpu.ops import deleteset as ds_ops
    from crdt_tpu.ops.merge import Interner, converge_maps, records_to_columns

    R = int(os.environ.get("BENCH_REPLICAS", 1000))
    K = int(os.environ.get("BENCH_OPS", 100))
    iters = int(os.environ.get("BENCH_ITERS", 5))
    total = R * K
    log(f"workload: {R} replicas x {K} ops = {total} ops on {jax.devices()[0].platform}")

    records, ds = build_workload(R, K)

    # ---- scalar baseline: the reference's one-at-a-time merge loop ----
    eng = Engine(0)
    t0 = time.perf_counter()
    eng.apply_records(records, ds)
    t_scalar = time.perf_counter() - t0
    oracle = eng.map_winner_table()
    log(f"scalar integrate: {t_scalar:.3f}s ({total / t_scalar:,.0f} ops/s)")

    # ---- device path: one batched convergence dispatch ---------------
    interner = Interner()
    pad = 1 << max(9, (total - 1).bit_length())
    cols = records_to_columns(records, interner, pad=pad)
    d_client, d_start, d_end = ds_ops.ranges_to_device(ds)
    dpad = 1 << max(6, (len(d_client) - 1).bit_length())
    d_client = np.asarray(list(d_client) + [-1] * (dpad - len(d_client)), np.int32)
    d_start = np.asarray(list(d_start) + [-1] * (dpad - len(d_start)), np.int64)
    d_end = np.asarray(list(d_end) + [-1] * (dpad - len(d_end)), np.int64)

    args = (
        jnp.asarray(cols["client"]),
        jnp.asarray(cols["clock"]),
        jnp.asarray(cols["parent_is_root"]),
        jnp.asarray(cols["parent_a"]),
        jnp.asarray(cols["parent_b"]),
        jnp.asarray(cols["key_id"]),
        jnp.asarray(cols["origin_client"]),
        jnp.asarray(cols["origin_clock"]),
        jnp.asarray(cols["valid"]),
        jnp.asarray(d_client),
        jnp.asarray(d_start),
        jnp.asarray(d_end),
    )
    fn = partial(converge_maps, num_segments=pad)

    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    log(f"compile+first run: {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    t_device = (time.perf_counter() - t0) / iters
    log(f"device converge: {t_device * 1e3:.2f}ms ({total / t_device:,.0f} ops/s)")

    # ---- correctness: device winners == scalar oracle ----------------
    order, seg, winners, visible, _, _ = (np.asarray(x) for x in out)
    got = {}
    for w, vis in zip(winners, visible):
        if w < 0:
            continue
        rec = records[order[w]] if order[w] < total else None
        if rec is None:
            continue
        got[(("root", rec.parent_root), rec.key)] = (rec.id, bool(vis))
    want = {k: v for k, v in oracle.items()}
    mismatch = sum(1 for k, v in want.items() if got.get(k) != v)
    assert mismatch == 0, f"{mismatch}/{len(want)} winners diverge from oracle"
    log(f"correctness: {len(want)} map keys, 0 divergent")

    print(
        json.dumps(
            {
                "metric": "map_converge_throughput",
                "value": round(total / t_device),
                "unit": "ops/s",
                "vs_baseline": round(t_scalar / t_device, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
